"""Schema-derived hostile-input fuzzing (ISSUE 15 tentpole, part 3).

The wire IR extracted by :mod:`.schema` is not just a lint input — it is
a *generator*: every op a dispatcher handles, every field it parses, and
every type PROTOCOL.md's machine-read rows declare for that field define
the space of frames a hostile or version-skewed peer can send.  This
module turns that space into a deterministic, seeded battery of mutated
frames per handler family:

- **meta mutations** — drop each required field (the server must reject:
  error reply or clean close, never a ``result``), retype fields to the
  wrong msgpack type, oversize string/bytes/int values, hostile float
  values (NaN / inf / negative — the ISSUE-17 sampling knobs), replace
  the whole meta map with a non-map;
- **frame mutations** — truncated payloads (outer length prefix lies
  long), inner header-length lies, non-msgpack headers, tensor specs
  whose declared byte counts disagree with the payload, rid games
  (huge, negative, string-typed, colliding), oversized outer prefixes;
- **handshake mutations** — ``hello`` frames with non-list / oversized
  feature offers;
- **seeded byte flips** — random single-byte corruptions of valid
  frames.

Every case carries an expectation: ``reject`` (the server must NOT
answer with a success ``result`` — the teeth behind the seeded-bug
self-validation in ``tools/lah_fuzz.py --selfcheck``) or ``tolerate``
(any of error reply / result / clean close is fine; only a crash, a
hang, or a sanitizer violation fails).  Cases serialize to JSON so a
found crash pins into ``tests/fuzz_corpus/`` as a regression corpus
replayed by pytest (tests/test_fuzz_replay.py).

Generation is pure: same seed → byte-identical cases (``random.Random``
only, no time, no os.urandom), which is what makes corpus replay and
CI triage deterministic.
"""

from __future__ import annotations

import dataclasses
import json
import struct
from random import Random
from typing import Iterable, Optional

import msgpack

from . import schema as _schema
from .lint import _doc_corpus, _doc_rows_for, _find_docs_dir

_U32 = struct.Struct("<I")

# Families the harness can host live instances of, in barrage order.
FAMILIES = ("expert", "gateway", "averaging", "dht")

# Counters the fuzz harness publishes (docs/OBSERVABILITY.md "Fuzzing"):
# one frame lands in exactly one outcome bucket, so the outcome counters
# sum to lah_fuzz_frames_total.
FUZZ_COUNTERS = (
    "lah_fuzz_frames_total",    # mutated frames driven at live handlers
    "lah_fuzz_rejects_total",   # outcome: error-shaped reply
    "lah_fuzz_results_total",   # outcome: success result reply
    "lah_fuzz_closes_total",    # outcome: server closed the connection
    "lah_fuzz_hangs_total",     # outcome: no reply within the deadline
    "lah_fuzz_crashes_total",   # liveness probe failed after a case
)

# (op, field) pairs whose required-field drop is deliberately answered
# with a benign result rather than an error: cancel of an absent stream
# is an idempotent no-op (``{"cancelled": False}``), not a fault.
SOFT_REJECT = {("gen_cancel", "sid")}

# Ops that mutate durable server state: ``drain`` flips the lifecycle
# with an EMPTY meta (every field is optional), ``replica`` installs an
# expert from any uid string, ``handoff`` opens transfer sessions, and
# ``migrate`` hands a hosted expert off to an arbitrary target then
# retires the source copy.  A socket barrage over these would
# drain/mutate the very instance whose liveness the run asserts, so
# they are excluded from generation and reported as skipped; their
# hostile-meta validation is covered by the in-process corpus replays
# (tests/fuzz_corpus/handoff_meta.json and the lifecycle/drain/migrate
# test batteries).
STATEFUL_OPS = ("drain", "replica", "handoff", "migrate")


@dataclasses.dataclass
class FuzzCase:
    """One mutated frame + its expectation.

    ``frame_hex`` is the COMPLETE byte sequence written to the socket,
    outer length prefix included — mutations are allowed to make the
    prefix lie, so the driver must not re-frame.  ``wait`` is False for
    cases that by construction can never be answered (the outer prefix
    declares more bytes than the case sends): the driver writes, closes,
    and classifies the outcome as ``close`` without burning a recv
    timeout per case.
    """

    family: str
    name: str
    op: str
    mutation: str
    expect: str  # "reject" | "tolerate"
    frame_hex: str
    wait: bool = True

    def frame(self) -> bytes:
        return bytes.fromhex(self.frame_hex)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_json(cls, obj: dict) -> "FuzzCase":
        return cls(**{
            k: obj[k] for k in (
                "family", "name", "op", "mutation", "expect", "frame_hex",
            )
        } | {"wait": bool(obj.get("wait", True))})


# ---------------------------------------------------------------------------
# frame construction — deliberately NOT serialization.pack_frames: the
# whole point is emitting frames pack_frames refuses to build
# ---------------------------------------------------------------------------


def build_frame(
    msg_type,
    meta,
    specs: Optional[list] = None,
    blobs: bytes = b"",
    rid=None,
    header_raw: Optional[bytes] = None,
    hlen_override: Optional[int] = None,
    outer_override: Optional[int] = None,
    truncate_to: Optional[int] = None,
) -> bytes:
    """Assemble ``u32(outer) u32(hlen) header blobs`` with every length
    field independently liable."""
    if header_raw is None:
        hmap = {"t": msg_type, "m": meta, "ts": specs if specs is not None else []}
        if rid is not None:
            hmap["rid"] = rid
        header_raw = msgpack.packb(hmap, use_bin_type=True)
    hlen = len(header_raw) if hlen_override is None else hlen_override
    payload = _U32.pack(hlen & 0xFFFFFFFF) + header_raw + blobs
    outer = len(payload) if outer_override is None else outer_override
    frame = _U32.pack(outer & 0xFFFFFFFF) + payload
    if truncate_to is not None:
        frame = frame[:truncate_to]
    return frame


def _tensor_blob(dtype: str, shape: list, fill: int = 1) -> tuple[list, bytes]:
    """A well-formed tensor spec + matching raw bytes (f32 ones by
    default) — the benign payload mutations start from."""
    import numpy as np

    arr = np.full(shape, fill, dtype=dtype)
    return [arr.dtype.name, list(arr.shape), arr.nbytes], arr.tobytes()


# ---------------------------------------------------------------------------
# field model: handler IR x PROTOCOL.md types
# ---------------------------------------------------------------------------

_TYPE_VALUES = {
    "str": "zz",
    "int": 3,
    "float": 1.0,
    "bytes": b"\x01\x02\x03\x04\x05\x06\x07\x08",
    "list": [],
    "dict": {},
    "bool": True,
}

# a value of a DIFFERENT msgpack type per declared type (retype probes)
_TYPE_SWAPS = {
    "str": 12345,
    "int": "not-an-int",
    "float": b"\x00",
    "bytes": 7,
    "list": "not-a-list",
    "dict": 0,
    "bool": [1, 2],
}


def field_model(paths: Iterable[str]) -> dict:
    """``{family: {op: {field: {"kind", "types"}}}}`` merged from the
    extracted handler IR (which fields, required or optional) and the
    PROTOCOL.md field rows (which types).  This is the generator's view
    of the wire contract — derived, never hand-listed, so a new op or
    field is fuzzed the moment a handler parses it."""
    py_files = list(paths)
    ir = _schema.extract(py_files)
    docs_dir = _find_docs_dir(py_files[0]) if py_files else None
    corpus = _doc_corpus(docs_dir) if docs_dir else {"fields": {}}
    model: dict = {}
    for h in ir.handlers:
        fam = model.setdefault(h.family, {})
        for op in h.ops:
            fields: dict = {}
            doc_rows = _doc_rows_for(corpus, op, h.family) or {}
            for name, use in h.accepted(op).items():
                doc = doc_rows.get(name) or {}
                types = tuple(doc.get("types") or ()) or tuple(use.types)
                # a handler may parse leniently (``.get`` + late
                # validation) while the CONTRACT still requires the
                # field — PROTOCOL.md's kind wins for the drop-probe
                # expectation, the parse-site kind for everything else
                kind = (
                    "req"
                    if use.kind == "req" or doc.get("kind") == "req"
                    else "opt"
                )
                fields[name] = {"kind": kind, "types": types or ("str",)}
            existing = fam.setdefault(op, {})
            for name, spec in fields.items():
                cur = existing.get(name)
                if cur is None:
                    existing[name] = spec
                elif spec["kind"] == "req":
                    cur["kind"] = "req"
    return model


def _baseline_meta(fields: dict, rng: Random) -> dict:
    meta = {}
    for name, spec in fields.items():
        t = spec["types"][0] if spec["types"] else "str"
        meta[name] = _TYPE_VALUES.get(t, "zz")
    return meta


# ---------------------------------------------------------------------------
# case generation
# ---------------------------------------------------------------------------


def _meta_cases(family: str, op: str, fields: dict, rng: Random):
    """Per-op meta-level mutations."""
    base = _baseline_meta(fields, rng)
    specs, blob = _tensor_blob("float32", [2, 4])
    tensors = dict(specs=[specs], blobs=blob)

    def case(name, mutation, expect, meta, **kw):
        frame = build_frame(op, meta, **kw)
        return FuzzCase(family, f"{op}/{name}", op, mutation, expect,
                        frame.hex())

    yield case("baseline", "none", "tolerate", base, **tensors)
    for fname, spec in sorted(fields.items()):
        dropped = {k: v for k, v in base.items() if k != fname}
        if spec["kind"] == "req":
            expect = ("tolerate" if (op, fname) in SOFT_REJECT else "reject")
            yield case(f"drop:{fname}", "drop_required", expect, dropped)
        else:
            yield case(f"drop:{fname}", "drop_optional", "tolerate", dropped)
        t = spec["types"][0] if spec["types"] else "str"
        retyped = dict(base)
        retyped[fname] = _TYPE_SWAPS.get(t, [None])
        yield case(f"retype:{fname}", "retype", "tolerate", retyped)
        if t in ("str", "bytes"):
            big = dict(base)
            big[fname] = ("A" * (1 << 20)) if t == "str" else b"\xff" * (1 << 20)
            yield case(f"oversize:{fname}", "oversize", "tolerate", big)
        elif t == "int":
            big = dict(base)
            big[fname] = 1 << 62
            yield case(f"oversize:{fname}", "oversize", "tolerate", big)
        elif t == "float":
            # value-level hostility for float fields (sampling knobs):
            # non-finite and out-of-range values must come back as
            # well-formed frames, never decoder state or a wedged loop
            for label, val in (("nan", float("nan")),
                               ("inf", float("inf")),
                               ("neg", -1.0)):
                hostile = dict(base)
                hostile[fname] = val
                yield case(f"hostile-{label}:{fname}", "hostile_value",
                           "tolerate", hostile)
    # whole-meta shapes
    yield case("meta-str", "meta_not_map", "tolerate", "junk")
    yield case("meta-list", "meta_not_map", "tolerate", [1, 2, 3])
    yield case("meta-nil", "meta_not_map", "tolerate", None)
    # extra unknown field next to a valid-shaped meta (version skew:
    # newer sender, older receiver — must be ignored or rejected cleanly)
    skew = dict(base)
    skew[f"xfield_{rng.randrange(1000)}"] = rng.randrange(1 << 30)
    yield case("skew-extra", "unknown_field", "tolerate", skew)


def _frame_cases(family: str, ops: list, rng: Random):
    """Framing-level mutations, spread across the family's real ops."""

    def pick_op():
        return ops[rng.randrange(len(ops))]

    def fc(name, mutation, expect, frame: bytes, wait=True):
        return FuzzCase(family, name, "*", mutation, expect, frame.hex(),
                        wait=wait)

    op = pick_op()
    # outer prefix declares more than we send: the server blocks on
    # readexactly until our close → IncompleteReadError → clean break
    whole = build_frame(op, {})
    yield fc("frame/short-read", "outer_lies_long", "tolerate",
             _U32.pack(len(whole) + 64) + whole[4:], wait=False)
    # truncated mid-header
    yield fc("frame/truncated", "truncated", "tolerate",
             build_frame(op, {"k": "v"}, truncate_to=9), wait=False)
    # outer prefix over MAX_FRAME_BYTES: recv_frame refuses
    yield fc("frame/outer-huge", "outer_oversized", "tolerate",
             _U32.pack((1 << 30) + 5) + b"\x00" * 16)
    # inner hlen exceeds the payload
    yield fc("frame/hlen-lie", "hlen_oversized", "tolerate",
             build_frame(op, {}, hlen_override=0xFFFF))
    yield fc("frame/hlen-zero", "hlen_zero", "tolerate",
             build_frame(op, {}, hlen_override=0))
    # header is not msgpack at all
    junk = bytes(rng.randrange(256) for _ in range(24))
    yield fc("frame/junk-header", "junk_header", "tolerate",
             build_frame(None, None, header_raw=junk))
    # header is msgpack but not a map / missing keys
    yield fc("frame/header-int", "junk_header", "tolerate",
             build_frame(None, None, header_raw=msgpack.packb(42)))
    yield fc("frame/header-no-t", "junk_header", "tolerate",
             build_frame(None, None,
                         header_raw=msgpack.packb({"m": {}, "ts": []})))
    # tensor-spec lies: declared nbytes disagree with payload / dtype
    yield fc("frame/spec-nbytes-lie", "tensor_spec_lie", "tolerate",
             build_frame(pick_op(), {}, specs=[["float32", [4], 999]],
                         blobs=b"\x00" * 16))
    yield fc("frame/spec-negative", "tensor_spec_lie", "tolerate",
             build_frame(pick_op(), {}, specs=[["float32", [-3], 12]],
                         blobs=b"\x00" * 12))
    yield fc("frame/spec-bad-dtype", "tensor_spec_lie", "tolerate",
             build_frame(pick_op(), {}, specs=[["no_such_dtype", [2], 8]],
                         blobs=b"\x00" * 8))
    yield fc("frame/spec-overflow-shape", "tensor_spec_lie", "tolerate",
             build_frame(pick_op(), {},
                         specs=[["float32", [1 << 40, 1 << 40], 16]],
                         blobs=b"\x00" * 16))
    # rid games (v1 connection: no hello, so rid must be inert)
    yield fc("frame/rid-huge", "rid_games", "tolerate",
             build_frame(pick_op(), {}, rid=(1 << 63) - 1))
    yield fc("frame/rid-negative", "rid_games", "tolerate",
             build_frame(pick_op(), {}, rid=-7))
    yield fc("frame/rid-str", "rid_games", "tolerate",
             build_frame(pick_op(), {}, rid="abc"))
    # unknown op: every dispatcher owes an error-shaped reply
    yield fc("frame/unknown-op", "unknown_op", "reject",
             build_frame(f"no_such_op_{rng.randrange(1000)}", {}))
    # hello boundary frames
    yield fc("hello/features-int", "hello_hostile", "tolerate",
             build_frame("hello", {"features": 7}))
    yield fc("hello/features-huge", "hello_hostile", "tolerate",
             build_frame("hello", {"features": ["f"] * 4096}))
    yield fc("hello/meta-nil", "hello_hostile", "tolerate",
             build_frame("hello", None))


def _byteflip_cases(family: str, ops: list, rng: Random, n: int):
    """Seeded single-byte corruptions of valid frames.  Flips inside the
    outer length prefix re-frame the byte stream arbitrarily, so these
    never wait on a reply — write, close, assert survival via the next
    liveness probe."""
    for i in range(n):
        op = ops[rng.randrange(len(ops))]
        specs, blob = _tensor_blob("float32", [2, 2], fill=i % 7)
        frame = bytearray(build_frame(op, {"uid": "e.0", "i": i},
                                      specs=[specs], blobs=blob))
        pos = rng.randrange(len(frame))
        frame[pos] ^= 1 << rng.randrange(8)
        yield FuzzCase(family, f"flip/{op}/{i}@{pos}", op, "byte_flip",
                       "tolerate", bytes(frame).hex(), wait=False)


def generate_cases(
    seed: int,
    paths: Iterable[str],
    families: Optional[Iterable[str]] = None,
    min_per_family: int = 220,
) -> list:
    """The full deterministic battery: same (seed, tree) → byte-identical
    cases in identical order."""
    model = field_model(paths)
    wanted = tuple(families) if families else FAMILIES
    cases: list = []
    for fam in wanted:
        ops_model = model.get(fam)
        if not ops_model:
            continue
        rng = Random((seed, fam).__repr__())
        fam_cases: list = []
        ops = sorted(o for o in ops_model if o not in STATEFUL_OPS)
        if not ops:
            continue
        for op in ops:
            fam_cases.extend(_meta_cases(fam, op, ops_model[op], rng))
        fam_cases.extend(_frame_cases(fam, ops, rng))
        deficit = max(0, min_per_family - len(fam_cases))
        fam_cases.extend(_byteflip_cases(fam, ops, rng, deficit + 16))
        cases.extend(fam_cases)
    return cases


# ---------------------------------------------------------------------------
# corpus I/O
# ---------------------------------------------------------------------------


def dump_corpus(cases: list, path: str, meta: Optional[dict] = None) -> None:
    doc = {
        "format": "lah-fuzz-corpus-v1",
        "meta": meta or {},
        "cases": [c.to_json() for c in cases],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=True)
        fh.write("\n")


def load_corpus(path: str) -> list:
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if doc.get("format") != "lah-fuzz-corpus-v1":
        raise ValueError(f"{path}: not a lah-fuzz corpus")
    return [FuzzCase.from_json(c) for c in doc["cases"]]
