"""lah-schema: AST extraction of the wire contract from BOTH sides (ISSUE 15).

The swarm's trust boundary is the framed tensor RPC: four dispatcher
families (expert ``connection_handler._dispatch``, gateway
``frontdoor._dispatch``, averaging ``handler._dispatch``, DHT
``protocol._serve``) parse peer-supplied meta maps, and a dozen client
construction sites emit them — across protocol v1/v2 framing and the
negotiated ``mux``/``codec`` features.  R8 checks op *names* against
PROTOCOL.md; nothing checked message *shapes* until this module.

This is a pure-AST extractor (no imports of the linted code, sub-second,
same contract as analysis/lint.py).  It recovers a per-op wire IR:

- **handler side** — for every op branch of a dispatch function
  (``msg_type == "op"`` / ``msg_type in (...)`` arms), the meta fields
  the handler parses: ``meta["k"]`` subscripts are *required* (``req``),
  ``meta.get("k")`` reads are *accepted* (``opt``); accesses before the
  branch chain are family-common.  Helpers the meta dict is forwarded to
  (``_on_join(meta)``, ``_gen_submit(meta)``, ``handoff.handle_part(meta,
  tensors)``) are followed transitively, across modules, so the parse
  site's true field set is recovered even when validation lives in a
  different file (server/lifecycle.py).  Value types are inferred from
  ``isinstance``/cast patterns on the fetched names where visible.

- **sender side** — every ``pool.rpc``/``pool.rpc_prepared`` call whose
  op resolves to a string literal, directly or through wrapper chains
  (``GatewayClient._rpc`` -> ``pool.rpc``; ``DHTProtocol._call`` ->
  ``_transport`` -> ``pool.rpc``; ``RemoteExpert._call_blocking`` ->
  ``_rpc``/``_rpc_prepared``; the MoE fan-out closures whose ``msg_type``
  is an enclosing function's parameter).  Meta fields are resolved from
  dict literals, local assignments, ``{**meta, ...}`` augmentation,
  conditional ``meta["k"] = v`` writes and single-dict transformer
  helpers; a field is *guaranteed* when no ``if`` dominates its
  construction that does not also dominate the emit call, *conditional*
  otherwise.  Wrapper augmentations (the DHT ``from``/``port`` stamp)
  count as guaranteed for every op routed through the wrapper.

- **feature gates** — a ``meta["wire"] = <dict codec form>`` write is
  *gated* when a dominating ``pool.supports("codec")`` test covers it;
  ``pack_frames(..., rid=...)`` emission is checked against the
  rid-echo/`next_rid` idioms (protocol v2 mux).  Ungated candidates feed
  lint rule R14 (the mixed-build version-skew class).

The IR feeds: lint rules R12-R15 (analysis/lint.py), the structure-aware
fuzzer (analysis/fuzz.py + tools/lah_fuzz.py) and the collect-gate
schema stage (tools/collect_gate.py --schema).  PROTOCOL.md's
machine-read field rows are the documentation mirror of this IR (R15).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Optional

# dispatch-function names recognized as handler entry points (same set
# R8 keys on) and the emit-call tails recognized as client senders
_DISPATCH_NAMES = ("_dispatch", "_serve")
_EMIT_TAILS = ("rpc", "rpc_prepared")
_FRAME_PACKERS = ("pack_frames", "pack_message")

# positional index of the meta argument in emit calls (after msg_type):
# rpc(msg_type, tensors, meta), rpc_prepared(msg_type, wire, meta)
_EMIT_META_POS = 2

# ops answered inline by serving loops (never dispatch branches)
HANDSHAKE_OPS = ("hello", "hello_ok")

# family inference from handled op names — a dispatcher is classified by
# what it serves, so single-file corpora work without basename hacks
_FAMILY_MARKERS = (
    ("gateway", {"gen_submit", "gen_poll", "gen_cancel"}),
    ("averaging", {"avg_join", "avg_part", "avg_stats"}),
    ("dht", {"ping", "store", "find_node", "find_value"}),
)

_MAX_DEPTH = 4  # wrapper/helper recursion bound (cycles guarded too)


@dataclasses.dataclass
class FieldUse:
    """One meta field as seen by a handler: ``req`` (subscript access)
    or ``opt`` (``.get``), with any isinstance/cast-inferred types."""

    name: str
    kind: str  # "req" | "opt"
    line: int = 0
    types: tuple = ()

    def merge(self, other: "FieldUse") -> None:
        if other.kind == "req":
            self.kind = "req"  # any hard access makes the field required
        self.types = tuple(sorted(set(self.types) | set(other.types)))


@dataclasses.dataclass
class SenderField:
    """One meta field at a sender construction site."""

    name: str
    kind: str  # "req" (on every path to the emit) | "opt" (conditional)
    line: int = 0
    gate: Optional[str] = None  # "codec"/"mux" when a supports() test dominates


@dataclasses.dataclass
class SenderSite:
    """One resolved (op, construction path) pair: the top call site where
    the op literal appears, plus the accumulated meta fields."""

    path: str
    line: int
    op: str
    fields: dict  # name -> SenderField
    via: str = ""  # wrapper chain, innermost first (diagnostics)


@dataclasses.dataclass
class HandlerSchema:
    """Per-dispatcher extraction result."""

    path: str
    family: str
    common: dict = dataclasses.field(default_factory=dict)  # name -> FieldUse
    ops: dict = dataclasses.field(default_factory=dict)  # op -> {name: FieldUse}
    op_lines: dict = dataclasses.field(default_factory=dict)  # op -> line

    def accepted(self, op: str) -> dict:
        out = dict(self.common)
        out.update(self.ops.get(op, {}))
        return out


@dataclasses.dataclass
class GateCandidate:
    """A feature-gated wire form emitted without a visible negotiation
    guard (R14 input): the dict ``wire`` codec form or a rid-tagged
    frame."""

    path: str
    line: int
    col: int
    what: str  # "wire" | "rid"
    detail: str


@dataclasses.dataclass
class WireIR:
    handlers: list = dataclasses.field(default_factory=list)  # [HandlerSchema]
    senders: list = dataclasses.field(default_factory=list)  # [SenderSite]
    gate_candidates: list = dataclasses.field(default_factory=list)
    unresolved: list = dataclasses.field(default_factory=list)  # (path, line, why)

    def families_handling(self, op: str) -> list:
        return sorted({h.family for h in self.handlers if op in h.ops})

    def handled_ops(self) -> set:
        out: set = set()
        for h in self.handlers:
            out.update(h.ops)
        return out

    def sender_sites(self, op: str) -> list:
        return [s for s in self.senders if s.op == op]


# ---------------------------------------------------------------------------
# module indexing: parents, functions, call sites
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _FuncRec:
    path: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    params: list  # positional param names (self/cls included)
    cls: Optional[str]  # enclosing class name, if a method
    enclosing: list  # outer function nodes, innermost last


class _Index:
    """Cross-file AST index built once per extraction."""

    def __init__(self) -> None:
        self.funcs: dict = {}  # short name -> [_FuncRec]
        self.parents: dict = {}  # id(node) -> parent node (per all trees)
        self.node_path: dict = {}  # id(node) -> file path
        self.trees: dict = {}  # path -> ast.Module

    def add_tree(self, path: str, tree: ast.Module) -> None:
        self.trees[path] = tree
        cls_stack: list = []
        func_stack: list = []

        def walk(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                self.parents[id(child)] = node
                self.node_path[id(child)] = path
                is_cls = isinstance(child, ast.ClassDef)
                is_fn = isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef)
                )
                if is_fn:
                    rec = _FuncRec(
                        path=path,
                        node=child,
                        params=[a.arg for a in child.args.args],
                        cls=cls_stack[-1] if cls_stack else None,
                        enclosing=list(func_stack),
                    )
                    self.funcs.setdefault(child.name, []).append(rec)
                if is_cls:
                    cls_stack.append(child.name)
                if is_fn:
                    func_stack.append(child)
                walk(child)
                if is_fn:
                    func_stack.pop()
                if is_cls:
                    cls_stack.pop()

        self.parents[id(tree)] = None
        self.node_path[id(tree)] = path
        walk(tree)

    def ancestors(self, node: ast.AST):
        cur = self.parents.get(id(node))
        while cur is not None:
            yield cur
            cur = self.parents.get(id(cur))

    def enclosing_funcs(self, node: ast.AST) -> list:
        """Enclosing function nodes, innermost first."""
        return [
            a for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

    def enclosing_class(self, node: ast.AST) -> Optional[str]:
        for a in self.ancestors(node):
            if isinstance(a, ast.ClassDef):
                return a.name
        return None

    def resolve_callee(self, call: ast.Call, from_path: str) -> list:
        """Candidate _FuncRecs for a call, preferring same-file/-class
        matches: ``self.f(...)`` binds to methods of the caller's own
        class first; bare ``f(...)`` to same-file defs first; dotted
        receivers (``self.averager._on_join``) match by tail anywhere."""
        fn = call.func
        if isinstance(fn, ast.Name):
            cands = self.funcs.get(fn.id, [])
            local = [c for c in cands if c.path == from_path]
            return local or cands
        if not isinstance(fn, ast.Attribute):
            return []
        cands = self.funcs.get(fn.attr, [])
        if isinstance(fn.value, ast.Name) and fn.value.id in ("self", "cls"):
            cls = self.enclosing_class(call)
            same = [c for c in cands if c.path == from_path and c.cls == cls]
            if same:
                return same
        return cands


# ---------------------------------------------------------------------------
# small AST helpers
# ---------------------------------------------------------------------------


def _attr_tail(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _op_literals(test: ast.AST, opvar: str) -> Optional[list]:
    """String literals a branch test compares ``opvar`` against, else
    None (not an op branch)."""
    for node in ast.walk(test):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name) and node.left.id == opvar):
            continue
        out: list = []
        for op, comp in zip(node.ops, node.comparators):
            if isinstance(op, ast.Eq):
                s = _const_str(comp)
                if s is not None:
                    out.append(s)
            elif isinstance(op, ast.In) and isinstance(
                comp, (ast.Tuple, ast.List, ast.Set)
            ):
                out.extend(
                    s for s in (_const_str(e) for e in comp.elts)
                    if s is not None
                )
        if out:
            return out
    return None


def _call_positional(call: ast.Call, rec: _FuncRec, param: str) -> Optional[ast.AST]:
    """The argument expression a call binds to ``param`` of ``rec``
    (positional, adjusted for bound ``self``, or keyword); None if the
    call does not pass it."""
    try:
        idx = rec.params.index(param)
    except ValueError:
        return None
    if rec.cls is not None and isinstance(call.func, ast.Attribute):
        idx -= 1  # self is bound by the attribute receiver
    if 0 <= idx < len(call.args):
        arg = call.args[idx]
        return None if isinstance(arg, ast.Starred) else arg
    for kw in call.keywords:
        if kw.arg == param:
            return kw.value
    return None


def _supports_feature(test: ast.AST) -> Optional[str]:
    """The feature literal of a ``<x>.supports("...")`` call inside a
    branch test, else None."""
    for node in ast.walk(test):
        if (
            isinstance(node, ast.Call)
            and _attr_tail(node.func) == "supports"
            and node.args
        ):
            s = _const_str(node.args[0])
            if s is not None:
                return s
    return None


def _legacy_wire_value(node: ast.AST) -> bool:
    """True for wire values that are provably the LEGACY STRING form
    (a dtype literal or a ``wire_dtype`` attribute) — understood by all
    peers, so no codec negotiation is needed (R14 exemption)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return True
    tail = _attr_tail(node)
    return tail is not None and tail.endswith("wire_dtype")


# ---------------------------------------------------------------------------
# handler-side extraction
# ---------------------------------------------------------------------------


def _family_of(ops: set) -> str:
    for family, markers in _FAMILY_MARKERS:
        if ops & markers:
            return family
    return "expert"


def _meta_var_of_dispatch(fn: ast.AST) -> tuple:
    """(op_var, meta_var) of a dispatch function: parameters named
    ``msg_type``/``meta`` when present, else the 1st/3rd targets of a
    tuple-assign from ``unpack_message(...)``."""
    params = [a.arg for a in fn.args.args]
    opvar = "msg_type" if "msg_type" in params else None
    metavar = "meta" if "meta" in params else None
    if opvar and metavar:
        return opvar, metavar
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if (
            isinstance(tgt, ast.Tuple)
            and len(tgt.elts) == 3
            and all(isinstance(e, ast.Name) for e in tgt.elts)
            and isinstance(node.value, ast.Call)
            and _attr_tail(node.value.func) == "unpack_message"
        ):
            opvar = opvar or tgt.elts[0].id
            metavar = metavar or tgt.elts[2].id
            break
    return opvar, metavar


def _infer_types(fn: ast.AST, metavar: str) -> dict:
    """field -> set of type names, from ``v = meta.get("k")`` /
    ``meta["k"]`` assignments followed by ``isinstance(v, T)`` checks or
    ``int(v)``/``float(v)``/``str(v)`` casts in the same function."""
    var_field: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            field = _meta_field_of(node.value, metavar)
            if field is not None:
                var_field[tgt.id] = field
    types: dict = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id == "isinstance":
            if len(node.args) == 2 and isinstance(node.args[0], ast.Name):
                field = var_field.get(node.args[0].id)
                if field is None:
                    continue
                tp = node.args[1]
                names = (
                    [e for e in tp.elts] if isinstance(tp, ast.Tuple) else [tp]
                )
                for n in names:
                    t = _attr_tail(n)
                    if t:
                        types.setdefault(field, set()).add(t)
        elif isinstance(node.func, ast.Name) and node.func.id in (
            "int", "float", "str", "bytes", "bool", "list",
        ):
            if len(node.args) >= 1:
                field = _meta_field_of(node.args[0], metavar)
                if field is None and isinstance(node.args[0], ast.Name):
                    field = var_field.get(node.args[0].id)
                if field is not None:
                    types.setdefault(field, set()).add(node.func.id)
    return types


def _meta_field_of(node: ast.AST, metavar: str) -> Optional[str]:
    """The field name when ``node`` is ``meta["k"]`` or ``meta.get("k"[, d])``."""
    if (
        isinstance(node, ast.Subscript)
        and isinstance(node.value, ast.Name)
        and node.value.id == metavar
    ):
        return _const_str(node.slice)
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "get"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == metavar
        and node.args
    ):
        return _const_str(node.args[0])
    return None


def _harvest_fields(
    index: _Index, fn: ast.AST, metavar: str, out: dict,
    depth: int, seen: set,
) -> None:
    """Collect meta field accesses within ``fn`` into ``out`` (field ->
    FieldUse), following calls that forward the meta variable."""
    if id(fn) in seen or depth > _MAX_DEPTH:
        return
    seen.add(id(fn))
    types = _infer_types(fn, metavar)
    for node in ast.walk(fn):
        field = _meta_field_of(node, metavar)
        if field is not None:
            kind = "req" if isinstance(node, ast.Subscript) else "opt"
            use = FieldUse(field, kind, node.lineno,
                           tuple(sorted(types.get(field, ()))))
            if field in out:
                out[field].merge(use)
            else:
                out[field] = use
            continue
        if isinstance(node, ast.Call):
            # meta forwarded to a helper? follow the callee's param
            passed = [
                i for i, a in enumerate(node.args)
                if isinstance(a, ast.Name) and a.id == metavar
            ]
            if not passed:
                continue
            from_path = index.node_path.get(id(node), "")
            for rec in index.resolve_callee(node, from_path)[:3]:
                idx = passed[0]
                if rec.cls is not None and isinstance(node.func, ast.Attribute):
                    idx += 1  # self bound by receiver
                if idx < len(rec.params):
                    _harvest_fields(
                        index, rec.node, rec.params[idx], out, depth + 1, seen
                    )


def _extract_handler(index: _Index, path: str, fn: ast.AST) -> Optional[HandlerSchema]:
    opvar, metavar = _meta_var_of_dispatch(fn)
    if opvar is None or metavar is None:
        return None
    # op branches: If nodes (elif arms are nested Ifs) testing the op var
    branch_of: dict = {}  # id(stmt body If) -> ops
    op_lines: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.If):
            ops = _op_literals(node.test, opvar)
            if ops:
                branch_of[id(node)] = ops
                for op in ops:
                    op_lines.setdefault(op, node.lineno)
    if not op_lines:
        return None

    def owning_ops(node: ast.AST) -> Optional[list]:
        """Ops of the innermost op-branch whose BODY contains the node."""
        cur = node
        for anc in index.ancestors(node):
            if isinstance(anc, ast.If) and id(anc) in branch_of:
                in_body = any(
                    cur is s or any(cur is w for w in ast.walk(s))
                    for s in anc.body
                )
                if in_body:
                    return branch_of[id(anc)]
            if anc is fn:
                break
        return None

    common: dict = {}
    per_op: dict = {op: {} for op in op_lines}
    types = _infer_types(fn, metavar)

    # direct accesses + helper calls, attributed to their op branch
    for node in ast.walk(fn):
        field = _meta_field_of(node, metavar)
        helper_call = None
        if field is None and isinstance(node, ast.Call):
            if any(
                isinstance(a, ast.Name) and a.id == metavar
                for a in node.args
            ):
                helper_call = node
        if field is None and helper_call is None:
            continue
        ops = owning_ops(node)
        if field is not None:
            kind = "req" if isinstance(node, ast.Subscript) else "opt"
            use = FieldUse(field, kind, node.lineno,
                           tuple(sorted(types.get(field, ()))))
            targets = (
                [per_op[o] for o in ops if o in per_op]
                if ops else [common]
            )
            for tgt in targets:
                if field in tgt:
                    tgt[field].merge(use)
                else:
                    tgt[field] = dataclasses.replace(use)
        else:
            harvested: dict = {}
            idx_args = [
                i for i, a in enumerate(helper_call.args)
                if isinstance(a, ast.Name) and a.id == metavar
            ]
            for rec in index.resolve_callee(helper_call, path)[:3]:
                if rec.node is fn:
                    continue
                idx = idx_args[0]
                if rec.cls is not None and isinstance(
                    helper_call.func, ast.Attribute
                ):
                    idx += 1
                if idx < len(rec.params):
                    _harvest_fields(
                        index, rec.node, rec.params[idx], harvested, 1,
                        {id(fn)},
                    )
            targets = (
                [per_op[o] for o in ops if o in per_op]
                if ops else [common]
            )
            for tgt in targets:
                for f, use in harvested.items():
                    if f in tgt:
                        tgt[f].merge(use)
                    else:
                        tgt[f] = dataclasses.replace(use)

    family = _family_of(set(op_lines))
    return HandlerSchema(
        path=path, family=family, common=common, ops=per_op,
        op_lines=op_lines,
    )


# ---------------------------------------------------------------------------
# sender-side extraction
# ---------------------------------------------------------------------------


def _dominating_ifs(index: _Index, node: ast.AST, scope: ast.AST) -> list:
    """If ancestors of ``node`` inside ``scope`` (innermost first)."""
    if node is scope:
        return []
    out = []
    for anc in index.ancestors(node):
        if anc is scope:
            break
        if isinstance(anc, ast.If):
            out.append(anc)
    return out


def _field_entries_from_dict(
    index: _Index, d: ast.Dict, scope: ast.AST, emit: ast.AST, ir: "WireIR",
) -> tuple:
    """(entries, passthrough_names): dict-literal fields are guaranteed;
    ``**name`` unpacks are returned for upstream resolution."""
    entries: list = []
    passthrough: list = []
    for k, v in zip(d.keys, d.values):
        if k is None:
            if isinstance(v, ast.Name):
                passthrough.append(v.id)
            continue
        name = _const_str(k)
        if name is not None:
            entries.append(SenderField(name, "req", k.lineno, None))
            if name == "wire" and not _legacy_wire_value(v):
                entries[-1].gate = _gate_of(index, d, scope, emit)
                if entries[-1].gate is None:
                    ir.gate_candidates.append(
                        GateCandidate(
                            index.node_path.get(id(d), ""), k.lineno,
                            d.col_offset, "wire",
                            "dict `wire` codec form in a meta literal "
                            "without a dominating `supports(\"codec\")` "
                            "guard",
                        )
                    )
    return entries, passthrough


def _gate_of(
    index: _Index, node: ast.AST, scope: ast.AST, emit: ast.AST,
) -> Optional[str]:
    """Feature gate dominating ``node`` but not the emit call."""
    emit_ifs = {id(i) for i in _dominating_ifs(index, emit, scope)}
    for anc in _dominating_ifs(index, node, scope):
        if id(anc) in emit_ifs:
            continue
        feat = _supports_feature(anc.test)
        if feat is not None:
            return feat
    return None


def _conditional(
    index: _Index, node: ast.AST, scope: ast.AST, emit: ast.AST,
) -> bool:
    """True when an ``if`` dominates ``node`` without dominating the
    emit call — the field is then not on every construction path."""
    emit_ifs = {id(i) for i in _dominating_ifs(index, emit, scope)}
    return any(
        id(i) not in emit_ifs
        for i in _dominating_ifs(index, node, scope)
    )


@dataclasses.dataclass
class _MetaShape:
    """Resolved meta construction: concrete fields (some op-conditional)
    plus pass-through parameter names still owed by callers."""

    entries: list = dataclasses.field(default_factory=list)  # SenderField
    op_cond: list = dataclasses.field(default_factory=list)  # (op, [SenderField])
    passthrough: list = dataclasses.field(default_factory=list)  # param names


def _resolve_meta_expr(
    index: _Index, expr: ast.AST, scope: ast.AST, emit: ast.AST,
    opvar: Optional[str], ir: WireIR, depth: int = 0,
) -> _MetaShape:
    shape = _MetaShape()
    if depth > _MAX_DEPTH or expr is None:
        return shape
    if isinstance(expr, ast.Dict):
        entries, passthrough = _field_entries_from_dict(
            index, expr, scope, emit, ir
        )
        shape.entries.extend(entries)
        for nm in passthrough:
            sub = _resolve_meta_expr(
                index, ast.Name(id=nm, ctx=ast.Load()), scope, emit,
                opvar, ir, depth + 1,
            )
            # the unpack inherits the dict's own position for guards
            shape.entries.extend(sub.entries)
            shape.op_cond.extend(sub.op_cond)
            shape.passthrough.extend(sub.passthrough)
        return shape
    if isinstance(expr, ast.IfExp):
        then = _resolve_meta_expr(
            index, expr.body, scope, emit, opvar, ir, depth + 1
        )
        other = _resolve_meta_expr(
            index, expr.orelse, scope, emit, opvar, ir, depth + 1
        )
        lits = _op_literals(expr.test, opvar) if opvar else None
        if lits and len(lits) == 1:
            shape.op_cond.append((lits[0], then.entries))
            shape.op_cond.append((None, other.entries))  # every other op
        else:
            both = {e.name for e in then.entries} & {
                e.name for e in other.entries
            }
            for e in then.entries + other.entries:
                e = dataclasses.replace(e)
                if e.name not in both:
                    e.kind = "opt"
                if e.name in both and any(
                    x.name == e.name for x in shape.entries
                ):
                    continue
                shape.entries.append(e)
        shape.passthrough.extend(then.passthrough + other.passthrough)
        return shape
    if isinstance(expr, ast.Call):
        # single-meta transformer helper: fields of its dict argument
        # plus the helper's own writes to that parameter (_wire_meta)
        from_path = index.node_path.get(id(expr), "")
        for rec in index.resolve_callee(expr, from_path)[:2]:
            arg_dicts = [a for a in expr.args if isinstance(a, ast.Dict)]
            if not arg_dicts:
                continue
            sub = _resolve_meta_expr(
                index, arg_dicts[0], scope, emit, opvar, ir, depth + 1
            )
            shape.entries.extend(sub.entries)
            shape.op_cond.extend(sub.op_cond)
            shape.passthrough.extend(sub.passthrough)
            idx = expr.args.index(arg_dicts[0])
            if rec.cls is not None and isinstance(expr.func, ast.Attribute):
                idx += 1
            if idx < len(rec.params):
                # relative to the helper's own body every dominating
                # ``if`` makes the write conditional (the helper returns
                # on all paths)
                _collect_augmentations(
                    index, rec.node, rec.params[idx], rec.node,
                    shape, ir, conditional_base=True,
                )
            break
        return shape
    if isinstance(expr, ast.Name):
        # a parameter: owed by callers
        for encl in [scope] + index.enclosing_funcs(scope):
            if expr.id in [a.arg for a in encl.args.args]:
                shape.passthrough.append(expr.id)
                return shape
        # a local: resolve its assignment + subscript augmentations
        owner = None
        for encl in [scope] + index.enclosing_funcs(emit):
            for node in ast.walk(encl):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                ):
                    owner = encl
                    sub = _resolve_meta_expr(
                        index, node.value, encl, emit, opvar, ir, depth + 1
                    )
                    for e in sub.entries:
                        if _conditional(index, node, encl, emit):
                            e = dataclasses.replace(e, kind="opt")
                        shape.entries.append(e)
                    shape.op_cond.extend(sub.op_cond)
                    shape.passthrough.extend(sub.passthrough)
            if owner is not None:
                break
        if owner is not None:
            _collect_augmentations(
                index, owner, expr.id, emit, shape, ir,
                conditional_base=True,
            )
        return shape
    return shape


def _collect_augmentations(
    index: _Index, scope: ast.AST, name: str, emit: ast.AST,
    shape: _MetaShape, ir: WireIR, conditional_base: bool,
) -> None:
    """``name["k"] = v`` writes inside ``scope``: guaranteed when every
    dominating ``if`` also dominates the emit, conditional otherwise;
    the ``wire`` dict form records its ``supports()`` gate (R14)."""
    for node in ast.walk(scope):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Subscript)
            and isinstance(node.targets[0].value, ast.Name)
            and node.targets[0].value.id == name
        ):
            continue
        field = _const_str(node.targets[0].slice)
        if field is None:
            continue
        cond = conditional_base and _conditional(index, node, scope, emit)
        entry = SenderField(field, "opt" if cond else "req", node.lineno)
        if field == "wire" and not _legacy_wire_value(node.value):
            entry.gate = _gate_of(index, node, scope, emit)
            if entry.gate is None:
                ir.gate_candidates.append(
                    GateCandidate(
                        index.node_path.get(id(node), ""), node.lineno,
                        node.col_offset, "wire",
                        "dict `wire` codec form assigned without a "
                        "dominating `supports(\"codec\")` guard",
                    )
                )
        shape.entries.append(entry)


def _materialize(shape: _MetaShape, op: str) -> dict:
    """Final field map for one resolved op."""
    fields: dict = {}

    def put(e: SenderField) -> None:
        if e.name in fields:
            # guaranteed beats conditional when both paths write it
            if e.kind == "req":
                fields[e.name].kind = "req"
        else:
            fields[e.name] = dataclasses.replace(e)

    for e in shape.entries:
        put(e)
    matched = any(cop == op for cop, _ in shape.op_cond)
    for cop, entries in shape.op_cond:
        if cop == op or (cop is None and not matched):
            for e in entries:
                put(e)
    return fields


def _own_augmentations(index: _Index, func: ast.AST, param: str) -> list:
    """Meta fields a wrapper stamps onto a pass-through parameter before
    forwarding it: ``param = {**param, "k": v}`` re-bindings and
    ``param["k"] = v`` writes (the DHT ``from``/``port`` stamp).
    Unconditional writes count as guaranteed for every op routed through
    the wrapper."""
    out: list = []
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        cond = bool(_dominating_ifs(index, node, func))
        if (
            isinstance(tgt, ast.Name) and tgt.id == param
            and isinstance(node.value, ast.Dict)
            and any(
                k is None and isinstance(v, ast.Name) and v.id == param
                for k, v in zip(node.value.keys, node.value.values)
            )
        ):
            for k in node.value.keys:
                nm = _const_str(k) if k is not None else None
                if nm is not None:
                    out.append(
                        SenderField(nm, "opt" if cond else "req", k.lineno)
                    )
        elif (
            isinstance(tgt, ast.Subscript)
            and isinstance(tgt.value, ast.Name)
            and tgt.value.id == param
        ):
            nm = _const_str(tgt.slice)
            if nm is not None:
                out.append(
                    SenderField(nm, "opt" if cond else "req", node.lineno)
                )
    return out


def _resolve_ops_upward(
    index: _Index, func: ast.AST, op_param: str, meta_param: Optional[str],
    ir: WireIR, depth: int, seen: set,
):
    """Yield (call_site, op_literal, caller_scope, meta_expr, extras) for
    every caller chain of ``func`` that pins the op to a string literal;
    ``extras`` accumulates wrapper-stamped meta fields along the chain."""
    if depth > _MAX_DEPTH or id(func) in seen:
        return
    seen = seen | {id(func)}
    recs = [r for rs in index.funcs.values() for r in rs if r.node is func]
    if not recs:
        return
    rec = recs[0]
    own = (
        _own_augmentations(index, func, meta_param) if meta_param else []
    )
    for path, tree in index.trees.items():
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            if _attr_tail(call.func) != func.name:
                continue
            # same-class guard for self-calls; bare names need same file
            if isinstance(call.func, ast.Name) and path != rec.path:
                continue
            if (
                isinstance(call.func, ast.Attribute)
                and isinstance(call.func.value, ast.Name)
                and call.func.value.id in ("self", "cls")
                and rec.cls is not None
                and index.enclosing_class(call) != rec.cls
            ):
                continue
            op_arg = _call_positional(call, rec, op_param)
            if op_arg is None:
                continue
            meta_expr = (
                _call_positional(call, rec, meta_param)
                if meta_param else None
            )
            enclosing = index.enclosing_funcs(call)
            scope = enclosing[0] if enclosing else None
            lit = _const_str(op_arg)
            if lit is not None:
                yield call, lit, scope, meta_expr, list(own)
            elif isinstance(op_arg, ast.Name) and scope is not None:
                bound = None
                for encl in enclosing:
                    if op_arg.id in [a.arg for a in encl.args.args]:
                        bound = encl
                        break
                if bound is not None:
                    # caller is itself a wrapper: recurse through it.
                    # its meta param (if the meta expr is a bare param
                    # name) keeps the chain's passthrough alive
                    next_meta = None
                    if isinstance(meta_expr, ast.Name) and meta_expr.id in [
                        a.arg for a in bound.args.args
                    ]:
                        next_meta = meta_expr.id
                    for item in _resolve_ops_upward(
                        index, bound, op_arg.id, next_meta, ir,
                        depth + 1, seen,
                    ):
                        up_call, up_lit, up_scope, up_meta, up_extra = item
                        # meta resolved at the LOWEST level that builds
                        # it; a passthrough defers to the caller's expr
                        yield up_call, up_lit, up_scope, (
                            up_meta if next_meta is not None else meta_expr
                        ), list(own) + up_extra
                else:
                    ir.unresolved.append(
                        (path, call.lineno,
                         f"op argument `{op_arg.id}` of {func.name}() is "
                         "not a parameter — op unresolvable statically")
                    )


def _extract_senders(index: _Index, ir: WireIR) -> None:
    for path, tree in index.trees.items():
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            tail = _attr_tail(call.func)
            if tail not in _EMIT_TAILS or not call.args:
                continue
            if isinstance(call.func, ast.Name):
                continue  # bare rpc(...) defs/recursion, not pool calls
            enclosing_names = {
                f.name for f in index.enclosing_funcs(call)
            }
            if enclosing_names & set(_EMIT_TAILS):
                # the pool's own entry points delegate to each other
                # (rpc -> rpc_prepared); their callers are already the
                # emit sites — re-deriving them here only duplicates
                continue
            op_arg = call.args[0]
            meta_expr = None
            if len(call.args) > _EMIT_META_POS:
                meta_expr = call.args[_EMIT_META_POS]
            for kw in call.keywords:
                if kw.arg == "meta":
                    meta_expr = kw.value
            enclosing = index.enclosing_funcs(call)
            scope = enclosing[0] if enclosing else None
            lit = _const_str(op_arg)
            targets = []  # (top_call, op, scope, meta_expr, extras)
            if lit is not None:
                targets.append((call, lit, scope, meta_expr, []))
            elif isinstance(op_arg, ast.Name) and scope is not None:
                bound = None
                for encl in enclosing:
                    if op_arg.id in [a.arg for a in encl.args.args]:
                        bound = encl
                        break
                if bound is None:
                    ir.unresolved.append(
                        (path, call.lineno,
                         f"emit op `{op_arg.id}` is not a literal nor an "
                         "enclosing parameter")
                    )
                    continue
                next_meta = None
                if isinstance(meta_expr, ast.Name) and meta_expr.id in [
                    a.arg for a in bound.args.args
                ]:
                    next_meta = meta_expr.id
                for item in _resolve_ops_upward(
                    index, bound, op_arg.id, next_meta, ir, 1, set()
                ):
                    up_call, up_lit, up_scope, up_meta, up_extra = item
                    targets.append((
                        up_call, up_lit, up_scope,
                        up_meta if next_meta is not None else meta_expr,
                        up_extra,
                    ))
            else:
                continue
            for top_call, op, top_scope, m_expr, extras in targets:
                if top_scope is None or m_expr is None:
                    fields: dict = {}
                else:
                    opvar = (
                        op_arg.id if isinstance(op_arg, ast.Name) else None
                    )
                    shape = _resolve_meta_expr(
                        index, m_expr, top_scope, top_call, opvar, ir
                    )
                    # fields built in the EMIT scope (closures over the
                    # wrapper's op param) are resolved there too
                    if m_expr is meta_expr and scope is not None and (
                        top_scope is not scope
                    ):
                        shape2 = _resolve_meta_expr(
                            index, meta_expr, scope, call, opvar, ir
                        )
                        shape.entries.extend(shape2.entries)
                        shape.op_cond.extend(shape2.op_cond)
                    fields = _materialize(shape, op)
                for e in extras:
                    if e.name in fields:
                        if e.kind == "req":
                            fields[e.name].kind = "req"
                    else:
                        fields[e.name] = dataclasses.replace(e)
                top_path = index.node_path.get(id(top_call), path)
                ir.senders.append(
                    SenderSite(
                        path=top_path, line=top_call.lineno, op=op,
                        fields=fields, via=tail,
                    )
                )


# ---------------------------------------------------------------------------
# rid gate candidates (protocol v2 mux)
# ---------------------------------------------------------------------------


def _rid_exempt(index: _Index, value: ast.AST, scope_chain: list) -> bool:
    """True for rid values that are echo/negotiated by construction:
    the literal None, a ``rid`` parameter of an enclosing function (the
    handlers' reply echo), a name unpacked from ``peek_header(...)``
    (the mux reader echo) or assigned from ``.next_rid()`` (issued only
    on an established mux connection)."""
    if isinstance(value, ast.Constant) and value.value is None:
        return True
    if isinstance(value, ast.Call) and value.args:
        return _rid_exempt(index, value.args[0], scope_chain)  # int(rid)
    if not isinstance(value, ast.Name):
        return False
    for fn in scope_chain:
        if value.id in [a.arg for a in fn.args.args]:
            return value.id == "rid"
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                tgts = node.targets[0] if len(node.targets) == 1 else None
                names = []
                if isinstance(tgts, ast.Name):
                    names = [tgts.id]
                elif isinstance(tgts, ast.Tuple):
                    names = [
                        e.id for e in tgts.elts if isinstance(e, ast.Name)
                    ]
                if value.id not in names:
                    continue
                src = node.value
                if isinstance(src, ast.Call) and _attr_tail(src.func) in (
                    "peek_header", "next_rid",
                ):
                    return True
    return False


def _extract_rid_candidates(index: _Index, ir: WireIR) -> None:
    for path, tree in index.trees.items():
        for call in ast.walk(tree):
            if not isinstance(call, ast.Call):
                continue
            if _attr_tail(call.func) not in _FRAME_PACKERS:
                continue
            for kw in call.keywords:
                if kw.arg != "rid":
                    continue
                chain = index.enclosing_funcs(call)
                gated = any(
                    _supports_feature(i.test) == "mux"
                    for fn in chain[:1]
                    for i in _dominating_ifs(index, call, fn)
                )
                if gated or _rid_exempt(index, kw.value, chain):
                    continue
                ir.gate_candidates.append(
                    GateCandidate(
                        path, call.lineno, call.col_offset, "rid",
                        "rid-tagged frame built outside the rid-echo / "
                        "next_rid() / supports(\"mux\") idioms — v1 peers "
                        "drop unknown header keys only after a reparse",
                    )
                )


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def _iter_py_files(paths: Iterable[str]) -> list:
    out: list = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(
                    os.path.join(root, f)
                    for f in files if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def extract(paths: Iterable[str]) -> WireIR:
    """Extract the wire IR from files/directories.  Unparseable files
    are skipped (lah-lint reports them as PARSE findings)."""
    index = _Index()
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError):
            continue
        index.add_tree(path, tree)
    ir = WireIR()
    for path, tree in index.trees.items():
        for node in ast.walk(tree):
            if (
                isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node.name in _DISPATCH_NAMES
            ):
                schema = _extract_handler(index, path, node)
                if schema is not None:
                    ir.handlers.append(schema)
    _extract_senders(index, ir)
    _extract_rid_candidates(index, ir)
    ir.handlers.sort(key=lambda h: h.path)
    # multiple resolution passes over shared wrappers re-derive the same
    # site/candidate — dedupe on stable identity
    seen_sites: set = set()
    sites: list = []
    for s in sorted(
        ir.senders,
        key=lambda s: (s.path, s.line, s.op, s.via, -len(s.fields)),
    ):
        key = (s.path, s.line, s.op, s.via)
        if key in seen_sites:
            continue
        seen_sites.add(key)
        sites.append(s)
    ir.senders = sites
    seen_cands: set = set()
    cands: list = []
    for c in sorted(
        ir.gate_candidates, key=lambda c: (c.path, c.line, c.what)
    ):
        key = (c.path, c.line, c.what)
        if key not in seen_cands:
            seen_cands.add(key)
            cands.append(c)
    ir.gate_candidates = cands
    return ir


def coverage_report(paths: Iterable[str], doc_ops: dict) -> dict:
    """Per-documented-op extraction coverage (the collect-gate schema
    stage asserts this): handler schema present for EVERY op in the
    PROTOCOL.md tables (R8's denominator), sender sites present for
    every op that has an in-tree sender.  Ops with no in-tree sender are
    listed — not failed — their required fields are validated by the
    handler itself (and exercised by lah_fuzz)."""
    ir = extract(paths)
    handled = ir.handled_ops()
    report = {
        "ops": {},
        "missing_handler": [],
        "senderless": [],
        "unresolved": list(ir.unresolved),
    }
    for op in sorted(doc_ops):
        if op in HANDSHAKE_OPS:
            continue
        has_handler = op in handled
        sites = ir.sender_sites(op)
        report["ops"][op] = {
            "families": ir.families_handling(op),
            "handler": has_handler,
            "sender_sites": len(sites),
        }
        if not has_handler:
            report["missing_handler"].append(op)
        elif not sites:
            report["senderless"].append(op)
    report["ok"] = not report["missing_handler"]
    return report
