"""Co-activation-aware expert placement (ISSUE 16, tentpole part 2).

A PURE cost model + solver: a serializable swarm *snapshot* goes in, a
deterministic *migration plan* comes out.  No DHT, no sockets, no clock
— `tools/lah_rebalance.py` builds snapshots from live telemetry and
executes plans over the `migrate` RPC; everything here is unit-testable
offline and byte-reproducible per seed (the collect-gate placement
stage runs the solver twice and diffs the bytes).

Snapshot (every section is peer-supplied somewhere upstream, so every
section tolerates absence or garbage — malformed entries are skipped,
never raised on):

```
{
  "experts":     {uid: "host:port"},          # current home per expert
  "activations": {uid: count},                # per-expert dispatch counts
  "coact":       {"uidA|uidB": count},        # undirected pair counts
  "links":       {src: {dst: [rtt_s, bw_bps|null]}},  # measured link EMAs
  "sources":     {src: weight},               # dispatching clients
  "capacity":    {node: max_experts},         # optional per-node cap
  "bytes_per_dispatch": float,                # payload bytes per expert hop
}
```

Cost model (MoETuner-style, cf. PAPERS.md; topology-aware in the
TA-MoE sense): a candidate assignment `uid -> node` is scored as the
expected per-window wire cost

    cost = Σ_pairs  coact[u,v] · link(node[u], node[v])
         + Σ_uids   act[u] · Σ_src w_src · link(src, node[u]) / Σ_src w

where `link(a, b)` is 0 for co-located endpoints and otherwise the
measured RTT EMA plus the transfer time of `bytes_per_dispatch` at the
measured bandwidth EMA (symmetrized; `DEFAULT_RTT_S` when unmeasured —
an optimistic prior, mirroring the routing cost model's exploration
default).  The first term rewards co-locating experts that fire
together (one node touched per dispatch instead of two); the second
pulls hot experts toward nodes the dispatching clients reach cheaply.

The solver is seeded greedy local search over two neighborhoods under
per-node capacity: single-expert moves, then pair swaps (exchanging two
experts' homes — occupancy-neutral, so always capacity-safe: the escape
hatch when every profitable single move is blocked by a full node).
Deterministic for a fixed (snapshot, seed) — ties break on sorted keys,
the visit order is `random.Random(seed)`.
"""

from __future__ import annotations

import json
import random
from typing import Optional

# unmeasured links score as a plausible same-region RTT: cheap enough
# that the solver still consolidates onto unmeasured nodes when the
# co-activation term dominates, never free (free would teleport every
# expert to whichever node lacks measurements)
DEFAULT_RTT_S = 0.02
DEFAULT_MAX_MOVES = 8
DEFAULT_MAX_ROUNDS = 6


def pair_key(a: str, b: str) -> str:
    """Canonical undirected co-activation pair key ("min|max")."""
    return f"{a}|{b}" if a <= b else f"{b}|{a}"


def _num(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if f == f and f >= 0.0 else None  # NaN / negatives: garbage


def _link_entry(v) -> Optional[tuple]:
    """One links-map value -> (rtt_s, bw_bps|None); None when malformed.
    Accepts the wire list form ``[rtt, bw]`` and the parsed dict form
    ``{"rtt_s": .., "bw_bps": ..}``."""
    if isinstance(v, dict):
        v = [v.get("rtt_s"), v.get("bw_bps")]
    if not isinstance(v, (list, tuple)) or not v:
        return None
    rtt = _num(v[0])
    if rtt is None:
        return None
    bw = _num(v[1]) if len(v) > 1 else None
    return (rtt, bw if bw else None)


class _Model:
    """Normalized snapshot + incremental cost evaluation."""

    def __init__(self, snapshot: dict):
        snapshot = snapshot if isinstance(snapshot, dict) else {}
        experts = snapshot.get("experts")
        self.assign: dict = {}
        if isinstance(experts, dict):
            for uid, node in experts.items():
                if isinstance(uid, str) and isinstance(node, str) and node:
                    self.assign[uid] = node
        self.nodes = sorted(set(self.assign.values()))
        acts = snapshot.get("activations")
        self.act = {}
        if isinstance(acts, dict):
            for uid, n in acts.items():
                w = _num(n)
                if uid in self.assign and w:
                    self.act[uid] = w
        # undirected neighbor lists: uid -> [(other, weight)]
        self.neighbors: dict = {uid: [] for uid in self.assign}
        coact = snapshot.get("coact")
        if isinstance(coact, dict):
            for key, n in sorted(coact.items(), key=lambda kv: str(kv[0])):
                w = _num(n)
                if not (isinstance(key, str) and w):
                    continue
                a, _, b = key.partition("|")
                if a in self.assign and b in self.assign and a != b:
                    self.neighbors[a].append((b, w))
                    self.neighbors[b].append((a, w))
        self.bytes_per_dispatch = (
            _num(snapshot.get("bytes_per_dispatch")) or 0.0
        )
        # symmetrized measured links: (a, b) sorted -> (rtt, bw)
        self._links: dict = {}
        links = snapshot.get("links")
        if isinstance(links, dict):
            for src in sorted(links, key=str):
                dsts = links[src]
                if not (isinstance(src, str) and isinstance(dsts, dict)):
                    continue
                for dst in sorted(dsts, key=str):
                    ent = _link_entry(dsts[dst])
                    if not isinstance(dst, str) or ent is None:
                        continue
                    k = (src, dst) if src <= dst else (dst, src)
                    old = self._links.get(k)
                    # keep the cheaper measurement of the two directions
                    if old is None or ent[0] < old[0]:
                        self._links[k] = ent
        srcs = snapshot.get("sources")
        self.sources: dict = {}
        if isinstance(srcs, dict):
            for src, w in srcs.items():
                ww = _num(w)
                if isinstance(src, str) and ww:
                    self.sources[src] = ww
        self._src_total = sum(self.sources.values())
        caps = snapshot.get("capacity")
        self.capacity: dict = {}
        if isinstance(caps, dict):
            for node, c in caps.items():
                cc = _num(c)
                if isinstance(node, str) and cc is not None:
                    self.capacity[node] = int(cc)
        self.occupancy: dict = {n: 0 for n in self.nodes}
        for node in self.assign.values():
            self.occupancy[node] += 1

    def link_cost(self, a: str, b: str) -> float:
        """Seconds per dispatch hop between endpoints ``a`` and ``b``."""
        if a == b:
            return 0.0
        ent = self._links.get((a, b) if a <= b else (b, a))
        rtt, bw = ent if ent is not None else (DEFAULT_RTT_S, None)
        transfer = self.bytes_per_dispatch / bw if bw else 0.0
        return rtt + transfer

    def expert_cost(self, uid: str, node: str) -> float:
        """``uid``'s contribution to the total with ``uid`` at ``node``
        (others where self.assign puts them) — the unit of the solver's
        move deltas.  Pair terms are counted from ``uid``'s side only,
        so a move delta is exact (the other side's view shifts by the
        same amount)."""
        cost = 0.0
        for other, w in self.neighbors[uid]:
            cost += w * self.link_cost(node, self.assign[other])
        act = self.act.get(uid)
        if act and self._src_total:
            src_cost = sum(
                w * self.link_cost(src, node)
                for src, w in self.sources.items()
            )
            cost += act * src_cost / self._src_total
        return cost

    def total_cost(self) -> float:
        cost = 0.0
        for uid in sorted(self.assign):
            node = self.assign[uid]
            for other, w in self.neighbors[uid]:
                if uid < other:  # each undirected pair once
                    cost += w * self.link_cost(node, self.assign[other])
            act = self.act.get(uid)
            if act and self._src_total:
                cost += act * sum(
                    w * self.link_cost(src, node)
                    for src, w in self.sources.items()
                ) / self._src_total
        return cost


def placement_cost(snapshot: dict) -> float:
    """Score the snapshot's CURRENT assignment (pure; test surface)."""
    return _Model(snapshot).total_cost()


def solve(
    snapshot: dict,
    *,
    seed: int = 0,
    max_moves: int = DEFAULT_MAX_MOVES,
    max_rounds: int = DEFAULT_MAX_ROUNDS,
    neighborhoods: tuple = ("move", "swap"),
) -> dict:
    """Snapshot in, migration plan out.  Deterministic per (snapshot,
    seed); tolerant of malformed/empty sections (empty plan, never a
    raise).  Capacity: explicit per-node caps from the snapshot, else
    a balanced default of ceil(n_experts / n_nodes) + 1 slack.

    ``neighborhoods`` selects the local-search moves explored per round:
    ``"move"`` (single-expert relocation) and/or ``"swap"`` (pair
    exchange).  The default runs both; restricting to ``("move",)``
    exists for A/B evaluation — the macro-sim's placement stress pins
    that the swap neighborhood strictly improves clustered topologies
    where every profitable single move is capacity-blocked."""
    model = _Model(snapshot)
    uids = sorted(model.assign)
    plan = {
        "seed": int(seed),
        "cost_before": model.total_cost(),
        "cost_after": None,
        "moves": [],
    }
    if len(model.nodes) < 2 or not uids:
        plan["cost_after"] = plan["cost_before"]
        return plan
    default_cap = -(-len(uids) // len(model.nodes)) + 1
    cap = {
        n: model.capacity.get(n, default_cap) for n in model.nodes
    }
    initial = dict(model.assign)
    rng = random.Random(int(seed))
    moved: set = set()
    do_move = "move" in neighborhoods
    do_swap = "swap" in neighborhoods
    for _ in range(max_rounds):
        order = list(uids) if do_move else []
        rng.shuffle(order)
        improved = False
        for uid in order:
            # a capped plan must stay executable move-for-move: once
            # max_moves DISTINCT experts moved, only those may keep
            # improving (their latest destination wins)
            if len(moved) >= max_moves and uid not in moved:
                continue
            cur = model.assign[uid]
            here = model.expert_cost(uid, cur)
            best, best_cost = cur, here
            for node in model.nodes:
                if node == cur or model.occupancy[node] >= cap[node]:
                    continue
                cost = model.expert_cost(uid, node)
                if cost < best_cost - 1e-12:
                    best, best_cost = node, cost
            if best != cur:
                model.assign[uid] = best
                model.occupancy[cur] -= 1
                model.occupancy[best] += 1
                moved.add(uid)
                improved = True
        # pair-swap neighborhood: exchanging two experts' homes leaves
        # every node's occupancy unchanged, so a swap is capacity-safe
        # even between FULL nodes — the configurations single moves can
        # never reach under tight caps
        pairs = [
            (uids[i], uids[j])
            for i in range(len(uids))
            for j in range(i + 1, len(uids))
        ] if do_swap else []
        rng.shuffle(pairs)
        for u, v in pairs:
            nu, nv = model.assign[u], model.assign[v]
            if nu == nv:
                continue
            if len(moved | {u, v}) > max_moves:
                continue
            before = model.expert_cost(u, nu) + model.expert_cost(v, nv)
            model.assign[u], model.assign[v] = nv, nu
            after = model.expert_cost(u, nv) + model.expert_cost(v, nu)
            # when u,v co-activate their shared pair term sits in both
            # sums on both sides and links are symmetric, so it cancels
            # — the delta over everything else is exact
            if after < before - 1e-12:
                moved.update((u, v))
                improved = True
            else:
                model.assign[u], model.assign[v] = nu, nv
        if not improved:
            break
    moves = []
    for uid in sorted(moved):
        if model.assign[uid] == initial[uid]:
            continue  # round-tripped back home: not a move
        final = model.assign[uid]
        # gain: the total-cost delta of undoing this one move against
        # the FINAL assignment (exact for single moves, stable ordering)
        after = model.expert_cost(uid, final)
        model.assign[uid] = initial[uid]
        before = model.expert_cost(uid, initial[uid])
        model.assign[uid] = final
        moves.append({
            "uid": uid,
            "from": initial[uid],
            "to": final,
            "gain": round(before - after, 9),
        })
    moves.sort(key=lambda m: (-m["gain"], m["uid"]))
    plan["moves"] = moves
    plan["cost_after"] = model.total_cost()
    plan["cost_before"] = round(plan["cost_before"], 9)
    plan["cost_after"] = round(plan["cost_after"], 9)
    return plan


def plan_to_json(plan: dict) -> str:
    """Canonical serialization: sorted keys, no whitespace — the form
    the collect-gate determinism smoke compares byte-for-byte."""
    return json.dumps(plan, sort_keys=True, separators=(",", ":"))
