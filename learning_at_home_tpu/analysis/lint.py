"""lah-lint: AST rules for the repo's threading/wire invariants (ISSUE 6).

Every rule encodes an invariant this codebase has already been burned by
(or nearly so) — the rules are repo-specific on purpose:

- **R1**  no blocking calls inside ``async def`` bodies.  Every
  ``async def`` in this package runs on one of the process's event loops
  (``lah-client``, the server's serving loop, ``lah-metrics``,
  ``lah-avg``, ``lah-dht``); a blocking call there stalls every
  connection that loop serves.  Flagged: ``time.sleep``, subprocess
  spawns, file I/O (``open``, ``numpy.load``/``save``), serialization
  work (``pack_message``, ``wire_cast``, ``encode_wire_tensors``,
  ``WireTensors.prepare`` with a payload, ``EncodedBatch.encode``), and
  un-awaited ``.acquire()`` without a timeout.
- **R2**  no blocking future waits that can self-deadlock a loop: any
  ``.result()`` inside an ``async def``, ``<loop>.run(...)`` inside an
  ``async def``, and the ``run_coroutine_threadsafe(...).result()``
  chain anywhere — the exact shape of the jitted-client ``io_callback``
  hang (ROUND5 hazards; utils/asyncio_utils.BackgroundLoop.run carries
  the matching runtime guard).
- **R3**  per-pool fan-out constants (``MAX_CHUNKS_PER_PART`` and kin,
  pattern ``MAX_(CHUNKS|RPCS|PARTS|CALLS)_PER_*``) must be statically
  **below** every ``max_inflight`` default in the linted tree: held
  replies need all of a partition's chunk RPCs admitted concurrently or
  reduction deadlocks-until-timeout (averaging/averager.py).
- **R4**  a module that speaks the held-reply protocol (references
  ``avg_part``) must construct its pools with ``require_v2=True`` —
  held replies on v1's one-RPC-per-socket discipline starve the pool.
- **R5**  msgpack meta maps use string keys only: dict literals passed
  as ``meta`` to ``pack_message``/``pack_frames``/``rpc``/
  ``rpc_prepared`` (or to ``MSGPackSerializer.dumps``/``msgpack.packb``)
  with non-string literal keys.  Int keys round-trip fine through
  msgpack but broke the ``stats`` RPC consumers once already (PR 1).
- **R6**  no bare ``except:`` and no swallowed broad handler
  (``except Exception:`` / ``except BaseException:`` whose whole body is
  ``pass``) — a swarm that eats its own failures cannot be debugged.
- **R7**  a locally-defined coroutine called as a bare statement is
  never scheduled (``foo()`` instead of ``await foo()``) — it silently
  does nothing.

Spec-conformance rules (ISSUE 14) check the CODE against the repo DOCS,
so the docs stay a checked artifact instead of prose.  The docs are
located by walking up from each linted file to the first directory
containing ``docs/PROTOCOL.md``; when none is found (isolated temp
trees) R8–R10 skip rather than guess:

- **R8**  every wire op handled by a server-side dispatcher (a
  ``msg_type == "..."`` / ``msg_type in (...)`` comparison in a module
  that defines ``_dispatch`` or ``_serve``) must appear in a
  PROTOCOL.md op table (a row whose first cell is a backticked name
  under a ``| type | ... |`` header); and — when the linted set spans
  the full package (both ``frontdoor.py`` and ``connection_handler.py``
  present) — every documented op must be handled somewhere.  The
  ``hello`` handshake is documented in prose, not a table
  (``_R8_HANDSHAKE_OPS``).
- **R9**  every headline metric name (a string literal matching
  ``lah_[a-z0-9_]+``; dynamic-prefix literals ending ``_`` are skipped)
  must appear in the OBSERVABILITY.md catalog — either verbatim or as a
  family prefix (``lah_server_*``) plus the backticked suffix.
- **R10**  every ``sanitizer.lock(name)`` name must appear in the
  CONCURRENCY.md named-lock table with a declared ordering rank, and no
  lexically nested acquisition (``with a: ... with b:``) may contradict
  the ranks (ranks must strictly increase inward).
- **R11**  a function called from an ``@runs_on``-asserted hot path
  (dispatch/decode cores) that itself acquires a tracked lock must
  carry its own ``@runs_on`` assertion or a baselined suppression —
  thread-ownership claims must cover the whole reachable hot path, not
  just its entry point.

Wire-contract conformance rules (ISSUE 15) run the analysis/schema.py
extractor over the linted set and check the SENDER-side message
construction against the HANDLER-side parse sites — the version-skew
and shape-drift classes PR 11 could only document by hand.  They
evaluate only where both sides are visible (cross-module; lint the
package root for the real verdict):

- **R12**  every meta field a sender emits for an op must be parsed by
  at least one handler of that op (``meta["f"]`` or ``meta.get("f")``)
  — an unparsed field is dead weight on every frame or, worse, a
  misspelled one the handler silently defaults.
- **R13**  every field a handler hard-requires (subscript access) must
  be guaranteed on EVERY sender construction path for that op —
  including retry/fallback/legacy branches — or an old or partial
  client turns into a server-side KeyError.
- **R14**  feature-gated wire forms may only be emitted under their
  negotiation guard: the dict ``wire`` codec form needs a dominating
  ``pool.supports("codec")`` test (legacy string dtypes are exempt);
  ``pack_frames(..., rid=...)`` outside the rid-echo /
  ``mux.next_rid()`` / ``peek_header`` idioms tags frames v1 peers
  never negotiated.  This is the mixed-build skew class as a rule.
- **R15**  PROTOCOL.md's machine-read field rows (``| field | op |
  kind | type | gate |`` tables) must match the extracted handler IR
  exactly — field set and required/optional kind both directions — so
  wire/doc drift fails the gate like lock-rank drift does.

R12–R15 suppressions additionally REQUIRE a written reason (text after
the ``ignore[...]`` bracket, or explanatory lines in the surrounding
comment block): a wire-contract asymmetry without a recorded why is a
bug, not a baseline.

R3 (gateway extension, ISSUE 14): gateway/handoff bounded-concurrency
constants — ``MAX_*SESSIONS`` class/module ints, ``*DEFAULT_PREFILL_
CHUNK`` module ints, and integer-literal env fallbacks for
``LAH_GW_*MAX*/*PENDING*/*CHUNK*`` knobs — must also sit below every
``max_inflight`` default (each concurrent session/chunk holds an
in-flight RPC window on the shared mux).  Dynamic defaults (e.g.
admission's ``4 * max_slots``) are out of static reach and are checked
at runtime by the quiesce audits instead.

Suppressions: ``# lah-lint: ignore[R1]`` (or ``ignore[R1,R5]``) on the
finding's line, or on a standalone comment line directly above it,
baselines the finding; add a reason after the bracket.  Suppressed
findings still appear with ``--list-suppressed``.  The merged tree lints
clean: ``python tools/lah_lint.py learning_at_home_tpu/`` exits 0.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Optional

RULES = {
    "R1": "blocking call inside an async function (event-loop stall)",
    "R2": "blocking future wait that can self-deadlock an event loop",
    "R3": "fan-out constant not statically below the mux in-flight limit",
    "R4": "held-reply pool constructed without require_v2=True",
    "R5": "msgpack meta dict with non-string keys",
    "R6": "bare or swallowed broad exception handler",
    "R7": "coroutine called without await (never scheduled)",
    "R8": "wire op handled in code but missing from PROTOCOL.md (or vice versa)",
    "R9": "metric name not in the OBSERVABILITY.md catalog",
    "R10": "sanitizer lock name missing from CONCURRENCY.md lock table or nested against its rank",
    "R11": "lock-acquiring function on a @runs_on hot path without its own @runs_on",
    "R12": "sender-emitted meta field no handler of that op parses",
    "R13": "handler-required meta field not guaranteed on every sender path",
    "R14": "feature-gated wire form emitted without its negotiation guard",
    "R15": "PROTOCOL.md field rows out of sync with the handler schema",
}

_SUPPRESS_RE = re.compile(r"lah-lint:\s*ignore\[([A-Z0-9,\s]+)\]")

# wire-contract suppressions must carry a written reason (see docstring)
_REASON_REQUIRED = {"R12", "R13", "R14", "R15"}

# R1 canonical blocking callables (after import-alias resolution)
_BLOCKING_CALLS = {
    "time.sleep",
    "os.system", "os.popen",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "numpy.load", "numpy.save", "numpy.savez", "numpy.savez_compressed",
    "numpy.loadtxt", "numpy.savetxt",
    "socket.create_connection",
    "requests.get", "requests.post", "requests.put", "requests.request",
}
# serialization work recognized by bare name (how this repo imports them)
_SERIALIZATION_FUNCS = {"pack_message", "wire_cast", "encode_wire_tensors"}

_FANOUT_CONST_RE = re.compile(r"^MAX_(CHUNKS|RPCS|PARTS|CALLS)_PER_[A-Z_]+$")

_META_CALLS = {  # callee tail -> positional index of the meta argument
    "pack_message": 2,
    "pack_frames": 2,
    "rpc": 2,
    "rpc_prepared": 2,
}

# R3 gateway extension: bounded-concurrency constants by NAME …
_GW_BOUND_CONST_RE = re.compile(
    r"^_?(?:MAX_(?:[A-Z0-9]+_)*SESSIONS|(?:[A-Z0-9]+_)*DEFAULT_PREFILL_CHUNK)$"
)
# … and by env knob with a static integer fallback
_GW_ENV_BOUND_RE = re.compile(r"^LAH_GW_[A-Z0-9_]*(?:MAX|PENDING|CHUNK)[A-Z0-9_]*$")

# R9: headline metric literals; names ending "_" are dynamic prefixes
# (f-string families like wire_codec_payloads_total_codec_<name>)
_METRIC_LITERAL_RE = re.compile(r"^lah_[a-z0-9_]*[a-z0-9]$")

# R8: ops documented in PROTOCOL.md prose (handshake), not in an op table
_R8_HANDSHAKE_OPS = {"hello"}

_BACKTICKED_LOCK_RE = re.compile(r"`([a-z0-9_.]+)`")


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag}: {self.message}"


def _dotted(node: ast.AST, aliases: dict) -> Optional[str]:
    """Resolve a call target to a dotted name through import aliases
    (``np.load`` -> ``numpy.load``); None when the base is dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(aliases.get(node.id, node.id))
        return ".".join(reversed(parts))
    return None


def _suppressions(source: str) -> dict[int, dict]:
    """line -> {rule-id: has_written_reason} suppressed there.  A
    suppression comment covers its own line; a comment-only line covers
    the next CODE line (comment blocks pass through — the marker may sit
    anywhere in a multi-line explanation above the finding).

    ``has_written_reason`` is True when text follows the ``ignore[...]``
    bracket, or the marker sits in a comment block with other
    explanatory comment lines; rules in ``_REASON_REQUIRED`` only
    suppress with a reason."""
    out: dict[int, dict] = {}
    lines = source.splitlines()

    def _is_comment_or_blank(idx0: int) -> bool:
        s = lines[idx0].strip() if idx0 < len(lines) else ""
        return not s or s.startswith("#")

    def _is_comment(idx0: int) -> bool:
        return (
            0 <= idx0 < len(lines) and lines[idx0].strip().startswith("#")
        )

    def _put(line: int, rules: set, reasoned: bool) -> None:
        slot = out.setdefault(line, {})
        for r in rules:
            slot[r] = slot.get(r, False) or reasoned

    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            line = tok.start[0]
            reasoned = bool(tok.string[m.end():].strip(" \t:—–-#"))
            standalone = tok.line.strip().startswith("#")
            if standalone and not reasoned:
                # multi-line explanation: any OTHER text-bearing comment
                # line in the contiguous block counts as the reason
                for idx0 in range(line - 2, -1, -1):  # lines above
                    if not _is_comment(idx0):
                        break
                    if lines[idx0].strip().lstrip("#").strip():
                        reasoned = True
                        break
                idx0 = line  # lines below (0-based `line` IS the next line)
                while not reasoned and _is_comment(idx0):
                    if lines[idx0].strip().lstrip("#").strip():
                        reasoned = True
                    idx0 += 1
            _put(line, rules, reasoned)
            if standalone:
                nxt = line  # 1-based; lines[nxt] is the NEXT line (0-based)
                while nxt < len(lines) and _is_comment_or_blank(nxt):
                    nxt += 1
                _put(nxt + 1, rules, reasoned)
    except tokenize.TokenError:
        pass
    return out


class _ModuleFacts:
    """Per-module inputs to the cross-module rules R3/R4/R8–R10."""

    def __init__(self) -> None:
        self.fanout_consts: list[tuple[int, int, str, int]] = []  # line,col,name,val
        self.gw_bound_consts: list[tuple[int, int, str, int]] = []  # line,col,name,val
        self.inflight_defaults: list[tuple[int, int]] = []  # line,val
        self.mentions_avg_part = False
        self.pool_ctor_calls: list[tuple[int, int, str, bool]] = []  # line,col,name,has_require_v2
        self.defines_dispatch = False  # module defines _dispatch/_serve (R8)
        self.handled_ops: list[tuple[int, int, str]] = []  # line,col,op
        self.metric_literals: list[tuple[int, int, str]] = []  # line,col,name
        self.lock_names: list[tuple[int, int, str]] = []  # line,col,name
        self.lock_edges: list[tuple[int, int, str, str]] = []  # line,col,outer,inner


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self.facts = _ModuleFacts()
        self.aliases: dict[str, str] = {}
        self._func_stack: list[ast.AST] = []  # enclosing function defs
        self._class_stack: list[str] = []
        self._awaited: set[int] = set()
        # names of locally-defined coroutines (module funcs and methods)
        self.async_funcs: set[str] = set()
        self.async_methods: dict[str, set] = {}

    # -- helpers ----------------------------------------------------------

    def _add(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, rule, msg)
        )

    def _in_async(self) -> bool:
        return bool(self._func_stack) and isinstance(
            self._func_stack[-1], ast.AsyncFunctionDef
        )

    # -- structure --------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = f"{node.module}.{a.name}" if node.module else a.name
        self.generic_visit(node)

    def _collect_defaults(self, node) -> None:
        # align trailing defaults with trailing args (positional part)
        pos_args = node.args.args
        pos_defaults = node.args.defaults
        pairs = list(zip(pos_args[len(pos_args) - len(pos_defaults):], pos_defaults))
        pairs += [
            (a, d)
            for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults)
            if d is not None
        ]
        for arg, default in pairs:
            if (
                arg.arg == "max_inflight"
                and isinstance(default, ast.Constant)
                and isinstance(default.value, int)
            ):
                self.facts.inflight_defaults.append((node.lineno, default.value))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        if node.name in ("_dispatch", "_serve"):
            self.facts.defines_dispatch = True
        self._collect_defaults(node)
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        # async_funcs / async_methods are filled by lint_paths' pre-pass
        # (call sites may lexically precede the definitions they target)
        if node.name in ("_dispatch", "_serve"):
            self.facts.defines_dispatch = True
        self._collect_defaults(node)
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_fanout_const(node.targets[0] if node.targets else None, node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_fanout_const(node.target, node.value, node)
        self.generic_visit(node)

    def _check_fanout_const(self, target, value, node) -> None:
        if (
            isinstance(target, ast.Name)
            and isinstance(value, ast.Constant)
            and isinstance(value.value, int)
        ):
            if _FANOUT_CONST_RE.match(target.id):
                self.facts.fanout_consts.append(
                    (node.lineno, node.col_offset, target.id, value.value)
                )
            elif _GW_BOUND_CONST_RE.match(target.id):
                self.facts.gw_bound_consts.append(
                    (node.lineno, node.col_offset, target.id, value.value)
                )

    def visit_Constant(self, node: ast.Constant) -> None:
        if node.value == "avg_part":
            self.facts.mentions_avg_part = True
        if (
            isinstance(node.value, str)
            and _METRIC_LITERAL_RE.match(node.value)
        ):
            self.facts.metric_literals.append(
                (node.lineno, node.col_offset, node.value)
            )
        self.generic_visit(node)

    # -- R8 facts: handled wire ops ---------------------------------------

    def visit_Compare(self, node: ast.Compare) -> None:
        left = node.left
        if isinstance(left, ast.Name) and left.id == "msg_type":
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.Eq, ast.NotEq)) and (
                    isinstance(comp, ast.Constant)
                    and isinstance(comp.value, str)
                ):
                    self.facts.handled_ops.append(
                        (comp.lineno, comp.col_offset, comp.value)
                    )
                elif isinstance(op, (ast.In, ast.NotIn)) and isinstance(
                    comp, (ast.Tuple, ast.List, ast.Set)
                ):
                    for el in comp.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            self.facts.handled_ops.append(
                                (el.lineno, el.col_offset, el.value)
                            )
        self.generic_visit(node)

    # -- R6 ---------------------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add(node, "R6", "bare `except:` hides every failure mode")
        else:
            names = []
            t = node.type
            for sub in t.elts if isinstance(t, ast.Tuple) else [t]:
                if isinstance(sub, ast.Name):
                    names.append(sub.id)
            if (
                any(n in ("Exception", "BaseException") for n in names)
                and len(node.body) == 1
                and isinstance(node.body[0], ast.Pass)
            ):
                self._add(
                    node, "R6",
                    "broad exception swallowed (`except "
                    f"{'/'.join(names)}: pass`) — log it or narrow the type",
                )
        self.generic_visit(node)

    # -- await bookkeeping ------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        self._awaited.add(id(node.value))
        self.generic_visit(node)

    # -- R7 ---------------------------------------------------------------

    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in self.async_funcs:
                self._add(
                    call, "R7",
                    f"coroutine {fn.id}() called without await — it is "
                    "never scheduled",
                )
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "self"
                and self._class_stack
                and fn.attr in self.async_methods.get(self._class_stack[-1], ())
            ):
                self._add(
                    call, "R7",
                    f"coroutine self.{fn.attr}() called without await — it "
                    "is never scheduled",
                )
        self.generic_visit(node)

    # -- calls: R1, R2, R4, R5 -------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func, self.aliases)
        tail = dotted.split(".")[-1] if dotted else None
        awaited = id(node) in self._awaited

        # R10 facts: named tracked locks
        lock_name = _sanitizer_lock_name(node, self.aliases)
        if lock_name is not None:
            self.facts.lock_names.append(
                (node.lineno, node.col_offset, lock_name)
            )

        # R3 gateway facts: integer-literal env fallbacks for bounded-
        # concurrency knobs (dynamic defaults are out of static reach)
        if dotted == "os.environ.get" and len(node.args) >= 2:
            key, default = node.args[0], node.args[1]
            if (
                isinstance(key, ast.Constant)
                and isinstance(key.value, str)
                and _GW_ENV_BOUND_RE.match(key.value)
                and isinstance(default, ast.Constant)
                and isinstance(default.value, str)
                and default.value.isdigit()
            ):
                self.facts.gw_bound_consts.append(
                    (node.lineno, node.col_offset, key.value,
                     int(default.value))
                )

        # R4 facts: pool constructions in held-reply modules
        if tail in ("PoolRegistry", "ConnectionPool"):
            has_req = any(
                kw.arg == "require_v2"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            self.facts.pool_ctor_calls.append(
                (node.lineno, node.col_offset, tail, has_req)
            )

        # R5: meta dict literals with non-string keys
        meta_arg = None
        if tail in _META_CALLS:
            pos = _META_CALLS[tail]
            if len(node.args) > pos:
                meta_arg = node.args[pos]
            for kw in node.keywords:
                if kw.arg == "meta":
                    meta_arg = kw.value
        elif tail in ("dumps", "packb") and dotted and (
            dotted.endswith("MSGPackSerializer.dumps")
            or dotted.endswith("msgpack.packb")
        ):
            if node.args:
                meta_arg = node.args[0]
        if meta_arg is not None:
            self._check_msgpack_keys(meta_arg)

        if self._in_async() and not awaited:
            # R2: blocking waits on the loop
            if isinstance(node.func, ast.Attribute) and node.func.attr == "result":
                self._add(
                    node, "R2",
                    "`.result()` inside an async function blocks the event "
                    "loop — and self-deadlocks when the future needs THIS "
                    "loop; await instead",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "run"
                and "loop"
                in (dotted or ast.unparse(node.func.value)).lower()
            ):
                recv = dotted or f"{ast.unparse(node.func.value)}.run"
                self._add(
                    node, "R2",
                    f"`{recv}(...)` inside an async function blocks this "
                    "loop on another loop's result — the io_callback "
                    "self-deadlock shape; await the coroutine or submit()",
                )
            # R1: blocking calls
            elif dotted in _BLOCKING_CALLS or tail in _SERIALIZATION_FUNCS:
                self._add(
                    node, "R1",
                    f"blocking call `{dotted or tail}` inside an async "
                    "function — move it to a host thread or executor",
                )
            elif isinstance(node.func, ast.Name) and node.func.id == "open":
                self._add(
                    node, "R1",
                    "file I/O (`open`) inside an async function — use an "
                    "executor",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "prepare"
                and dotted is not None
                and dotted.endswith("WireTensors.prepare")
                and node.args
            ):
                self._add(
                    node, "R1",
                    "WireTensors.prepare(tensors) inside an async function "
                    "— hot-path payloads must be prepared off-loop "
                    "(rpc_prepared contract)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "encode"
                and dotted is not None
                and dotted.endswith("EncodedBatch.encode")
            ):
                self._add(
                    node, "R1",
                    "EncodedBatch.encode inside an async function — "
                    "quantize is O(bytes) work, encode off-loop",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and not any(kw.arg in ("timeout", "blocking") for kw in node.keywords)
                and not node.args
            ):
                self._add(
                    node, "R1",
                    "un-awaited `.acquire()` without a timeout inside an "
                    "async function — a threading lock here parks the loop",
                )

        # R2 (anywhere): run_coroutine_threadsafe(...).result() chain
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "result"
            and isinstance(node.func.value, ast.Call)
        ):
            inner = _dotted(node.func.value.func, self.aliases)
            if inner and inner.endswith("run_coroutine_threadsafe"):
                self._add(
                    node, "R2",
                    "run_coroutine_threadsafe(...).result() — guaranteed "
                    "self-deadlock when called on the target loop's own "
                    "thread; use BackgroundLoop.run (it carries the "
                    "thread-identity guard)",
                )
        self.generic_visit(node)

    def _check_msgpack_keys(self, node: ast.AST) -> None:
        if not isinstance(node, ast.Dict):
            return
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and not isinstance(k.value, str):
                self._add(
                    k, "R5",
                    f"msgpack meta key {k.value!r} is "
                    f"{type(k.value).__name__}, not str — stats/meta maps "
                    "must use string keys (PR 1 contract)",
                )
            if isinstance(v, ast.Dict):
                self._check_msgpack_keys(v)


def _sanitizer_lock_name(node: ast.AST, aliases: dict) -> Optional[str]:
    """The name argument of a ``sanitizer.lock("...")`` call (any import
    spelling that resolves to it), else None."""
    if not isinstance(node, ast.Call):
        return None
    dotted = _dotted(node.func, aliases)
    if not dotted or not dotted.endswith("sanitizer.lock"):
        return None
    if (
        node.args
        and isinstance(node.args[0], ast.Constant)
        and isinstance(node.args[0].value, str)
    ):
        return node.args[0].value
    return None


# ---------------------------------------------------------------------------
# R8–R10 doc corpus: parsed once per docs/ directory
# ---------------------------------------------------------------------------

_DOC_CACHE: dict[str, dict] = {}


def _find_docs_dir(path: str) -> Optional[str]:
    """Walk up from a linted file to the first dir holding docs/PROTOCOL.md."""
    d = os.path.dirname(os.path.abspath(path))
    while True:
        cand = os.path.join(d, "docs")
        if os.path.isfile(os.path.join(cand, "PROTOCOL.md")):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _doc_corpus(docs_dir: str) -> dict:
    cached = _DOC_CACHE.get(docs_dir)
    if cached is not None:
        return cached
    corpus = {
        "protocol_path": os.path.join(docs_dir, "PROTOCOL.md"),
        "concurrency_path": os.path.join(docs_dir, "CONCURRENCY.md"),
        "ops": {},  # op name -> PROTOCOL.md line of its table row
        "fields": {},  # op key -> {field: {kind, types, gate, line}} (R15)
        "metric_tokens": set(),
        "metric_families": [],
        "have_observability": False,
        "lock_ranks": {},  # lock name -> int rank
        "have_concurrency": False,
    }
    # PROTOCOL.md op tables: rows whose first cell is a backticked name,
    # under a table header whose first cell is "type".  Field tables
    # (R15) use a "field" first header cell: | field | op | kind | type
    # | gate |; the op cell holds `op`, `op@family` or `*@family`, and a
    # literal (none) field cell declares an op with no op-specific
    # fields (registers the op key for coverage).
    try:
        with open(corpus["protocol_path"], encoding="utf-8") as fh:
            in_op_table = False
            in_field_table = False
            for lineno, raw in enumerate(fh, 1):
                s = raw.strip()
                if not s.startswith("|"):
                    in_op_table = False
                    in_field_table = False
                    continue
                cells = [c.strip() for c in s.strip("|").split("|")]
                if cells and cells[0] == "type":
                    in_op_table = True
                    continue
                if cells and cells[0] == "field":
                    in_field_table = True
                    continue
                if in_op_table and cells:
                    m = re.fullmatch(r"`([a-z][a-z0-9_]*)`", cells[0])
                    if m:
                        corpus["ops"].setdefault(m.group(1), lineno)
                if in_field_table and len(cells) >= 3:
                    mo = re.fullmatch(
                        r"`([a-z_*][a-z0-9_]*(?:@[a-z_]+)?)`", cells[1]
                    )
                    if mo is None:
                        continue
                    opkey = mo.group(1)
                    rows = corpus["fields"].setdefault(opkey, {})
                    mf = re.fullmatch(r"`([a-z_][a-z0-9_]*)`", cells[0])
                    if mf is None:
                        continue  # (none) / separator: op key registered
                    kind = (
                        "req" if cells[2].lower().startswith("req")
                        else "opt"
                    )
                    types = tuple(
                        t for t in re.findall(
                            r"[a-z]+", cells[3].split("[")[0]
                        )
                    ) if len(cells) > 3 else ()
                    gate = None
                    if len(cells) > 4:
                        mg = re.fullmatch(r"`([a-z]+)`", cells[4])
                        if mg:
                            gate = mg.group(1)
                    rows[mf.group(1)] = {
                        "kind": kind, "types": types, "gate": gate,
                        "line": lineno,
                    }
    except OSError:
        pass
    # OBSERVABILITY.md: every backticked token (label suffixes like
    # `{type=}` stripped); `lah_x_*` tokens declare family prefixes
    try:
        with open(os.path.join(docs_dir, "OBSERVABILITY.md"),
                  encoding="utf-8") as fh:
            text = fh.read()
        corpus["have_observability"] = True
        toks = {
            t.split("{")[0].strip()
            for t in re.findall(r"`([^`\n]+)`", text)
        }
        corpus["metric_tokens"] = toks
        corpus["metric_families"] = sorted(
            t[:-1] for t in toks if t.startswith("lah_") and t.endswith("_*")
        )
    except OSError:
        pass
    # CONCURRENCY.md lock table: | `name` | rank | ... | under the
    # "Lock node" header
    try:
        with open(corpus["concurrency_path"], encoding="utf-8") as fh:
            in_lock_table = False
            for raw in fh:
                s = raw.strip()
                if not s.startswith("|"):
                    in_lock_table = False
                    continue
                cells = [c.strip() for c in s.strip("|").split("|")]
                if cells and cells[0] == "Lock node":
                    in_lock_table = True
                    continue
                if in_lock_table and len(cells) >= 2:
                    try:
                        rank = int(cells[1])
                    except ValueError:
                        continue  # separator row
                    for nm in _BACKTICKED_LOCK_RE.findall(cells[0]):
                        corpus["lock_ranks"][nm] = rank
        corpus["have_concurrency"] = True
    except OSError:
        pass
    _DOC_CACHE[docs_dir] = corpus
    return corpus


def _metric_documented(name: str, corpus: dict) -> bool:
    toks = corpus["metric_tokens"]
    if name in toks:
        return True
    return any(
        name.startswith(fam) and name[len(fam):] in toks
        for fam in corpus["metric_families"]
    )


# ---------------------------------------------------------------------------
# R10/R11 structural pass: lock aliases, lexical nesting, hot-path reach
# ---------------------------------------------------------------------------


def _lock_alias_map(tree: ast.AST, aliases: dict) -> dict:
    """('attr', class, attr)/('mod', None, name) -> lock name, from
    ``self.x = sanitizer.lock("n")`` / ``x = sanitizer.lock("n")``."""
    amap: dict = {}

    def scan(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                scan(child, child.name)
                continue
            if isinstance(child, ast.Assign):
                nm = _sanitizer_lock_name(child.value, aliases)
                if nm:
                    for t in child.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            amap[("attr", cls, t.attr)] = nm
                        elif isinstance(t, ast.Name):
                            amap[("mod", None, t.id)] = nm
            scan(child, cls)

    scan(tree, None)
    return amap


def _resolve_lock_expr(
    expr: ast.AST, amap: dict, aliases: dict, cls: Optional[str]
) -> Optional[str]:
    nm = _sanitizer_lock_name(expr, aliases)
    if nm:
        return nm
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return amap.get(("attr", cls, expr.attr))
    if isinstance(expr, ast.Name):
        return amap.get(("mod", None, expr.id))
    return None


def _collect_lock_edges(
    tree: ast.AST, amap: dict, aliases: dict
) -> list[tuple[int, int, str, str]]:
    """(line, col, outer, inner) for every lexically nested acquisition
    of two resolvable tracked locks."""
    edges: list[tuple[int, int, str, str]] = []

    def walk(node: ast.AST, held: list, cls: Optional[str]) -> None:
        if isinstance(node, ast.ClassDef):
            cls = node.name
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            held = []  # a nested def does not run under the enclosing with
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                nm = _resolve_lock_expr(item.context_expr, amap, aliases, cls)
                if nm:
                    for h in held:
                        edges.append(
                            (node.lineno, node.col_offset, h, nm)
                        )
                    held = held + [nm]
            for b in node.body:
                walk(b, held, cls)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, held, cls)

    walk(tree, [], None)
    return edges


def _r11_findings(
    path: str, tree: ast.AST, amap: dict, aliases: dict
) -> list[Finding]:
    """Functions called from an @runs_on-decorated function (direct
    ``self.m()`` / bare same-module calls) that acquire a tracked lock
    but carry no @runs_on of their own."""
    funcs: dict = {}  # (class, name) -> (def node, decorated?)

    def collect(node: ast.AST, cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                collect(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                decorated = any(
                    "runs_on" in ast.unparse(d) for d in child.decorator_list
                )
                funcs[(cls, child.name)] = (child, decorated)
                collect(child, cls)
            else:
                collect(child, cls)

    collect(tree, None)

    def acquires(node: ast.AST, cls: Optional[str]) -> Optional[str]:
        for sub in ast.walk(node):
            if isinstance(sub, (ast.With, ast.AsyncWith)):
                for item in sub.items:
                    nm = _resolve_lock_expr(
                        item.context_expr, amap, aliases, cls
                    )
                    if nm:
                        return nm
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "acquire"
            ):
                nm = _resolve_lock_expr(sub.func.value, amap, aliases, cls)
                if nm:
                    return nm
        return None

    findings: list[Finding] = []
    flagged: set = set()
    for (cls, name), (node, decorated) in funcs.items():
        if not decorated:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            if (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "self"
            ):
                target = (cls, fn.attr)
            elif isinstance(fn, ast.Name):
                target = (None, fn.id)
            else:
                continue
            if target not in funcs or target in flagged:
                continue
            tnode, tdecorated = funcs[target]
            if tdecorated:
                continue
            lock_nm = acquires(tnode, target[0])
            if lock_nm is None:
                continue
            flagged.add(target)
            findings.append(
                Finding(
                    path, tnode.lineno, tnode.col_offset, "R11",
                    f"`{target[1]}` acquires tracked lock `{lock_nm}` and "
                    f"is called from @runs_on hot path `{name}` but carries "
                    "no @runs_on assertion — thread ownership must cover "
                    "the whole reachable hot path",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# R12–R15: wire-contract conformance (analysis/schema.py IR)
# ---------------------------------------------------------------------------


def _doc_rows_for(corpus: dict, op: str, family: str) -> Optional[dict]:
    """Merged field rows for an op: family-common ``*@family`` rows
    overlaid by the per-op rows (qualified ``op@family`` wins over the
    bare op key).  None when the docs carry no rows for the op at all —
    including no family rows and no ``(none)`` marker."""
    fields = corpus.get("fields", {})
    per_op = fields.get(f"{op}@{family}")
    if per_op is None:
        per_op = fields.get(op)
    fam = fields.get(f"*@{family}")
    if per_op is None and fam is None:
        return None
    merged = dict(fam or {})
    merged.update(per_op or {})
    return merged


def _wire_conformance_findings(py_files: list[str]) -> list[Finding]:
    from . import schema as _schema

    ir = _schema.extract(py_files)
    findings: list[Finding] = []
    if not ir.handlers and not ir.gate_candidates:
        return findings

    # R12: every sender-emitted field must be parsed by some handler of
    # the op (evaluated only when a handler of the op is in the set)
    for site in ir.senders:
        handlers = [h for h in ir.handlers if site.op in h.ops]
        if not handlers:
            continue
        accepted: set = set()
        for h in handlers:
            accepted.update(h.accepted(site.op))
        for name, fld in sorted(site.fields.items()):
            if name not in accepted:
                findings.append(
                    Finding(
                        site.path, fld.line or site.line, 0, "R12",
                        f"sender emits meta field `{name}` for op "
                        f"`{site.op}` but no handler of that op parses "
                        f"it (accepted: {sorted(accepted)})",
                    )
                )

    # R13: handler-required fields must be guaranteed on every sender
    # construction path.  For multi-family ops only fields EVERY family
    # requires are checked (a family-specific requirement cannot bind
    # senders addressing the other family).
    for op in sorted(ir.handled_ops()):
        required: Optional[set] = None
        for h in ir.handlers:
            if op not in h.ops:
                continue
            req = {
                name for name, use in h.accepted(op).items()
                if use.kind == "req"
            }
            required = req if required is None else (required & req)
        if not required:
            continue
        for site in ir.sender_sites(op):
            for name in sorted(required):
                fld = site.fields.get(name)
                if fld is None or fld.kind != "req":
                    how = (
                        "only conditionally" if fld is not None
                        else "never"
                    )
                    findings.append(
                        Finding(
                            site.path, site.line, 0, "R13",
                            f"handler of op `{op}` hard-requires meta "
                            f"field `{name}` (subscript access) but this "
                            f"construction path sets it {how}",
                        )
                    )

    # R14: ungated feature-dependent wire forms found by the extractor
    for cand in ir.gate_candidates:
        findings.append(
            Finding(
                cand.path, cand.line, cand.col, "R14",
                f"feature-gated `{cand.what}` form: {cand.detail}",
            )
        )

    # R15: handler IR vs the PROTOCOL.md machine-read field rows.  Ops
    # absent from the op tables entirely are R8's finding, not ours;
    # docs without any field tables leave the rule inert (pre-ISSUE-15
    # corpora).
    for h in ir.handlers:
        docs_dir = _find_docs_dir(h.path)
        if docs_dir is None:
            continue
        corpus = _doc_corpus(docs_dir)
        if not corpus.get("fields"):
            continue
        for op in sorted(h.ops):
            if op in _R8_HANDSHAKE_OPS or op not in corpus["ops"]:
                continue
            doc_fields = _doc_rows_for(corpus, op, h.family)
            op_line = h.op_lines.get(op, 0)
            if doc_fields is None:
                findings.append(
                    Finding(
                        h.path, op_line, 0, "R15",
                        f"op `{op}` ({h.family}) has no machine-read "
                        "field rows in PROTOCOL.md — add a | field | op "
                        "| kind | ... | row per field (or a (none) row)",
                    )
                )
                continue
            code_fields = h.accepted(op)
            for name, use in sorted(code_fields.items()):
                if name not in doc_fields:
                    findings.append(
                        Finding(
                            h.path, use.line or op_line, 0, "R15",
                            f"op `{op}` ({h.family}) parses meta field "
                            f"`{name}` but PROTOCOL.md has no field row "
                            "for it",
                        )
                    )
            sites = ir.sender_sites(op)
            for name, row in sorted(doc_fields.items()):
                use = code_fields.get(name)
                if use is None:
                    findings.append(
                        Finding(
                            h.path, op_line, 0, "R15",
                            f"PROTOCOL.md documents field `{name}` for "
                            f"op `{op}` ({h.family}) but the handler "
                            "never parses it (stale row or missing "
                            "parse)",
                        )
                    )
                    continue
                if use.kind == "req" and row["kind"] != "req":
                    findings.append(
                        Finding(
                            h.path, use.line or op_line, 0, "R15",
                            f"op `{op}` ({h.family}): handler "
                            f"hard-requires `{name}` but PROTOCOL.md "
                            "documents it optional",
                        )
                    )
                elif row["kind"] == "req" and use.kind != "req":
                    # a doc-required field the handler reads softly is
                    # honored when every in-set sender guarantees it (the
                    # handler validates dynamically); senderless ops
                    # trust the handler's own validation
                    if sites and any(
                        name not in s.fields
                        or s.fields[name].kind != "req"
                        for s in sites
                    ):
                        findings.append(
                            Finding(
                                h.path, use.line or op_line, 0, "R15",
                                f"op `{op}` ({h.family}): PROTOCOL.md "
                                f"documents `{name}` required but some "
                                "sender path does not guarantee it "
                                "(doc row or sender is wrong)",
                            )
                        )
    return findings


def _iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(
                    os.path.join(root, f) for f in files if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """Lint files/directories; returns ALL findings with ``suppressed``
    set for baselined ones.  Cross-module rules (R3, R4) are evaluated
    over the whole linted set, so lint the package root for the real
    verdict."""
    findings: list[Finding] = []
    all_facts: list[tuple[str, _ModuleFacts]] = []
    suppress_by_path: dict[str, dict[int, dict]] = {}
    py_files = _iter_py_files(paths)
    for path in py_files:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(
                Finding(path, getattr(e, "lineno", 0) or 0, 0, "PARSE",
                        f"could not parse: {e}")
            )
            continue
        suppress_by_path[path] = _suppressions(source)
        # pre-pass: async def names must exist before visiting call sites.
        # Scoped precisely — MODULE-LEVEL async defs only for bare-name
        # calls, and per-class direct methods for self.<m>() calls — so a
        # sync module function sharing a name with some class's coroutine
        # is never false-flagged (R7 findings fail the gate; precision
        # beats recall here)
        visitor = _Visitor(path)
        for node in tree.body:
            if isinstance(node, ast.AsyncFunctionDef):
                visitor.async_funcs.add(node.name)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.AsyncFunctionDef):
                        visitor.async_methods.setdefault(
                            node.name, set()
                        ).add(sub.name)
        visitor.visit(tree)
        # structural pass: lock aliases feed R10 nesting edges + R11
        amap = _lock_alias_map(tree, visitor.aliases)
        visitor.facts.lock_edges = _collect_lock_edges(
            tree, amap, visitor.aliases
        )
        findings.extend(_r11_findings(path, tree, amap, visitor.aliases))
        findings.extend(visitor.findings)
        all_facts.append((path, visitor.facts))

    # R3: every fan-out constant must sit below every max_inflight default
    inflight = [
        (path, line, val)
        for path, facts in all_facts
        for line, val in facts.inflight_defaults
    ]
    if inflight:
        limit = min(v for _, _, v in inflight)
        where = next((f"{p}:{ln}" for p, ln, v in inflight if v == limit), "?")
        for path, facts in all_facts:
            for line, col, name, val in facts.fanout_consts:
                if val >= limit:
                    findings.append(
                        Finding(
                            path, line, col, "R3",
                            f"{name}={val} must be < the mux in-flight "
                            f"limit {limit} ({where}): held replies need "
                            "every chunk RPC admitted concurrently",
                        )
                    )

    # R3 gateway extension: bounded-concurrency gateway/handoff constants
    # join the same comparison (each concurrent session/chunk holds an
    # in-flight RPC window on the shared mux)
    if inflight:
        limit = min(v for _, _, v in inflight)
        where = next((f"{p}:{ln}" for p, ln, v in inflight if v == limit), "?")
        for path, facts in all_facts:
            for line, col, name, val in facts.gw_bound_consts:
                if val >= limit:
                    findings.append(
                        Finding(
                            path, line, col, "R3",
                            f"{name}={val} must be < the mux in-flight "
                            f"limit {limit} ({where}): every concurrent "
                            "gateway session/chunk holds an in-flight RPC "
                            "window",
                        )
                    )

    # R4: held-reply modules must pin require_v2=True on their pools
    for path, facts in all_facts:
        if not facts.mentions_avg_part:
            continue
        for line, col, name, has_req in facts.pool_ctor_calls:
            if not has_req:
                findings.append(
                    Finding(
                        path, line, col, "R4",
                        f"{name}(...) in a held-reply (avg_part) module "
                        "without require_v2=True — held replies starve "
                        "v1's one-RPC-per-socket pool",
                    )
                )

    # R8–R10: spec conformance against the repo docs.  Docs are located
    # per linted file (so the corpus under tests/ resolves the real
    # repo docs); files with no docs in reach skip these rules.
    handled_ops_all: set = set(_R8_HANDSHAKE_OPS)
    for _, facts in all_facts:
        handled_ops_all.update(op for _, _, op in facts.handled_ops)
    basenames = {os.path.basename(p) for p, _ in all_facts}
    reverse_r8_docs: Optional[dict] = None
    for path, facts in all_facts:
        docs_dir = _find_docs_dir(path)
        if docs_dir is None:
            continue
        corpus = _doc_corpus(docs_dir)
        if facts.defines_dispatch and corpus["ops"]:
            if os.path.basename(path) in (
                "frontdoor.py", "connection_handler.py"
            ):
                reverse_r8_docs = corpus
            for line, col, op in facts.handled_ops:
                if op not in corpus["ops"] and op not in _R8_HANDSHAKE_OPS:
                    findings.append(
                        Finding(
                            path, line, col, "R8",
                            f"handled op `{op}` is not documented in any "
                            f"PROTOCOL.md op table "
                            f"({corpus['protocol_path']})",
                        )
                    )
        if corpus["have_observability"]:
            for line, col, name in facts.metric_literals:
                if not _metric_documented(name, corpus):
                    findings.append(
                        Finding(
                            path, line, col, "R9",
                            f"metric `{name}` is not in the "
                            "OBSERVABILITY.md catalog (add it verbatim or "
                            "as family prefix + suffix)",
                        )
                    )
        if corpus["have_concurrency"]:
            ranks = corpus["lock_ranks"]
            for line, col, name in facts.lock_names:
                if name not in ranks:
                    findings.append(
                        Finding(
                            path, line, col, "R10",
                            f"lock `{name}` has no row/rank in the "
                            "CONCURRENCY.md named-lock table",
                        )
                    )
            for line, col, outer, inner in facts.lock_edges:
                ra, rb = ranks.get(outer), ranks.get(inner)
                if ra is not None and rb is not None and ra >= rb:
                    findings.append(
                        Finding(
                            path, line, col, "R10",
                            f"lock `{inner}` (rank {rb}) acquired while "
                            f"holding `{outer}` (rank {ra}) — ranks must "
                            "strictly increase inward "
                            "(docs/CONCURRENCY.md lock table)",
                        )
                    )
    # R8 reverse direction: only meaningful when the linted set spans the
    # full package (both dispatcher families present)
    if (
        reverse_r8_docs is not None
        and {"frontdoor.py", "connection_handler.py"} <= basenames
    ):
        for op, doc_line in sorted(reverse_r8_docs["ops"].items()):
            if op not in handled_ops_all:
                findings.append(
                    Finding(
                        reverse_r8_docs["protocol_path"], doc_line, 0, "R8",
                        f"documented op `{op}` has no handler in the "
                        "linted set (stale PROTOCOL.md row or missing "
                        "dispatch arm)",
                    )
                )

    # R12–R15: wire-contract conformance over the schema IR (both sides
    # must be in the linted set; doc-less trees skip R15 like R8–R10)
    findings.extend(_wire_conformance_findings(py_files))

    # apply suppressions (R12–R15 demand a written reason — see
    # _suppressions; an unreasoned marker does not baseline them)
    for f in findings:
        rules = suppress_by_path.get(f.path, {}).get(f.line, {})
        if f.rule in rules:
            if f.rule in _REASON_REQUIRED and not rules[f.rule]:
                f.message += (
                    " [suppression present but carries no written "
                    "reason — wire-contract baselines must say why]"
                )
            else:
                f.suppressed = True
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def format_findings(findings: list[Finding], show_suppressed: bool = False) -> str:
    lines = [
        f.render()
        for f in findings
        if show_suppressed or not f.suppressed
    ]
    active = sum(1 for f in findings if not f.suppressed)
    sup = len(findings) - active
    lines.append(
        f"lah-lint: {active} finding(s), {sup} suppressed"
    )
    return "\n".join(lines)
