"""lah-lint: AST rules for the repo's threading/wire invariants (ISSUE 6).

Every rule encodes an invariant this codebase has already been burned by
(or nearly so) — the rules are repo-specific on purpose:

- **R1**  no blocking calls inside ``async def`` bodies.  Every
  ``async def`` in this package runs on one of the process's event loops
  (``lah-client``, the server's serving loop, ``lah-metrics``,
  ``lah-avg``, ``lah-dht``); a blocking call there stalls every
  connection that loop serves.  Flagged: ``time.sleep``, subprocess
  spawns, file I/O (``open``, ``numpy.load``/``save``), serialization
  work (``pack_message``, ``wire_cast``, ``encode_wire_tensors``,
  ``WireTensors.prepare`` with a payload, ``EncodedBatch.encode``), and
  un-awaited ``.acquire()`` without a timeout.
- **R2**  no blocking future waits that can self-deadlock a loop: any
  ``.result()`` inside an ``async def``, ``<loop>.run(...)`` inside an
  ``async def``, and the ``run_coroutine_threadsafe(...).result()``
  chain anywhere — the exact shape of the jitted-client ``io_callback``
  hang (ROUND5 hazards; utils/asyncio_utils.BackgroundLoop.run carries
  the matching runtime guard).
- **R3**  per-pool fan-out constants (``MAX_CHUNKS_PER_PART`` and kin,
  pattern ``MAX_(CHUNKS|RPCS|PARTS|CALLS)_PER_*``) must be statically
  **below** every ``max_inflight`` default in the linted tree: held
  replies need all of a partition's chunk RPCs admitted concurrently or
  reduction deadlocks-until-timeout (averaging/averager.py).
- **R4**  a module that speaks the held-reply protocol (references
  ``avg_part``) must construct its pools with ``require_v2=True`` —
  held replies on v1's one-RPC-per-socket discipline starve the pool.
- **R5**  msgpack meta maps use string keys only: dict literals passed
  as ``meta`` to ``pack_message``/``pack_frames``/``rpc``/
  ``rpc_prepared`` (or to ``MSGPackSerializer.dumps``/``msgpack.packb``)
  with non-string literal keys.  Int keys round-trip fine through
  msgpack but broke the ``stats`` RPC consumers once already (PR 1).
- **R6**  no bare ``except:`` and no swallowed broad handler
  (``except Exception:`` / ``except BaseException:`` whose whole body is
  ``pass``) — a swarm that eats its own failures cannot be debugged.
- **R7**  a locally-defined coroutine called as a bare statement is
  never scheduled (``foo()`` instead of ``await foo()``) — it silently
  does nothing.

Suppressions: ``# lah-lint: ignore[R1]`` (or ``ignore[R1,R5]``) on the
finding's line, or on a standalone comment line directly above it,
baselines the finding; add a reason after the bracket.  Suppressed
findings still appear with ``--list-suppressed``.  The merged tree lints
clean: ``python tools/lah_lint.py learning_at_home_tpu/`` exits 0.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Iterable, Optional

RULES = {
    "R1": "blocking call inside an async function (event-loop stall)",
    "R2": "blocking future wait that can self-deadlock an event loop",
    "R3": "fan-out constant not statically below the mux in-flight limit",
    "R4": "held-reply pool constructed without require_v2=True",
    "R5": "msgpack meta dict with non-string keys",
    "R6": "bare or swallowed broad exception handler",
    "R7": "coroutine called without await (never scheduled)",
}

_SUPPRESS_RE = re.compile(r"lah-lint:\s*ignore\[([A-Z0-9,\s]+)\]")

# R1 canonical blocking callables (after import-alias resolution)
_BLOCKING_CALLS = {
    "time.sleep",
    "os.system", "os.popen",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output", "subprocess.Popen",
    "numpy.load", "numpy.save", "numpy.savez", "numpy.savez_compressed",
    "numpy.loadtxt", "numpy.savetxt",
    "socket.create_connection",
    "requests.get", "requests.post", "requests.put", "requests.request",
}
# serialization work recognized by bare name (how this repo imports them)
_SERIALIZATION_FUNCS = {"pack_message", "wire_cast", "encode_wire_tensors"}

_FANOUT_CONST_RE = re.compile(r"^MAX_(CHUNKS|RPCS|PARTS|CALLS)_PER_[A-Z_]+$")

_META_CALLS = {  # callee tail -> positional index of the meta argument
    "pack_message": 2,
    "pack_frames": 2,
    "rpc": 2,
    "rpc_prepared": 2,
}


@dataclasses.dataclass
class Finding:
    path: str
    line: int
    col: int
    rule: str
    message: str
    suppressed: bool = False

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag}: {self.message}"


def _dotted(node: ast.AST, aliases: dict) -> Optional[str]:
    """Resolve a call target to a dotted name through import aliases
    (``np.load`` -> ``numpy.load``); None when the base is dynamic."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(aliases.get(node.id, node.id))
        return ".".join(reversed(parts))
    return None


def _suppressions(source: str) -> dict[int, set]:
    """line -> rule-ids suppressed there.  A suppression comment covers
    its own line; a comment-only line covers the next CODE line (comment
    blocks pass through — the marker may sit anywhere in a multi-line
    explanation above the finding)."""
    out: dict[int, set] = {}
    lines = source.splitlines()

    def _is_comment_or_blank(idx0: int) -> bool:
        s = lines[idx0].strip() if idx0 < len(lines) else ""
        return not s or s.startswith("#")

    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            line = tok.start[0]
            out.setdefault(line, set()).update(rules)
            if tok.line.strip().startswith("#"):  # standalone comment line
                nxt = line  # 1-based; lines[nxt] is the NEXT line (0-based)
                while nxt < len(lines) and _is_comment_or_blank(nxt):
                    nxt += 1
                out.setdefault(nxt + 1, set()).update(rules)
    except tokenize.TokenError:
        pass
    return out


class _ModuleFacts:
    """Per-module inputs to the cross-module rules R3/R4."""

    def __init__(self) -> None:
        self.fanout_consts: list[tuple[int, int, str, int]] = []  # line,col,name,val
        self.inflight_defaults: list[tuple[int, int]] = []  # line,val
        self.mentions_avg_part = False
        self.pool_ctor_calls: list[tuple[int, int, str, bool]] = []  # line,col,name,has_require_v2


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: list[Finding] = []
        self.facts = _ModuleFacts()
        self.aliases: dict[str, str] = {}
        self._func_stack: list[ast.AST] = []  # enclosing function defs
        self._class_stack: list[str] = []
        self._awaited: set[int] = set()
        # names of locally-defined coroutines (module funcs and methods)
        self.async_funcs: set[str] = set()
        self.async_methods: dict[str, set] = {}

    # -- helpers ----------------------------------------------------------

    def _add(self, node: ast.AST, rule: str, msg: str) -> None:
        self.findings.append(
            Finding(self.path, node.lineno, node.col_offset, rule, msg)
        )

    def _in_async(self) -> bool:
        return bool(self._func_stack) and isinstance(
            self._func_stack[-1], ast.AsyncFunctionDef
        )

    # -- structure --------------------------------------------------------

    def visit_Import(self, node: ast.Import) -> None:
        for a in node.names:
            self.aliases[a.asname or a.name.split(".")[0]] = (
                a.name if a.asname else a.name.split(".")[0]
            )
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for a in node.names:
            if a.name == "*":
                continue
            self.aliases[a.asname or a.name] = f"{node.module}.{a.name}" if node.module else a.name
        self.generic_visit(node)

    def _collect_defaults(self, node) -> None:
        # align trailing defaults with trailing args (positional part)
        pos_args = node.args.args
        pos_defaults = node.args.defaults
        pairs = list(zip(pos_args[len(pos_args) - len(pos_defaults):], pos_defaults))
        pairs += [
            (a, d)
            for a, d in zip(node.args.kwonlyargs, node.args.kw_defaults)
            if d is not None
        ]
        for arg, default in pairs:
            if (
                arg.arg == "max_inflight"
                and isinstance(default, ast.Constant)
                and isinstance(default.value, int)
            ):
                self.facts.inflight_defaults.append((node.lineno, default.value))

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._collect_defaults(node)
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        # async_funcs / async_methods are filled by lint_paths' pre-pass
        # (call sites may lexically precede the definitions they target)
        self._collect_defaults(node)
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._func_stack.append(node)
        self.generic_visit(node)
        self._func_stack.pop()

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_fanout_const(node.targets[0] if node.targets else None, node.value, node)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        self._check_fanout_const(node.target, node.value, node)
        self.generic_visit(node)

    def _check_fanout_const(self, target, value, node) -> None:
        if (
            isinstance(target, ast.Name)
            and _FANOUT_CONST_RE.match(target.id)
            and isinstance(value, ast.Constant)
            and isinstance(value.value, int)
        ):
            self.facts.fanout_consts.append(
                (node.lineno, node.col_offset, target.id, value.value)
            )

    def visit_Constant(self, node: ast.Constant) -> None:
        if node.value == "avg_part":
            self.facts.mentions_avg_part = True
        self.generic_visit(node)

    # -- R6 ---------------------------------------------------------------

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._add(node, "R6", "bare `except:` hides every failure mode")
        else:
            names = []
            t = node.type
            for sub in t.elts if isinstance(t, ast.Tuple) else [t]:
                if isinstance(sub, ast.Name):
                    names.append(sub.id)
            if (
                any(n in ("Exception", "BaseException") for n in names)
                and len(node.body) == 1
                and isinstance(node.body[0], ast.Pass)
            ):
                self._add(
                    node, "R6",
                    "broad exception swallowed (`except "
                    f"{'/'.join(names)}: pass`) — log it or narrow the type",
                )
        self.generic_visit(node)

    # -- await bookkeeping ------------------------------------------------

    def visit_Await(self, node: ast.Await) -> None:
        self._awaited.add(id(node.value))
        self.generic_visit(node)

    # -- R7 ---------------------------------------------------------------

    def visit_Expr(self, node: ast.Expr) -> None:
        call = node.value
        if isinstance(call, ast.Call):
            fn = call.func
            if isinstance(fn, ast.Name) and fn.id in self.async_funcs:
                self._add(
                    call, "R7",
                    f"coroutine {fn.id}() called without await — it is "
                    "never scheduled",
                )
            elif (
                isinstance(fn, ast.Attribute)
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "self"
                and self._class_stack
                and fn.attr in self.async_methods.get(self._class_stack[-1], ())
            ):
                self._add(
                    call, "R7",
                    f"coroutine self.{fn.attr}() called without await — it "
                    "is never scheduled",
                )
        self.generic_visit(node)

    # -- calls: R1, R2, R4, R5 -------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func, self.aliases)
        tail = dotted.split(".")[-1] if dotted else None
        awaited = id(node) in self._awaited

        # R4 facts: pool constructions in held-reply modules
        if tail in ("PoolRegistry", "ConnectionPool"):
            has_req = any(
                kw.arg == "require_v2"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in node.keywords
            )
            self.facts.pool_ctor_calls.append(
                (node.lineno, node.col_offset, tail, has_req)
            )

        # R5: meta dict literals with non-string keys
        meta_arg = None
        if tail in _META_CALLS:
            pos = _META_CALLS[tail]
            if len(node.args) > pos:
                meta_arg = node.args[pos]
            for kw in node.keywords:
                if kw.arg == "meta":
                    meta_arg = kw.value
        elif tail in ("dumps", "packb") and dotted and (
            dotted.endswith("MSGPackSerializer.dumps")
            or dotted.endswith("msgpack.packb")
        ):
            if node.args:
                meta_arg = node.args[0]
        if meta_arg is not None:
            self._check_msgpack_keys(meta_arg)

        if self._in_async() and not awaited:
            # R2: blocking waits on the loop
            if isinstance(node.func, ast.Attribute) and node.func.attr == "result":
                self._add(
                    node, "R2",
                    "`.result()` inside an async function blocks the event "
                    "loop — and self-deadlocks when the future needs THIS "
                    "loop; await instead",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "run"
                and "loop"
                in (dotted or ast.unparse(node.func.value)).lower()
            ):
                recv = dotted or f"{ast.unparse(node.func.value)}.run"
                self._add(
                    node, "R2",
                    f"`{recv}(...)` inside an async function blocks this "
                    "loop on another loop's result — the io_callback "
                    "self-deadlock shape; await the coroutine or submit()",
                )
            # R1: blocking calls
            elif dotted in _BLOCKING_CALLS or tail in _SERIALIZATION_FUNCS:
                self._add(
                    node, "R1",
                    f"blocking call `{dotted or tail}` inside an async "
                    "function — move it to a host thread or executor",
                )
            elif isinstance(node.func, ast.Name) and node.func.id == "open":
                self._add(
                    node, "R1",
                    "file I/O (`open`) inside an async function — use an "
                    "executor",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "prepare"
                and dotted is not None
                and dotted.endswith("WireTensors.prepare")
                and node.args
            ):
                self._add(
                    node, "R1",
                    "WireTensors.prepare(tensors) inside an async function "
                    "— hot-path payloads must be prepared off-loop "
                    "(rpc_prepared contract)",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "encode"
                and dotted is not None
                and dotted.endswith("EncodedBatch.encode")
            ):
                self._add(
                    node, "R1",
                    "EncodedBatch.encode inside an async function — "
                    "quantize is O(bytes) work, encode off-loop",
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
                and not any(kw.arg in ("timeout", "blocking") for kw in node.keywords)
                and not node.args
            ):
                self._add(
                    node, "R1",
                    "un-awaited `.acquire()` without a timeout inside an "
                    "async function — a threading lock here parks the loop",
                )

        # R2 (anywhere): run_coroutine_threadsafe(...).result() chain
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "result"
            and isinstance(node.func.value, ast.Call)
        ):
            inner = _dotted(node.func.value.func, self.aliases)
            if inner and inner.endswith("run_coroutine_threadsafe"):
                self._add(
                    node, "R2",
                    "run_coroutine_threadsafe(...).result() — guaranteed "
                    "self-deadlock when called on the target loop's own "
                    "thread; use BackgroundLoop.run (it carries the "
                    "thread-identity guard)",
                )
        self.generic_visit(node)

    def _check_msgpack_keys(self, node: ast.AST) -> None:
        if not isinstance(node, ast.Dict):
            return
        for k, v in zip(node.keys, node.values):
            if isinstance(k, ast.Constant) and not isinstance(k.value, str):
                self._add(
                    k, "R5",
                    f"msgpack meta key {k.value!r} is "
                    f"{type(k.value).__name__}, not str — stats/meta maps "
                    "must use string keys (PR 1 contract)",
                )
            if isinstance(v, ast.Dict):
                self._check_msgpack_keys(v)


def _iter_py_files(paths: Iterable[str]) -> list[str]:
    out: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [d for d in dirs if d != "__pycache__"]
                out.extend(
                    os.path.join(root, f) for f in files if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(paths: Iterable[str]) -> list[Finding]:
    """Lint files/directories; returns ALL findings with ``suppressed``
    set for baselined ones.  Cross-module rules (R3, R4) are evaluated
    over the whole linted set, so lint the package root for the real
    verdict."""
    findings: list[Finding] = []
    all_facts: list[tuple[str, _ModuleFacts]] = []
    suppress_by_path: dict[str, dict[int, set]] = {}
    for path in _iter_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            findings.append(
                Finding(path, getattr(e, "lineno", 0) or 0, 0, "PARSE",
                        f"could not parse: {e}")
            )
            continue
        suppress_by_path[path] = _suppressions(source)
        # pre-pass: async def names must exist before visiting call sites.
        # Scoped precisely — MODULE-LEVEL async defs only for bare-name
        # calls, and per-class direct methods for self.<m>() calls — so a
        # sync module function sharing a name with some class's coroutine
        # is never false-flagged (R7 findings fail the gate; precision
        # beats recall here)
        visitor = _Visitor(path)
        for node in tree.body:
            if isinstance(node, ast.AsyncFunctionDef):
                visitor.async_funcs.add(node.name)
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, ast.AsyncFunctionDef):
                        visitor.async_methods.setdefault(
                            node.name, set()
                        ).add(sub.name)
        visitor.visit(tree)
        findings.extend(visitor.findings)
        all_facts.append((path, visitor.facts))

    # R3: every fan-out constant must sit below every max_inflight default
    inflight = [
        (path, line, val)
        for path, facts in all_facts
        for line, val in facts.inflight_defaults
    ]
    if inflight:
        limit = min(v for _, _, v in inflight)
        where = next((f"{p}:{ln}" for p, ln, v in inflight if v == limit), "?")
        for path, facts in all_facts:
            for line, col, name, val in facts.fanout_consts:
                if val >= limit:
                    findings.append(
                        Finding(
                            path, line, col, "R3",
                            f"{name}={val} must be < the mux in-flight "
                            f"limit {limit} ({where}): held replies need "
                            "every chunk RPC admitted concurrently",
                        )
                    )

    # R4: held-reply modules must pin require_v2=True on their pools
    for path, facts in all_facts:
        if not facts.mentions_avg_part:
            continue
        for line, col, name, has_req in facts.pool_ctor_calls:
            if not has_req:
                findings.append(
                    Finding(
                        path, line, col, "R4",
                        f"{name}(...) in a held-reply (avg_part) module "
                        "without require_v2=True — held replies starve "
                        "v1's one-RPC-per-socket pool",
                    )
                )

    # apply suppressions
    for f in findings:
        rules = suppress_by_path.get(f.path, {}).get(f.line, set())
        if f.rule in rules:
            f.suppressed = True
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def format_findings(findings: list[Finding], show_suppressed: bool = False) -> str:
    lines = [
        f.render()
        for f in findings
        if show_suppressed or not f.suppressed
    ]
    active = sum(1 for f in findings if not f.suppressed)
    sup = len(findings) - active
    lines.append(
        f"lah-lint: {active} finding(s), {sup} suppressed"
    )
    return "\n".join(lines)
