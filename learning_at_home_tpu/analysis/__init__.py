"""Static analysis for the swarm's concurrency invariants (ISSUE 6).

``lah_lint`` (tools/lah_lint.py fronts :mod:`.lint`) encodes the
threading rules the runtime sanitizer (utils/sanitizer.py) checks
dynamically — the static layer catches the violation at review time, the
runtime layer catches whatever slips through.  docs/CONCURRENCY.md is
the prose contract both layers enforce.
"""

from learning_at_home_tpu.analysis.lint import (  # noqa: F401
    Finding,
    RULES,
    format_findings,
    lint_paths,
)

__all__ = ["Finding", "RULES", "format_findings", "lint_paths"]
