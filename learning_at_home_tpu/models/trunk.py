"""Shared transformer trunk pieces used by both deployment modes.

Pod mode (models/transformer.py, sharded MoE) and swarm mode
(models/transformer_swarm.py, remote MoE) must stay numerically identical
in everything but the FFN — LN epsilon, causal masking, attention math
live HERE once so they cannot drift.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(p: dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Pre-LN in float32, cast back to the input dtype."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def qkv_projections(lp: dict, x: jax.Array, n_heads: int):
    """Shared Q/K/V projections: [B,S,d] → three [B,S,H,hd]."""
    b, s, d = x.shape
    hd = d // n_heads
    q = (x @ lp["wq"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    k = (x @ lp["wk"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    v = (x @ lp["wv"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    return q, k, v


def output_projection(lp: dict, out: jax.Array) -> jax.Array:
    """[B,S,H,hd] → [B,S,d] @ wo."""
    b, s, h, hd = out.shape
    return out.reshape(b, s, h * hd) @ lp["wo"].astype(out.dtype)


def causal_attention(
    lp: dict, x: jax.Array, n_heads: int, impl: str = "xla"
) -> jax.Array:
    """Multi-head causal self-attention.

    impl="xla": ``jax.nn.dot_product_attention`` (f32 softmax, 1/sqrt(hd)
    scale).  NB: jax 0.9's default implementation still materializes the
    [B,H,S,S] scores — the API is used so future jax releases/backends
    can substitute fused kernels, NOT for a memory win today.

    impl="flash": the TPU Pallas flash-attention kernel
    (``jax.experimental.pallas.ops.tpu.flash_attention``) — O(S) memory,
    block-streamed online softmax on the MXU.  TPU-only; sequence length
    must divide its block size (512 or S, whichever is smaller).

    For sequences split ACROSS chips use the ring path
    (parallel/ring_attention.py), which shares :func:`qkv_projections` /
    :func:`output_projection` and replaces only this dense core.
    """
    q, k, v = qkv_projections(lp, x, n_heads)
    return output_projection(lp, attention_core(q, k, v, impl))


def attention_core(
    q: jax.Array, k: jax.Array, v: jax.Array, impl: str = "xla"
) -> jax.Array:
    """The causal attention math on pre-projected [B,S,H,hd] q/k/v —
    shared by :func:`causal_attention` and the KV-cache decoder's prefill
    so the two paths cannot diverge numerically per ``impl``."""
    if impl not in ("xla", "flash"):
        raise ValueError(f"impl must be 'xla' or 'flash', got {impl!r}")
    if impl == "flash":
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention,
        )

        hd = q.shape[-1]
        # kernel convention is [B, H, S, hd] and applies no scale itself
        return flash_attention(
            q.transpose(0, 2, 1, 3),
            k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3),
            causal=True,
            sm_scale=1.0 / (hd ** 0.5),
        ).transpose(0, 2, 1, 3)
    return jax.nn.dot_product_attention(q, k, v, is_causal=True)


def one_query_attention(
    lp: dict, q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, t
) -> jax.Array:
    """Attention for ONE query position per row over a KV cache.

    q [B,1,H,hd]; caches [B,S,H,hd] (positions > t are garbage and
    masked).  f32 softmax, 1/sqrt(hd) scale — the same numerics as
    ``jax.nn.dot_product_attention`` in the full forward.

    ``t`` is either a scalar (pod decode: every row sits at the same
    position) or anything broadcastable against the [B,H,Q,S] score mask
    — the swarm KV decoder (models/swarm_decoder.py) passes [B,1,1,1]
    per-slot positions so one continuous batch can hold streams at
    different depths, and its chunked prefill passes Q > 1 queries with
    [1,1,Q,1] per-query positions (the einsums generalize over Q
    untouched).  Shared here so the pod decoder and the gateway's swarm
    decoder cannot drift numerically.
    """
    hd = q.shape[-1]
    scores = jnp.einsum(
        "bqhd,bshd->bhqs", q, k_cache, preferred_element_type=jnp.float32
    ) * (1.0 / np.sqrt(hd))
    s = k_cache.shape[1]
    mask = jnp.arange(s, dtype=jnp.int32)[None, None, None, :] <= t
    scores = jnp.where(mask, scores, -jnp.inf)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bhqs,bshd->bqhd", w, v_cache)
    return output_projection(lp, out)


def gather_kv_pages(
    pool: jax.Array, page_tables: jax.Array
) -> jax.Array:
    """[num_pages,P,H,hd] pool + [B,n] int32 page tables → a [B,n*P,H,hd]
    contiguous per-row KV view.  A static-shape gather — jit-friendly
    int32 indirection, no data-dependent shapes.  Unmapped table entries
    point at scratch page 0; its (finite) garbage sits at positions the
    caller's ``t`` mask excludes, so the softmax sees weight exactly 0
    there and the output is bitwise what a dense cache would produce.
    """
    b, n = page_tables.shape
    num_pages, page_len, h, hd = pool.shape
    return pool[page_tables].reshape(b, n * page_len, h, hd)


def paged_one_query_attention(
    lp: dict,
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_tables: jax.Array,
    t,
) -> jax.Array:
    """:func:`one_query_attention` over a PAGED KV cache: per-row caches
    are materialized from the shared page pool via int32 page-table
    gathers, then the identical masked-softmax core runs on the view —
    paged decode is bitwise-equal to dense decode by construction (the
    tier-1 parity contract).  A fused TPU kernel (Pallas paged_attention,
    /opt/skills/guides/boom_attention_tricks.md §8) would stream pages
    without materializing the view; this path keeps the same [pages,
    page table] layout so that swap stays a kernel substitution.
    """
    k = gather_kv_pages(k_pool, page_tables)
    v = gather_kv_pages(v_pool, page_tables)
    return one_query_attention(lp, q, k, v, t)
