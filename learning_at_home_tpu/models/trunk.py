"""Shared transformer trunk pieces used by both deployment modes.

Pod mode (models/transformer.py, sharded MoE) and swarm mode
(models/transformer_swarm.py, remote MoE) must stay numerically identical
in everything but the FFN — LN epsilon, causal masking, attention math
live HERE once so they cannot drift.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def layer_norm(p: dict, x: jax.Array, eps: float = 1e-5) -> jax.Array:
    """Pre-LN in float32, cast back to the input dtype."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * p["scale"] + p["bias"]).astype(x.dtype)


def qkv_projections(lp: dict, x: jax.Array, n_heads: int):
    """Shared Q/K/V projections: [B,S,d] → three [B,S,H,hd]."""
    b, s, d = x.shape
    hd = d // n_heads
    q = (x @ lp["wq"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    k = (x @ lp["wk"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    v = (x @ lp["wv"].astype(x.dtype)).reshape(b, s, n_heads, hd)
    return q, k, v


def output_projection(lp: dict, out: jax.Array) -> jax.Array:
    """[B,S,H,hd] → [B,S,d] @ wo."""
    b, s, h, hd = out.shape
    return out.reshape(b, s, h * hd) @ lp["wo"].astype(out.dtype)


def causal_attention(lp: dict, x: jax.Array, n_heads: int) -> jax.Array:
    """Multi-head causal self-attention; softmax in float32.

    The ring-attention path (parallel/ring_attention.py) shares
    :func:`qkv_projections` / :func:`output_projection` and replaces only
    this dense score/softmax core with the ppermute ring + online softmax.
    """
    q, k, v = qkv_projections(lp, x, n_heads)
    s = x.shape[1]
    hd = q.shape[-1]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    causal = jnp.tril(jnp.ones((s, s), bool))
    scores = jnp.where(causal, scores.astype(jnp.float32), -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
    return output_projection(lp, out)
