"""Swarm-mode DMoE-Transformer: local trunk, network-remote expert FFNs.

This is the reference's headline training setup (SURVEY.md §3.5): the
trainer owns the embeddings/attention/gates and steps them with its own
optimizer; every MoE FFN layer is a ``RemoteMixtureOfExperts`` whose
experts live on DHT-discovered servers and update themselves
asynchronously on each backward RPC.

The remote dispatch rides ``io_callback`` under ``custom_vjp``
(client/moe.py), so the whole step still jits on backends with
host-callback support (CPU/GPU; the axon TPU plugin lacks callbacks — pod
mode's ShardedMixtureOfExperts is the TPU path, SURVEY.md §2.2).

Deployment note: run trainers and expert servers in SEPARATE processes
(the normal swarm topology).  In one process they share one XLA runtime,
and a trainer's blocking host callback can occupy the execution slot the
server's own jitted expert computation needs — under concurrency that
degenerates into stalls.  ``background_server`` in-process is fine for
light tests; real training should talk to ``python -m
learning_at_home_tpu.server`` peers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import ExpertSource
from learning_at_home_tpu.models.trunk import causal_attention, layer_norm


@dataclasses.dataclass(frozen=True)
class SwarmTransformerConfig:
    vocab_size: int = 258
    d_model: int = 256
    n_layers: int = 2
    n_heads: int = 8
    seq_len: int = 128
    grid_size: tuple = (16, 16)  # 256-expert grid, [BJ] config 3
    k_best: int = 4
    k_min: int = 1
    backward_k_min: int = 1
    uid_prefix: str = "ffn"
    routing: str = "enumerate"
    dtype: Any = jnp.float32
    # generous defaults: first-time XLA compiles per batch bucket happen
    # inside the server's RPC window
    forward_timeout: float = 60.0
    backward_timeout: float = 60.0
    timeout_after_k_min: float = 1.0
    # "bfloat16"/"float16": downcast activation/grad payloads on the wire
    # (both directions; servers compute in f32) — halves the DCN bytes of
    # the large-row dispatches that dominate swarm dispatch p50
    wire_dtype: Any = None
    # wire CODEC pin ("none"/"bf16"/"f16"/"u8"/"blockq8"); None = adaptive
    # per-pool escalation (client/moe.py wire_codec, docs/PROTOCOL.md) —
    # 8-bit codecs quarter the DCN bytes vs f32
    wire_codec: Any = None
    # > 0: debit each expert's SELECTION score by this × its endpoint's
    # RTT EMA (seconds) so routing avoids slow/overloaded peers
    # proactively (see client/moe.py latency_weight); 0 = off
    latency_weight: float = 0.0
    # latency-aware routing cost model (ISSUE 8): bias selection by
    # predicted completion time (RTT EMA + DHT-advertised queue depth +
    # estimated transfer at the negotiated codec), minimized over each
    # expert's replica set.  None falls back to latency_weight; 0 = off
    # (bias=None, selection bitwise the blind gate).  See
    # client/routing.py RoutingCostModel / DEFAULT_COST_WEIGHT.
    routing_cost_weight: Any = None
    # DHT scope of the ``load.<prefix>`` heartbeats the cost model reads
    # (must match the servers' --telemetry-prefix; see utils/telemetry.py)
    telemetry_prefix: str = "swarm"


class SwarmDMoETransformerLM:
    """Trainer-side model; expert parameters never touch this process."""

    def __init__(self, config: SwarmTransformerConfig, source: ExpertSource):
        self.cfg = config
        # one MoE layer object per transformer layer: layers may route to
        # different uid prefixes (ffn0., ffn1., ...) so experts specialize
        self.moes = [
            RemoteMixtureOfExperts(
                in_features=config.d_model,
                grid_size=config.grid_size,
                uid_prefix=f"{config.uid_prefix}{i}",
                source=source,
                k_best=config.k_best,
                k_min=config.k_min,
                backward_k_min=config.backward_k_min,
                routing=config.routing,
                forward_timeout=config.forward_timeout,
                backward_timeout=config.backward_timeout,
                timeout_after_k_min=config.timeout_after_k_min,
                wire_dtype=config.wire_dtype,
                wire_codec=config.wire_codec,
                latency_weight=config.latency_weight,
                routing_cost_weight=config.routing_cost_weight,
                telemetry_prefix=config.telemetry_prefix,
            )
            for i in range(config.n_layers)
        ]

    def init_params(self, rng: jax.Array) -> Any:
        cfg = self.cfg
        d, v, s = cfg.d_model, cfg.vocab_size, cfg.seq_len
        dense = jax.nn.initializers.lecun_normal()
        embed_init = jax.nn.initializers.normal(1.0 / np.sqrt(d))
        keys = iter(jax.random.split(rng, 3 + 6 * cfg.n_layers))

        def ln():
            return {"scale": jnp.ones((d,)), "bias": jnp.zeros((d,))}

        params = {
            "embed": embed_init(next(keys), (v, d)),
            "pos": embed_init(next(keys), (s, d)),
            "ln_f": ln(),
            "layers": [],
        }
        for i in range(cfg.n_layers):
            params["layers"].append(
                {
                    "ln1": ln(),
                    "wq": dense(next(keys), (d, d)),
                    "wk": dense(next(keys), (d, d)),
                    "wv": dense(next(keys), (d, d)),
                    "wo": dense(next(keys), (d, d)),
                    "ln2": ln(),
                    "gate": self.moes[i].init_gate_params(next(keys)),
                }
            )
        return params

    def apply(self, params, token_ids):
        b, s = token_ids.shape
        x = params["embed"][token_ids] + params["pos"][None, :s]
        for i, lp in enumerate(params["layers"]):
            x = x + causal_attention(lp, layer_norm(lp["ln1"], x), self.cfg.n_heads)
            moe_in = layer_norm(lp["ln2"], x).reshape(b * s, self.cfg.d_model)
            moe_out = self.moes[i](moe_in, lp["gate"])
            x = x + moe_out.reshape(b, s, self.cfg.d_model)
        x = layer_norm(params["ln_f"], x)
        return x @ params["embed"].T

    def apply_overlapped(self, params, token_ids, *, overlap: bool = True):
        """ScMoE-style parallel-branch step with communication/compute
        overlap (ISSUE 7; cf. Shortcut-connected Expert Parallelism,
        arXiv:2404.05019).

        Architecture note — this is a DIFFERENT (shortcut) wiring from
        :meth:`apply`: each layer's MoE branch reads ``ln2`` of the layer
        INPUT (not the post-attention residual), so the expert fan-out
        for layer *i* has no data dependency on layer *i*'s attention and
        can be FIRED before it.  The overlapped schedule fires the MoE,
        computes the attention trunk while the RPCs fly, and joins the
        future only where the residual add needs the replies.  Backward
        mirrors it automatically: the join op's bwd fires the grad
        fan-out, the attention backward computes, and the fire op's bwd
        joins (client/moe.py).

        ``overlap=False`` runs the SAME primitive ops in the serial
        schedule (join immediately after fire) — only host-side
        scheduling differs, so serial and overlapped outputs and
        gradients are bitwise identical; that is the A/B contract
        bench.py and the parity tests rely on."""
        cfg = self.cfg
        b, s = token_ids.shape
        x = params["embed"][token_ids] + params["pos"][None, :s]
        for i, lp in enumerate(params["layers"]):
            moe_in = layer_norm(lp["ln2"], x).reshape(b * s, cfg.d_model)
            pending = self.moes[i].fire(moe_in, lp["gate"])
            try:
                if not overlap:  # serial schedule: eat the wait right here
                    moe_out = self.moes[i].join(*pending)
                x = x + causal_attention(
                    lp, layer_norm(lp["ln1"], x), cfg.n_heads
                )
                if overlap:  # join as late as the data dependency allows
                    moe_out = self.moes[i].join(*pending)
            except Exception:
                # a raise between fire and join must not leak the
                # in-flight fan-out until ticket eviction (no-op if the
                # join already consumed it)
                self.moes[i].discard(*pending)
                raise
            x = x + moe_out.reshape(b, s, cfg.d_model)
        x = layer_norm(params["ln_f"], x)
        return x @ params["embed"].T

    def loss_fn(self, params, token_ids, targets):
        logits = self.apply(params, token_ids)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    def loss_fn_overlapped(self, params, token_ids, targets, *,
                           overlap: bool = True):
        logits = self.apply_overlapped(params, token_ids, overlap=overlap)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets
        ).mean()

    def make_train_step(self, optimizer: optax.GradientTransformation) -> Callable:
        """Eager-host train step: local grads via jax.grad (backward RPCs
        fire inside), optimizer on trunk+gates only."""

        def step(params, opt_state, ids, targets):
            loss, grads = jax.value_and_grad(self.loss_fn)(params, ids, targets)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        return step

    def make_overlapped_train_step(
        self, optimizer: optax.GradientTransformation, *,
        overlap: bool = True,
    ) -> Callable:
        """Train step over the shortcut architecture — ``overlap``
        selects the schedule (overlapped vs serial) without changing a
        single primitive op; see :meth:`apply_overlapped`."""

        def loss(params, ids, targets):
            return self.loss_fn_overlapped(
                params, ids, targets, overlap=overlap
            )

        def step(params, opt_state, ids, targets):
            loss_val, grads = jax.value_and_grad(loss)(params, ids, targets)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss_val

        return step
