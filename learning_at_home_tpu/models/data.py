"""LM data pipeline: byte-level tokenization over local text, with a
synthetic fallback corpus.

The reference's headline experiment trains on WikiText-103 (SURVEY.md §3.5).
This sandbox has zero network egress, so the dataset cannot be fetched;
the pipeline therefore (a) consumes any local text/token file when given
one — point ``--data`` at a WikiText dump to reproduce the reference
setup — and (b) otherwise generates a deterministic synthetic corpus with
natural-language-like statistics (Zipfian unigrams + Markov bigram
structure) so every experiment runs end-to-end out of the box.

Byte-level vocab (256 + specials) keeps the stack dependency-free; a
subword tokenizer can be slotted in via ``encode_fn``.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional

import numpy as np

VOCAB_SIZE = 258  # 256 bytes + BOS + EOS
BOS, EOS = 256, 257


def encode_bytes(text: str) -> np.ndarray:
    return np.frombuffer(text.encode("utf-8", errors="replace"), dtype=np.uint8)


def synthetic_corpus(n_tokens: int, seed: int = 0) -> np.ndarray:
    """Zipfian word soup over a fixed lexicon (vectorized, deterministic).

    Word identities follow a Zipf law (like natural text); bytes within a
    word are deterministic, so a language model has real structure to
    learn — loss decreases measurably within a few hundred steps."""
    rng = np.random.RandomState(seed)
    lexicon_size = 1024
    lengths = rng.randint(2, 11, size=lexicon_size)
    lexicon = [
        rng.randint(97, 123, size=n).astype(np.uint8) for n in lengths  # a-z
    ]
    ranks = np.arange(1, lexicon_size + 1)
    probs = 1.0 / ranks**1.1
    probs /= probs.sum()
    avg_word = float(np.mean(lengths)) + 1.0  # +1 for the space
    n_words = int(n_tokens / avg_word) + lexicon_size
    word_ids = rng.choice(lexicon_size, size=n_words, p=probs)
    space = np.array([32], np.uint8)
    stream = np.concatenate(
        [part for wid in word_ids for part in (lexicon[wid], space)]
    )
    return stream[:n_tokens].astype(np.int32)


def load_corpus(
    path: Optional[str] = None,
    n_synthetic_tokens: int = 1 << 20,
    seed: int = 0,
) -> np.ndarray:
    """Token stream from a local file (.npy tokens or raw text) or synthetic."""
    if path:
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        if path.endswith(".npy"):
            return np.load(path).astype(np.int32)
        with open(path, "rb") as f:
            return np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
    return synthetic_corpus(n_synthetic_tokens, seed)


class LMBatcher:
    """Contiguous next-token-prediction batches over a token stream."""

    def __init__(
        self,
        tokens: np.ndarray,
        batch_size: int,
        seq_len: int,
        seed: int = 0,
    ):
        if len(tokens) < seq_len + 2:
            raise ValueError("corpus shorter than one sequence")
        self.tokens = tokens
        self.batch_size = batch_size
        self.seq_len = seq_len
        self.rng = np.random.RandomState(seed)

    def skip(self, n_batches: int) -> None:
        """Advance the RNG past n_batches draws WITHOUT materializing them —
        resume must continue the uninterrupted run's data order, not replay
        batches already trained on."""
        for _ in range(n_batches):
            self.rng.randint(
                0, len(self.tokens) - self.seq_len - 1, size=self.batch_size
            )

    def __iter__(self) -> Iterator[tuple[np.ndarray, np.ndarray]]:
        return self

    def __next__(self) -> tuple[np.ndarray, np.ndarray]:
        starts = self.rng.randint(
            0, len(self.tokens) - self.seq_len - 1, size=self.batch_size
        )
        idx = starts[:, None] + np.arange(self.seq_len + 1)[None, :]
        window = self.tokens[idx]
        return window[:, :-1].astype(np.int32), window[:, 1:].astype(np.int32)
