"""Expert layer zoo: the sample blocks servers can host, by registry name.

Parity with the reference's ``hivemind/server/layers/`` registry
(``name_to_block``-style, SURVEY.md §2 "Expert layer zoo"; unverifiable
refs, mount empty): an FFN block and a Transformer-encoder block, keyed by
name so CLI/server configs can say ``expert_cls="ffn"``.

TPU notes: blocks are flax modules; matmul-heavy, bias-light shapes that
tile cleanly onto the MXU.  ``dtype`` controls activation/compute precision
(bfloat16 by default on TPU); parameters stay float32.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class FeedforwardBlock(nn.Module):
    """Residual pre-LN MLP expert: LN → Dense(4h) → GELU → Dense(h) + x."""

    hidden_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype)(h)
        return x + h


class TransformerEncoderBlock(nn.Module):
    """Pre-LN transformer encoder layer expert over [batch, seq, hidden]."""

    hidden_dim: int
    num_heads: int = 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype
        )(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype)(h)
        return x + h


class SwiGLUBlock(nn.Module):
    """Residual pre-LN SwiGLU expert: LN → (W1·x) ⊙ silu(Wg·x) → W2 + x.

    The modern MoE expert shape (gated linear unit) — three matmuls that
    tile cleanly onto the MXU; ~same params as the 4x GELU FFN at
    ffn_mult 8/3 but here kept at 4x·2/3 per branch for simplicity."""

    hidden_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        up = nn.Dense(8 * self.hidden_dim // 3, use_bias=False, dtype=self.dtype)(h)
        gate = nn.Dense(8 * self.hidden_dim // 3, use_bias=False, dtype=self.dtype)(h)
        h = up * nn.silu(gate)
        h = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype)(h)
        return x + h


class DeterministicDropoutBlock(nn.Module):
    """FFN expert with dropout that is a pure function of a per-row seed.

    The reference ships a deterministic-dropout layer because its server
    RE-RUNS forward inside backward (autograd re-execution) — a stateful
    dropout mask would differ between the two passes and corrupt the
    gradients (SURVEY.md §3.2; ``hivemind/server/layers`` det-dropout,
    unverifiable refs, mount empty).  Same constraint here: backward is
    one jitted ``jax.vjp`` re-forward (``expert_backend.py``), so the mask
    must derive only from wire inputs.  The client sends a per-row int32
    ``seed`` tensor alongside ``x``; the mask is a counter-based hash of
    the seed (threefry via ``jax.random``) — identical on forward and on
    backward's re-forward because both see the same wire rows, and
    trivially vmappable/XLA-fusible (no RNG state anywhere).
    """

    hidden_dim: int
    rate: float = 0.1
    dtype: Any = jnp.float32

    @staticmethod
    def wire_inputs(hidden_dim: int, rows: int) -> list:
        """x plus a per-row int32 mask seed (see sample_inputs)."""
        import numpy as np

        return [
            np.zeros((rows, hidden_dim), np.float32),
            np.arange(rows, dtype=np.int32),
        ]

    @nn.compact
    def __call__(self, x, seed):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        keep = 1.0 - self.rate
        masks = jax.vmap(
            lambda s: jax.random.bernoulli(
                jax.random.PRNGKey(s), keep, (4 * self.hidden_dim,)
            )
        )(seed)
        h = h * masks.astype(h.dtype) / keep
        h = nn.Dense(self.hidden_dim, dtype=self.dtype)(h)
        return x + h


class NopBlock(nn.Module):
    """Identity expert — used by throughput benchmarks to isolate the
    batching/transport overhead from compute."""

    hidden_dim: int = 0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # one trainable scalar so backward/optimizer paths stay exercised
        scale = self.param("scale", nn.initializers.ones, ())
        return x * scale


name_to_block: dict[str, Callable[..., nn.Module]] = {
    "ffn": FeedforwardBlock,
    "transformer": TransformerEncoderBlock,
    "swiglu": SwiGLUBlock,
    "det_dropout": DeterministicDropoutBlock,
    "nop": NopBlock,
}


def sample_inputs(expert_cls: str, hidden_dim: int, rows: int = 2) -> list:
    """One example row-batch per wire input for a registry expert —
    drives init, warmup bucket compilation, and ``n_inputs``.

    Arity knowledge lives ON the block: a multi-input block declares a
    ``wire_inputs(hidden_dim, rows)`` staticmethod (see
    ``DeterministicDropoutBlock``); blocks without one take the standard
    single ``[rows, hidden]`` tensor."""
    import numpy as np

    block_cls = name_to_block[expert_cls]
    wire = getattr(block_cls, "wire_inputs", None)
    if wire is not None:
        return wire(hidden_dim, rows)
    return [np.zeros((rows, hidden_dim), np.float32)]


def make_expert(
    expert_cls: str,
    hidden_dim: int,
    rng: jax.Array,
    sample_input=None,
    dtype=jnp.float32,
) -> tuple[Callable, Any]:
    """Build ``(apply_fn, params)`` for an ExpertBackend from a registry name."""
    module = name_to_block[expert_cls](hidden_dim=hidden_dim, dtype=dtype)
    samples = (
        [sample_input]
        if sample_input is not None
        else sample_inputs(expert_cls, hidden_dim)
    )
    params = module.init(rng, *samples)

    def apply_fn(params, *inputs):
        return module.apply(params, *inputs)

    return apply_fn, params
