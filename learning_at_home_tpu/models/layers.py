"""Expert layer zoo: the sample blocks servers can host, by registry name.

Parity with the reference's ``hivemind/server/layers/`` registry
(``name_to_block``-style, SURVEY.md §2 "Expert layer zoo"; unverifiable
refs, mount empty): an FFN block and a Transformer-encoder block, keyed by
name so CLI/server configs can say ``expert_cls="ffn"``.

TPU notes: blocks are flax modules; matmul-heavy, bias-light shapes that
tile cleanly onto the MXU.  ``dtype`` controls activation/compute precision
(bfloat16 by default on TPU); parameters stay float32.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.linen as nn
import jax
import jax.numpy as jnp


class FeedforwardBlock(nn.Module):
    """Residual pre-LN MLP expert: LN → Dense(4h) → GELU → Dense(h) + x."""

    hidden_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype)(h)
        return x + h


class TransformerEncoderBlock(nn.Module):
    """Pre-LN transformer encoder layer expert over [batch, seq, hidden]."""

    hidden_dim: int
    num_heads: int = 8
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.num_heads, dtype=self.dtype
        )(h, h)
        x = x + h
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(4 * self.hidden_dim, dtype=self.dtype)(h)
        h = nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=self.dtype)(h)
        return x + h


class SwiGLUBlock(nn.Module):
    """Residual pre-LN SwiGLU expert: LN → (W1·x) ⊙ silu(Wg·x) → W2 + x.

    The modern MoE expert shape (gated linear unit) — three matmuls that
    tile cleanly onto the MXU; ~same params as the 4x GELU FFN at
    ffn_mult 8/3 but here kept at 4x·2/3 per branch for simplicity."""

    hidden_dim: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.LayerNorm(dtype=self.dtype)(x)
        up = nn.Dense(8 * self.hidden_dim // 3, use_bias=False, dtype=self.dtype)(h)
        gate = nn.Dense(8 * self.hidden_dim // 3, use_bias=False, dtype=self.dtype)(h)
        h = up * nn.silu(gate)
        h = nn.Dense(self.hidden_dim, use_bias=False, dtype=self.dtype)(h)
        return x + h


class NopBlock(nn.Module):
    """Identity expert — used by throughput benchmarks to isolate the
    batching/transport overhead from compute."""

    hidden_dim: int = 0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        # one trainable scalar so backward/optimizer paths stay exercised
        scale = self.param("scale", nn.initializers.ones, ())
        return x * scale


name_to_block: dict[str, Callable[..., nn.Module]] = {
    "ffn": FeedforwardBlock,
    "transformer": TransformerEncoderBlock,
    "swiglu": SwiGLUBlock,
    "nop": NopBlock,
}


def make_expert(
    expert_cls: str, hidden_dim: int, rng: jax.Array, sample_input, dtype=jnp.float32
) -> tuple[Callable, Any]:
    """Build ``(apply_fn, params)`` for an ExpertBackend from a registry name."""
    module = name_to_block[expert_cls](hidden_dim=hidden_dim, dtype=dtype)
    params = module.init(rng, sample_input)

    def apply_fn(params, *inputs):
        return module.apply(params, *inputs)

    return apply_fn, params
