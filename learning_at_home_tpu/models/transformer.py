"""DMoE-Transformer language model — the flagship ([BJ] config 3/5).

The reference's headline experiment: a Transformer LM whose FFN layers are
mixtures of experts (256-expert grid on WikiText-103 — SURVEY.md §3.5).
Two deployment modes share this module:

- **pod mode** (this file's train step): MoE FFNs are
  ``ShardedMixtureOfExperts`` — experts sharded over the mesh's ``expert``
  axis, dispatch via ``lax.all_to_all`` inside one compiled program.
- **swarm mode**: the same trunk with ``RemoteMixtureOfExperts`` FFNs
  calling DHT-discovered servers (see ``experiments/``).

Design notes for the MXU: everything is einsum-shaped, params in float32
with bfloat16 compute, static shapes throughout, optional per-layer remat
(``jax.checkpoint``) to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learning_at_home_tpu.models.trunk import (
    attention_core,
    causal_attention,
    layer_norm,
    one_query_attention,
    output_projection,
    qkv_projections,
)
from learning_at_home_tpu.parallel.mesh import batch_sharding
from learning_at_home_tpu.parallel.sharded_moe import ShardedMixtureOfExperts

Params = Any


@dataclasses.dataclass(frozen=True)
class DMoETransformerConfig:
    vocab_size: int = 32768
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    seq_len: int = 256
    num_experts: int = 256
    k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 1e-2
    router_z_weight: float = 1e-3  # ST-MoE router z-loss
    # Switch-style multiplicative routing noise (deterministic pattern;
    # see ops.moe_dispatch.router_jitter).  Essential for byte-level
    # corpora where near-identical rows otherwise collapse onto the same
    # experts (measured 0.73 init dropped fraction on the 256-expert
    # flagship).  Default OFF: the fixed row↦noise map is not
    # permutation-invariant, so it would break exact zigzag/contiguous
    # sequence-layout equivalence; trainers opt in (train_lm
    # --router-jitter).
    router_jitter: float = 0.0
    # 'topk' (token-choice, capacity drops) or 'expert_choice' (each
    # expert picks top-C tokens; perfectly balanced, no aux loss; routing
    # depends on the batch — see ops.moe_dispatch.expert_choice_gating)
    gating: str = "topk"
    # 'xla' = jax.nn.dot_product_attention (materializes [B,H,S,S]);
    # 'flash' = TPU Pallas flash-attention kernel (O(S) memory) — TPU
    # only, seq_len must divide the kernel block (min(512, S));
    # 'auto' = flash on TPU at seq_len >= 8192, else xla.
    # Measured table (v5e, 4-layer/64-expert, remat, tok/s): 2048 XLA
    # 101.7k vs flash 82.3k; 4096 tie (57.9 vs 57.1); 8192 flash 8.6x
    # (36.7k vs 4.3k — materialized scores hit the HBM cliff); 16384
    # XLA 24.8k vs flash 21.5k.  Auto still picks flash at 16384 — a
    # DELIBERATE exception to the measured winner: XLA's win there came
    # from a batch small enough that [B,H,S,S] fit (B*H*S*S*2 bytes;
    # at S=16384 even B=2,H=8 is 8.6 GB), and growing batch or heads
    # re-enters the 8192-style cliff, while flash stays O(S).  Paying
    # a measured -13% at one swept point buys a path whose memory does
    # not explode with batch; pass attn_impl='xla' explicitly to take
    # the 16384 point's winner at small batch.
    attn_impl: str = "auto"
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = False
    # remat granularity: "full" saves only each layer's input and
    # recomputes ALL internals in backward; "dots" saves matmul outputs
    # (jax.checkpoint_policies.dots_with_no_batch_dims_saveable) and
    # recomputes only the cheap elementwise chains — fewer recompute
    # FLOPs for more activation HBM
    remat_policy: str = "full"
    # True: lax.scan over stacked layer params (ONE compiled layer body —
    # HLO size and compile time ÷ L).  False: unrolled Python loop over
    # static slices of the SAME stacked params — L inlined bodies, but
    # the backward builds the stacked grad with pad+add chains XLA can
    # simplify instead of scan's per-iteration dynamic-update-slice
    # writes into a zero-initialized param-sized buffer (measured ~13
    # ms/step of pure HBM traffic at the 2.15 B-param flagship).
    scan_layers: bool = True
    # True: layer params live as ONE stacked pytree (leading n_layers dim
    # on every leaf) — required by scan_layers.  False: a tuple of
    # per-layer pytrees; with the unrolled loop the layers consume their
    # leaves directly, so the per-step slice-out copies of the stacked
    # layout (~13 ms at the 2.15 B-param flagship: remat saves the
    # sliced layer params as residuals) disappear.
    stack_layers: bool = True
    tie_embeddings: bool = True
    # sequence/context parallelism: attention runs as a ring over the
    # mesh's 'seq' axis (parallel/ring_attention.py).  The MoE stays
    # data+expert sharded; XLA inserts the reshard at the boundary.
    seq_parallel: bool = False
    # "zigzag" balances causal work across the ring (~2× fewer attention
    # FLOPs at scale); "contiguous" is the plain ring
    seq_layout: str = "zigzag"
    # token-chunk size for the rematerialized cross-entropy (peak logits
    # memory = ce_chunk × vocab × 4 bytes; see loss_fn)
    ce_chunk: int = 1024
    # "chunked" (default): checkpointed [ce_chunk, V] scan.  "fused": the
    # Pallas streaming-LSE kernel (ops/fused_ce.py) — logits never touch
    # HBM; multi-device meshes run it per-shard under shard_map (no seq
    # parallelism), anything else falls back to chunked.  Opt-in until
    # validated on hardware (tunnel down rounds 3-5).
    ce_impl: str = "chunked"
    # fused-CE tile sizes (row tile, vocab tile); vocab tile must divide
    # V and be a multiple of 128 (lane dim), row tile must divide the
    # (per-shard) token count
    ce_block_n: int = 128
    ce_block_v: int = 1024


class DMoETransformerLM:
    """Functional model: explicit param pytree, jit/pjit-friendly apply."""

    def __init__(self, config: DMoETransformerConfig, mesh: Mesh):
        if config.attn_impl == "auto":
            # the flash kernel is TPU-only (Mosaic lowering): require the
            # tpu backend specifically, not merely "not cpu"
            impl = (
                "flash"
                if jax.default_backend() == "tpu"
                and config.seq_len >= 8192
                and config.seq_len % min(512, config.seq_len) == 0
                else "xla"
            )
            config = dataclasses.replace(config, attn_impl=impl)
        if config.scan_layers and not config.stack_layers:
            raise ValueError(
                "scan_layers=True requires stack_layers=True (lax.scan "
                "consumes the stacked param pytree)"
            )
        self.cfg = config
        self.mesh = mesh
        # compiled decoders (one per decode path) + the memoized
        # eval-routing twin (see generate / decode_model): without these,
        # every generate() call re-traces its whole decode loop — measured
        # 17.1 s vs 0.07 s compiled for 60 tokens at seq_len 1024 on CPU
        self._gen_jit: dict = {}
        self._decode_model: "DMoETransformerLM | None" = None
        self.moe = ShardedMixtureOfExperts(
            mesh,
            hidden_dim=config.d_model,
            num_experts=config.num_experts,
            k=config.k,
            capacity_factor=config.capacity_factor,
            dtype=config.dtype,
            param_dtype=config.param_dtype,
            router_jitter=config.router_jitter,
            gating=config.gating,
        )
        self._ring = None
        self._zig = self._zig_inv = None
        if config.seq_parallel:
            if "seq" not in mesh.axis_names:
                raise ValueError("seq_parallel=True requires a 'seq' mesh axis")
            from learning_at_home_tpu.parallel.ring_attention import (
                make_ring_attention,
                zigzag_indices,
            )

            layout = config.seq_layout
            n_seq = mesh.shape["seq"]
            if layout == "zigzag" and config.seq_len % (2 * n_seq):
                import logging

                logging.getLogger(__name__).warning(
                    "seq_len %d not divisible by 2*%d — falling back to the "
                    "contiguous ring layout (zigzag needs paired chunks)",
                    config.seq_len, n_seq,
                )
                layout = "contiguous"
            if layout == "zigzag":
                # the residual stream is permuted ONCE at the model
                # boundary (see apply); the ring consumes zigzag order
                # directly — 2 gathers per step instead of 4 per layer
                self._zig = zigzag_indices(config.seq_len, n_seq)
                self._zig_inv = np.argsort(self._zig)
            self._ring = make_ring_attention(
                mesh, causal=True, layout=layout,
                pre_permuted=self._zig is not None,
            )

    # ---- parameters ----

    def init_params(self, rng: jax.Array) -> Params:
        """Layer params are STACKED (leading ``n_layers`` dim on every
        leaf) and the forward scans over them — one compiled layer body
        instead of ``n_layers`` inlined copies, which divides HLO size and
        compile time by ~L for the 256-expert flagship."""
        cfg = self.cfg
        d, v, s = cfg.d_model, cfg.vocab_size, cfg.seq_len
        dense = jax.nn.initializers.lecun_normal()
        embed_init = jax.nn.initializers.normal(1.0 / np.sqrt(d))
        k_embed, k_pos, k_head, k_layers = jax.random.split(rng, 4)
        pdt = cfg.param_dtype

        def ln():
            return {"scale": jnp.ones((d,), pdt), "bias": jnp.zeros((d,), pdt)}

        def init_layer(key):
            ks = jax.random.split(key, 5)
            return {
                "ln1": ln(),
                "wq": dense(ks[0], (d, d), pdt),
                "wk": dense(ks[1], (d, d), pdt),
                "wv": dense(ks[2], (d, d), pdt),
                "wo": dense(ks[3], (d, d), pdt),
                "ln2": ln(),
                "moe": self.moe.init_params(ks[4], device_put=False),
            }

        layer_keys = jax.random.split(k_layers, cfg.n_layers)
        params: dict = {
            "embed": embed_init(k_embed, (v, d), pdt),
            "pos": embed_init(k_pos, (s, d), pdt),
            "ln_f": ln(),
            "layers": (
                jax.vmap(init_layer)(layer_keys)
                if cfg.stack_layers
                else tuple(init_layer(k) for k in layer_keys)
            ),
        }
        if not cfg.tie_embeddings:
            params["lm_head"] = dense(k_head, (d, v), pdt)
        return jax.device_put(params, self.param_shardings(params))

    def param_shardings(self, params_shape: Params) -> Params:
        """Replicated everywhere except the expert stacks (whose specs gain
        a leading ``None`` for the stacked layer dim when stack_layers)."""
        stacked_moe = self.moe.param_shardings(stacked=self.cfg.stack_layers)
        repl = NamedSharding(self.mesh, P())

        def assign(path, leaf):
            for p in path:
                name = getattr(p, "key", getattr(p, "name", None))
                if name == "moe":
                    inner = path[-1]
                    return stacked_moe[getattr(inner, "key", None)]
            return repl

        return jax.tree_util.tree_map_with_path(assign, params_shape)

    # ---- forward ----

    def _ring_attention(self, lp, x):
        q, k, v = qkv_projections(lp, x, self.cfg.n_heads)
        return output_projection(lp, self._ring(q, k, v))

    def _layer(self, lp, x, layer_idx, token_mask=None):
        attn = self._ring_attention if self._ring is not None else (
            lambda lp, x: causal_attention(
                lp, x, self.cfg.n_heads, impl=self.cfg.attn_impl
            )
        )
        x = x + attn(lp, layer_norm(lp["ln1"], x))
        b, s, d = x.shape
        moe_in = layer_norm(lp["ln2"], x).reshape(b * s, d)
        # layer index salts the router jitter: decorrelates the
        # deterministic noise pattern across layers (round-2 advisor)
        moe_out, aux = self.moe(
            lp["moe"], moe_in, jitter_salt=layer_idx,
            token_mask=None if token_mask is None else token_mask.reshape(b * s),
        )
        x = x + moe_out.reshape(b, s, d)
        return x, aux

    def _hidden(
        self, params: Params, token_ids: jax.Array,
        token_mask: jax.Array | None = None,
    ) -> tuple[jax.Array, dict]:
        """token_ids [B, S] → final-LN hidden states [B, S, d]; aux scalars.

        ``token_mask`` [B, S] bool (optional, traced): False marks padding
        positions that must not participate in MoE routing (they claim no
        expert capacity and receive zero MoE output) — used by
        :meth:`generate` so a row's right-padding cannot evict other rows'
        real tokens from expert slots.  Attention needs no mask: causality
        already keeps real positions from attending to future padding."""
        cfg = self.cfg
        x = params["embed"][token_ids].astype(cfg.dtype)
        x = x + params["pos"][None, : token_ids.shape[1]].astype(cfg.dtype)
        layer_fn = self._layer
        if cfg.remat:
            if cfg.remat_policy == "dots":
                layer_fn = jax.checkpoint(
                    layer_fn,
                    policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                )
            elif cfg.remat_policy == "full":
                layer_fn = jax.checkpoint(layer_fn)
            else:
                raise ValueError(
                    f"remat_policy must be 'full' or 'dots', got "
                    f"{cfg.remat_policy!r}"
                )

        def body(x, lp_idx):
            lp, idx = lp_idx
            x, aux = layer_fn(lp, x, idx, token_mask)
            return x, aux

        if self._zig is not None:
            if token_ids.shape[1] != len(self._zig):
                raise ValueError(
                    f"zigzag layout was built for seq_len {len(self._zig)}, "
                    f"got {token_ids.shape[1]} — the pre-permuted ring would "
                    "silently misattend on other lengths"
                )
            # zigzag sequence layout for the whole layer stack: attention
            # consumes it natively; MoE and norms are per-token (order-
            # independent); positions were already added above
            x = x[:, self._zig]
            if token_mask is not None:
                token_mask = token_mask[:, self._zig]
        if cfg.scan_layers:
            # scan over the stacked layer params: ONE compiled layer body;
            # the layer index rides along as data (it is traced, so it can
            # still salt the router-jitter key inside the body)
            x, aux_stack = jax.lax.scan(
                body, x,
                (params["layers"], jnp.arange(cfg.n_layers, dtype=jnp.int32)),
            )
            aux_total = {k: jnp.sum(v) for k, v in aux_stack.items()}
        else:
            # unrolled: per-layer params, either static slices of the
            # stacked tree (same checkpoint layout as scan) or direct
            # leaves of the unstacked tuple (no slice-out copies)
            aux_total = None
            for i in range(cfg.n_layers):
                lp = (
                    jax.tree_util.tree_map(lambda l: l[i], params["layers"])
                    if cfg.stack_layers
                    else params["layers"][i]
                )
                x, aux = layer_fn(lp, x, i, token_mask)
                aux_total = (
                    aux
                    if aux_total is None
                    else {k: aux_total[k] + aux[k] for k in aux_total}
                )
        if self._zig is not None:
            x = x[:, self._zig_inv]
        x = layer_norm(params["ln_f"], x)
        aux_mean = {k: v / cfg.n_layers for k, v in aux_total.items()}
        return x, aux_mean

    def _head(self, params: Params) -> jax.Array:
        # compute dtype (bf16 on TPU), NOT f32: the MXU runs bf16 operands
        # at full rate with f32 accumulation (preferred_element_type at
        # the logits matmul); an f32 operand forces the slow multi-pass
        # path — measured as the dominant cost of the chunked CE.
        return (
            params["embed"].T
            if self.cfg.tie_embeddings
            else params["lm_head"]
        ).astype(self.cfg.dtype)

    @staticmethod
    def _logits(x: jax.Array, head: jax.Array) -> jax.Array:
        return jnp.einsum(
            "...d,dv->...v", x, head, preferred_element_type=jnp.float32
        )

    def apply(
        self, params: Params, token_ids: jax.Array,
        token_mask: jax.Array | None = None,
    ) -> tuple[jax.Array, dict]:
        """token_ids [B, S] → logits [B, S, V] (f32); aux dict of scalars.
        ``token_mask``: see :meth:`_hidden` (padding-vs-routing)."""
        x, aux_mean = self._hidden(params, token_ids, token_mask)
        return self._logits(x, self._head(params)), aux_mean

    # ---- autoregressive decoding ----

    def decode_model(self) -> "DMoETransformerLM":
        """The model to EVALUATE/DECODE with — identical weights, eval-safe
        routing.

        Two train-time routing behaviors cannot be reproduced
        autoregressively and are switched off here:

        - ``gating='expert_choice'``: each expert picks its top-C tokens
          *of the batch*, so routing is batch-dependent (the documented
          causality leak in ``ops.moe_dispatch.expert_choice_gating``).
          At decode there is no batch to pick from — with one live token,
          capacity clamps to 1 and EVERY expert would select that token,
          a regime the router never saw in training.  Decode therefore
          falls back to token-choice top-k over the same gate affinities
          (the expert-choice paper's own inference recipe is a learned
          router/top-k approximation; plain top-k is the zero-extra-state
          version).  Expect a quality gap vs teacher-forced eval — the
          training CE of an expert-choice model includes routing that
          decode cannot see (BASELINE.md notes this on the CE-parity row).
        - ``router_jitter``: selection noise is a training-only
          regularizer; decode routes on clean gates.

        Memoized: repeated ``generate()`` calls must reuse the same twin
        (and hence its compiled-decoder cache).
        """
        cfg = self.cfg
        changed = {}
        if cfg.gating == "expert_choice":
            import logging

            logging.getLogger(__name__).warning(
                "expert_choice routing is batch-dependent and cannot be "
                "reproduced at autoregressive decode; falling back to "
                "token-choice top-%d routing over the same gate "
                "affinities (see DMoETransformerLM.decode_model)",
                cfg.k,
            )
            changed["gating"] = "topk"
        if cfg.router_jitter:
            changed["router_jitter"] = 0.0
        if not changed:
            return self
        if self._decode_model is None:
            self._decode_model = DMoETransformerLM(
                dataclasses.replace(self.cfg, **changed), self.mesh
            )
        return self._decode_model

    def generate(
        self,
        params: Params,
        prompt_ids: jax.Array,
        max_new_tokens: int,
        temperature: float = 0.0,
        rng: jax.Array | None = None,
        use_cache: bool = False,
    ) -> jax.Array:
        """Greedy (or temperature-sampled) autoregressive decoding.

        prompt_ids: [B, P] int32 with P + max_new_tokens <= seq_len.
        Returns [B, P + max_new_tokens].  Each step re-runs the full
        forward over the fixed-length buffer (static shapes for XLA) —
        the straightforward eval path, not a KV-cache serving stack.
        Routing follows :meth:`decode_model` (token-choice, no jitter).

        Right-padding is masked out of MoE routing via ``token_mask``:
        causality makes padding inert for *attention*, but capacity
        routing is cross-token (slot claims are token-order over the
        flattened [B*S] buffer), so unmasked padding from earlier rows
        could exhaust expert capacity ahead of later rows' real tokens
        and decode output would silently depend on padding occupancy
        (round-3 advisor finding).

        ``use_cache=True`` switches to the incremental KV-cache decoder
        (:meth:`_generate_cached`): O(S·d) per new token instead of the
        full O(S²·d) re-forward.  Routing note: each decode step routes
        only the B live tokens (per-step capacity), whereas the
        re-forward path routes the whole masked buffer — identical
        whenever capacity never binds (generous ``capacity_factor``),
        and the per-step regime is what a serving stack does anyway.
        """
        b, p = prompt_ids.shape
        s = self.cfg.seq_len
        if p == 0:
            raise ValueError(
                "prompt must have at least one token (p=0 would wrap the "
                "first write to the end of the decode buffer)"
            )
        if p + max_new_tokens > s:
            raise ValueError(
                f"prompt ({p}) + max_new_tokens ({max_new_tokens}) exceeds "
                f"seq_len {s}"
            )
        if max_new_tokens < 0:
            # almost certainly caller arithmetic gone negative (e.g. a
            # token budget minus the prompt length) — refuse loudly
            raise ValueError(
                f"max_new_tokens must be >= 0, got {max_new_tokens}"
            )
        if temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if temperature > 0 and rng is None:
            raise ValueError("temperature > 0 requires an rng key")
        if max_new_tokens == 0:
            # nothing to decode (validation above still applies); the
            # cached path would otherwise allocate a (b, 0) output
            # buffer and fail at trace time on .at[:, 0]
            return prompt_ids
        if use_cache:
            if self.cfg.seq_parallel:
                raise NotImplementedError(
                    "use_cache=True does not compose with seq_parallel "
                    "(the cache is not ring-sharded); decode on a "
                    "non-seq-parallel mesh"
                )
            from learning_at_home_tpu.parallel.mesh import data_axes

            n_shards = 1
            for a in data_axes(self.mesh):
                n_shards *= self.mesh.shape[a]
            if b % n_shards or (b * p) % n_shards:
                raise ValueError(
                    f"use_cache=True routes B={b} rows per decode step and "
                    f"B*P={b * p} in prefill, which must divide the mesh's "
                    f"{n_shards} token shards — grow the batch or decode "
                    "without the cache (the re-forward path routes the "
                    "whole buffer and is immune)"
                )
        model = self.decode_model()
        # one compiled decoder per path, cached on the decode twin; jit's
        # own shape/static keying handles (b, p, max_new_tokens,
        # temperature) variation.  Eager tracing of the whole decode loop
        # cost 17.1 s where the compiled call takes 0.07 s (60 tokens,
        # seq 1024, CPU).
        fn = model._gen_jit.get(use_cache)
        if fn is None:
            fn = jax.jit(
                model._generate_cached if use_cache else model._generate_full,
                static_argnums=(2, 3),  # max_new_tokens, temperature
            )
            model._gen_jit[use_cache] = fn
        if rng is None:
            rng = jax.random.PRNGKey(0)  # unused at temperature == 0
        return fn(params, prompt_ids, max_new_tokens, float(temperature), rng)

    def _generate_full(
        self,
        params: Params,
        prompt_ids: jax.Array,
        max_new_tokens: int,
        temperature: float,
        rng: jax.Array,
    ) -> jax.Array:
        """Re-forward decoding: every step runs the full masked forward
        over the fixed-length buffer.  Simple and exactly the training
        graph; O(S²·d) per token — prefer ``use_cache=True`` for long
        buffers."""
        b, p = prompt_ids.shape
        s = self.cfg.seq_len
        buf = jnp.zeros((b, s), prompt_ids.dtype).at[:, :p].set(prompt_ids)

        def step(carry, t):
            buf, rng = carry
            # positions <= t hold real tokens this step; the rest is
            # padding and must not compete for expert capacity
            valid = jnp.broadcast_to(
                jnp.arange(s, dtype=jnp.int32)[None, :] <= t, buf.shape
            )
            logits, _ = self.apply(params, buf, token_mask=valid)
            step_logits = jax.lax.dynamic_index_in_dim(
                logits, t, axis=1, keepdims=False
            )  # [B, V]
            rng, sub = jax.random.split(rng)
            if temperature > 0:  # static: resolved at trace time
                nxt = jax.random.categorical(sub, step_logits / temperature)
            else:
                nxt = jnp.argmax(step_logits, axis=-1)
            nxt = nxt.astype(buf.dtype)
            # all rows write the same column t+1 (static bound covers the
            # scan length; writes are always in range here)
            buf = jax.lax.dynamic_update_slice_in_dim(
                buf, nxt[:, None], t + 1, axis=1
            )
            return (buf, rng), None

        (buf, _), _ = jax.lax.scan(
            step,
            (buf, rng),
            jnp.arange(p - 1, p - 1 + max_new_tokens, dtype=jnp.int32),
        )
        return buf[:, : p + max_new_tokens]

    # ---- incremental (KV-cache) decoding ----

    def _layer_params(self, params: Params, i: int):
        """Layer i's param tree under either layout (stacked / tuple)."""
        if self.cfg.stack_layers:
            return jax.tree_util.tree_map(lambda l: l[i], params["layers"])
        return params["layers"][i]

    @staticmethod
    def _one_query_attention(
        lp, q: jax.Array, k_cache: jax.Array, v_cache: jax.Array, t: jax.Array
    ) -> jax.Array:
        """Attention for ONE query position over the cache — the shared
        :func:`~learning_at_home_tpu.models.trunk.one_query_attention`
        (the swarm KV decoder uses the same function with per-row ``t``,
        so pod and gateway decode steps cannot drift numerically)."""
        return one_query_attention(lp, q, k_cache, v_cache, t)

    def _generate_cached(
        self,
        params: Params,
        prompt_ids: jax.Array,
        max_new_tokens: int,
        temperature: float,
        rng: jax.Array | None,
    ) -> jax.Array:
        """Incremental decode: prefill the KV cache on the prompt, then
        one O(S·d) step per new token.  Called via
        ``generate(use_cache=True)`` on the :meth:`decode_model` (this
        instance already has eval-safe routing)."""
        cfg = self.cfg
        b, p = prompt_ids.shape
        s_cache = p + max_new_tokens
        hd = cfg.d_model // cfg.n_heads
        if rng is None:
            rng = jax.random.PRNGKey(0)  # unused at temperature == 0

        def sample(logits_1d, key):  # [B, V] -> [B]
            if temperature > 0:  # static: resolved at trace time
                return jax.random.categorical(key, logits_1d / temperature)
            return jnp.argmax(logits_1d, axis=-1)

        # ---- prefill: full forward over the prompt, caches filled ----
        x = params["embed"][prompt_ids].astype(cfg.dtype)
        x = x + params["pos"][None, :p].astype(cfg.dtype)
        k_caches, v_caches = [], []
        for i in range(cfg.n_layers):
            lp = self._layer_params(params, i)
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            # same impl as the full forward: the parity guarantee vs the
            # re-forward decoder must survive flash-attention configs
            x = x + output_projection(
                lp, attention_core(q, k, v, cfg.attn_impl)
            )
            moe_in = layer_norm(lp["ln2"], x).reshape(b * p, cfg.d_model)
            moe_out, _ = self.moe(lp["moe"], moe_in, jitter_salt=i)
            x = x + moe_out.reshape(b, p, cfg.d_model)
            kc = jnp.zeros((b, s_cache, cfg.n_heads, hd), k.dtype)
            vc = jnp.zeros_like(kc)
            k_caches.append(jax.lax.dynamic_update_slice(kc, k, (0, 0, 0, 0)))
            v_caches.append(jax.lax.dynamic_update_slice(vc, v, (0, 0, 0, 0)))
        x_last = layer_norm(params["ln_f"], x[:, -1:])
        logits = self._logits(x_last, self._head(params))[:, 0]  # [B, V]
        rng, sub = jax.random.split(rng)
        next_tok = sample(logits, sub).astype(prompt_ids.dtype)

        out_buf = (
            jnp.zeros((b, max_new_tokens), prompt_ids.dtype)
            .at[:, 0].set(next_tok)
        )

        # ---- decode: one position per step, caches appended in place.
        # Caches stay a TUPLE of per-layer arrays (scan carry leaves): a
        # stacked [L, ...] cache would need .at[i].set, which copies the
        # whole stack per layer per step — measured 2x slower end-to-end.
        def step(carry, t):
            k_caches, v_caches, tok, out_buf, rng = carry
            x = params["embed"][tok].astype(cfg.dtype)  # [B, d]
            x = x + jnp.take(
                params["pos"].astype(cfg.dtype), t, axis=0
            )[None, :]
            x = x[:, None, :]  # [B, 1, d]
            k_caches, v_caches = list(k_caches), list(v_caches)
            for i in range(cfg.n_layers):
                lp = self._layer_params(params, i)
                h = layer_norm(lp["ln1"], x)
                q, k, v = qkv_projections(lp, h, cfg.n_heads)
                k_caches[i] = jax.lax.dynamic_update_slice(
                    k_caches[i], k, (0, t, 0, 0)
                )
                v_caches[i] = jax.lax.dynamic_update_slice(
                    v_caches[i], v, (0, t, 0, 0)
                )
                x = x + self._one_query_attention(
                    lp, q, k_caches[i], v_caches[i], t
                )
                moe_in = layer_norm(lp["ln2"], x).reshape(b, cfg.d_model)
                moe_out, _ = self.moe(lp["moe"], moe_in, jitter_salt=i)
                x = x + moe_out.reshape(b, 1, cfg.d_model)
            x = layer_norm(params["ln_f"], x)
            logits = self._logits(x, self._head(params))[:, 0]
            rng, sub = jax.random.split(rng)
            nxt = sample(logits, sub).astype(tok.dtype)
            out_buf = jax.lax.dynamic_update_slice_in_dim(
                out_buf, nxt[:, None], t - p + 1, axis=1
            )
            return (
                tuple(k_caches), tuple(v_caches), nxt, out_buf, rng
            ), None

        if max_new_tokens > 1:
            (_, _, _, out_buf, _), _ = jax.lax.scan(
                step,
                (tuple(k_caches), tuple(v_caches), next_tok, out_buf, rng),
                jnp.arange(p, p + max_new_tokens - 1, dtype=jnp.int32),
            )
        return jnp.concatenate([prompt_ids, out_buf], axis=1)

    # ---- loss / train step ----

    def _fused_ce_or_none(self, x, head, targets, flat_x, flat_t, n):
        """Mean CE via the Pallas streaming-LSE kernel (ops/fused_ce.py)
        when ``ce_impl="fused"`` and the kernel's constraints hold —
        else None, and the caller runs the chunked scan (NOT a full
        [n, V] logits materialization, which would blow the memory bound
        the chunking exists for).

        Multi-device meshes without seq parallelism run the kernel
        per-shard under ``shard_map``: each device computes CE for its
        own batch rows against a replicated head (the kernel's dhead
        cotangent is psum-reduced by the shard_map transpose).  Ring-
        sharded sequences fall back to chunked — the flat token axis
        would interleave shards."""
        if self.cfg.ce_impl != "fused":
            return None
        from learning_at_home_tpu.ops.fused_ce import (
            _check,
            fused_softmax_ce,
        )

        bn, bv = self.cfg.ce_block_n, self.cfg.ce_block_v
        interpret = jax.devices()[0].platform == "cpu"
        if self.mesh.devices.size == 1:
            if _check(flat_x, head, flat_t, bn, bv) is not None:
                return None
            ce_rows = fused_softmax_ce(flat_x, head, flat_t, bn, bv,
                                       interpret)
            return ce_rows.sum() / n

        from learning_at_home_tpu.parallel.mesh import data_axes
        from learning_at_home_tpu.utils.jax_compat import shard_map

        if "seq" in self.mesh.axis_names and self.mesh.shape["seq"] > 1:
            return None
        da = data_axes(self.mesh)
        n_shards = 1
        for a in da:
            n_shards *= self.mesh.shape[a]
        b, s, d = x.shape
        if b % n_shards:
            return None
        n_loc = (b // n_shards) * s
        # the same predicate the kernel enforces, applied to the LOCAL
        # per-shard shapes — one source of truth, so a constraint added
        # to _check keeps meaning "fall back to chunked", never a trace
        # error inside shard_map
        if _check(
            jax.ShapeDtypeStruct((n_loc, d), x.dtype), head,
            jax.ShapeDtypeStruct((n_loc,), jnp.int32), bn, bv,
        ) is not None:
            return None

        def _local_ce(xl, hl, tl):
            bl, sl, dl = xl.shape
            ce_l = fused_softmax_ce(
                xl.reshape(bl * sl, dl), hl, tl.reshape(bl * sl),
                bn, bv, interpret,
            )
            return ce_l.reshape(bl, sl)

        ce_bs = shard_map(
            _local_ce,
            mesh=self.mesh,
            in_specs=(P(da, None, None), P(None, None), P(da, None)),
            out_specs=P(da, None),
            check_vma=False,  # custom_vjp inside has no varying-axes rule
        )(x, head, targets)
        return ce_bs.sum() / n

    def loss_fn(
        self, params: Params, token_ids: jax.Array, targets: jax.Array
    ) -> tuple[jax.Array, dict]:
        """Chunked cross-entropy: the [tokens, V] f32 logits are never
        materialized at once.  Token chunks of ``ce_chunk`` go through the
        head + softmax-CE under ``jax.checkpoint`` inside a ``lax.scan``,
        so peak logits memory is chunk×V and the backward recomputes each
        chunk's logits (one extra head matmul ≈ few % FLOPs).  At the
        256-expert flagship shape this is what lifts the per-chip batch
        from 16 to 64 — the f32 logits (+ cotangents) were the dominant
        activation term."""
        x, aux = self._hidden(params, token_ids)
        head = self._head(params)
        n = x.shape[0] * x.shape[1]
        flat_x = x.reshape(n, x.shape[-1])
        flat_t = targets.reshape(n)

        ce = self._fused_ce_or_none(x, head, targets, flat_x, flat_t, n)
        if ce is not None:
            loss = (
                ce
                + self.cfg.aux_loss_weight * aux["aux_loss"]
                + self.cfg.router_z_weight * aux["router_z_loss"]
            )
            return loss, {"ce": ce, **aux}

        chunk = min(self.cfg.ce_chunk, n)

        def chunk_ce(carry, xt):
            xc, tc = xt
            logits = self._logits(xc, head)
            ce = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
            return carry + ce.sum(), None

        ce_sum = jnp.float32(0)
        main = (n // chunk) * chunk
        if main > chunk:  # scan the divisible prefix in chunk-size pieces
            xs = (
                flat_x[:main].reshape(main // chunk, chunk, -1),
                flat_t[:main].reshape(main // chunk, chunk),
            )
            ce_sum, _ = jax.lax.scan(jax.checkpoint(chunk_ce), ce_sum, xs)
        elif main:
            ce_sum, _ = jax.checkpoint(chunk_ce)(
                ce_sum, (flat_x[:main], flat_t[:main])
            )
        if n > main:  # sub-chunk remainder: one extra checkpointed call,
            # so memory stays chunk-bounded for EVERY n (an indivisible n
            # must not silently re-materialize full [n, V] logits)
            ce_sum, _ = jax.checkpoint(chunk_ce)(
                ce_sum, (flat_x[main:], flat_t[main:])
            )
        ce = ce_sum / n
        loss = (
            ce
            + self.cfg.aux_loss_weight * aux["aux_loss"]
            + self.cfg.router_z_weight * aux["router_z_loss"]
        )
        return loss, {"ce": ce, **aux}

    def init_opt_state(
        self, optimizer: optax.GradientTransformation, params: Params
    ):
        """Optimizer state with correct shardings (expert stacks stay
        expert-sharded; scalars replicated) — plain jit(opt.init) leaves
        outputs on one device, which breaks restore + mixed-device steps."""
        from learning_at_home_tpu.parallel.mesh import opt_state_shardings

        abstract = jax.eval_shape(optimizer.init, params)
        shardings = opt_state_shardings(
            abstract, self.param_shardings(params), params, self.mesh
        )
        return jax.jit(optimizer.init, out_shardings=shardings)(params)

    def make_train_step(
        self, optimizer: optax.GradientTransformation, accum_steps: int = 1
    ) -> Callable:
        """Donating, fully-jitted train step; inputs sharded over the mesh.

        ``accum_steps > 1`` returns a step that takes token_ids/targets of
        shape [accum, batch, seq], runs the microbatches sequentially
        through one ``lax.scan`` (sequential execution is what bounds
        live activations to one microbatch — grad_fn is already the
        differentiated function, so no checkpoint wrapper applies),
        averages the gradients, and applies ONE optimizer update —
        effective batch = accum × batch without the activation HBM of
        the large batch."""
        grad_fn = jax.value_and_grad(self.loss_fn, has_aux=True)
        # FusedOptimizer (ops.fused_adafactor) folds the param add into the
        # optimizer's own final pass — the update tree never hits HBM
        apply_fn = getattr(optimizer, "apply_fused", None)
        if apply_fn is None:
            def apply_fn(params, grads, opt_state):
                # optax transforms expect grads in the param dtype
                grads = jax.tree_util.tree_map(
                    lambda g, p: g.astype(p.dtype), grads, params
                )
                updates, opt_state = optimizer.update(grads, opt_state, params)
                return optax.apply_updates(params, updates), opt_state

        def train_step(params, opt_state, token_ids, targets):
            (loss, metrics), grads = grad_fn(params, token_ids, targets)
            params, opt_state = apply_fn(params, grads, opt_state)
            return params, opt_state, loss, metrics

        def accum_step(params, opt_state, token_ids, targets):
            def micro(carry, xt):
                gsum, lsum, msum = carry
                ids, tgt = xt
                (loss, metrics), grads = grad_fn(params, ids, tgt)
                # accumulate in f32: with the bf16 param_dtype recipe the
                # microbatch grads are bf16, and a bf16 running sum loses
                # ~precision to swamping as accum_steps grows
                gsum = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), gsum, grads
                )
                msum = jax.tree_util.tree_map(jnp.add, msum, metrics)
                return (gsum, lsum + loss, msum), None

            zeros_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
            zeros_m = jax.eval_shape(
                lambda p: grad_fn(p, token_ids[0], targets[0])[0][1], params
            )
            zeros_m = jax.tree_util.tree_map(
                lambda l: jnp.zeros(l.shape, l.dtype), zeros_m
            )
            (gsum, lsum, msum), _ = jax.lax.scan(
                micro,
                (zeros_g, jnp.float32(0), zeros_m),
                (token_ids, targets),
            )
            inv = 1.0 / accum_steps
            # stay f32: the fused optimizer consumes f32 grads directly
            # (its state dtypes key off the PARAM dtype); the optax
            # fallback's apply_fn casts to param dtype itself
            grads = jax.tree_util.tree_map(lambda g: g * inv, gsum)
            params, opt_state = apply_fn(params, grads, opt_state)
            metrics = jax.tree_util.tree_map(lambda m: m * inv, msum)
            return params, opt_state, lsum * inv, metrics

        data_shard = batch_sharding(self.mesh)
        if accum_steps > 1:
            # microbatch axis is leading: prepend None to the batch spec
            data_shard = NamedSharding(
                self.mesh, P(None, *data_shard.spec)
            )
        return jax.jit(
            accum_step if accum_steps > 1 else train_step,
            in_shardings=(None, None, data_shard, data_shard),
            donate_argnums=(0, 1),
        )
