"""Counter-based sampling RNG for the swarm decoders (ISSUE 17).

The serving determinism contracts (preemption-recompute token identity,
coalescing bitwise parity, chunk-size invariance — PR 13) held because
decoding was greedy: argmax is a pure function of the logits, so any
replay of the same positions reproduces the same tokens.  Temperature
sampling with a *stateful* RNG would break every one of those contracts
— a preempted stream replays its prefix, consuming RNG draws a
non-preempted run never made.

This module makes sampled decoding deterministic BY CONSTRUCTION
instead: the random draw for the token at absolute sequence index ``i``
of a stream is keyed on ``(stream_seed, i)`` via the counter-based
threefry generator (``jax.random.fold_in``).  No draw depends on *when*
or *in which batch* a position is decoded — recompute-after-preemption,
coalesced vs solo execution and any prefill chunking all visit the same
``(seed, position)`` pairs and therefore sample the same tokens.  The
same property is what makes exact self-speculative decoding possible:
the verifier recomputes the draw a non-speculative pass would have made
at each position and accepts drafts only where they match
(models/swarm_decoder.py :meth:`verify_step`).

``temperature == 0`` short-circuits to argmax so greedy streams stay
bitwise identical to the pre-sampling decoder.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

_MAX_SEED = 2 ** 63 - 1


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-stream sampling configuration, validated at construction so
    the gateway front door can surface hostile values as well-formed
    error frames (ValueError) before the decode thread sees them."""

    seed: int = 0
    temperature: float = 0.0
    top_p: float = 1.0
    top_k: int = 0

    def __post_init__(self):
        if not (0 <= int(self.seed) <= _MAX_SEED):
            raise ValueError(
                f"seed must be in [0, 2**63), got {self.seed!r}"
            )
        t = float(self.temperature)
        if not math.isfinite(t) or t < 0.0:
            raise ValueError(
                f"temperature must be a finite number >= 0, got "
                f"{self.temperature!r}"
            )
        p = float(self.top_p)
        if not math.isfinite(p) or not 0.0 < p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p!r}"
            )
        if int(self.top_k) < 0:
            raise ValueError(
                f"top_k must be >= 0 (0 disables), got {self.top_k!r}"
            )

    @property
    def greedy(self) -> bool:
        return float(self.temperature) == 0.0

    def to_meta(self) -> dict:
        """The wire representation (gen_submit fields)."""
        return {
            "seed": int(self.seed),
            "temperature": float(self.temperature),
            "top_p": float(self.top_p),
            "top_k": int(self.top_k),
        }


def sample_token(
    logits, params: Optional[SamplingParams], position: int
) -> int:
    """Draw the token at absolute sequence index ``position`` from one
    row of logits.

    ``params is None`` or ``temperature == 0`` is argmax — bitwise the
    pre-sampling greedy decoder.  Otherwise: scale by temperature, apply
    the top-k then top-p masks, and draw with
    ``jax.random.categorical`` under the counter-based key
    ``fold_in(PRNGKey(seed), position)``.  The draw depends only on
    ``(logits, seed, position)`` — never on batch composition or call
    order — which is the whole determinism contract.
    """
    if params is None or params.greedy:
        return int(np.asarray(jnp.argmax(jnp.asarray(logits).reshape(-1))))
    l = jnp.asarray(logits, jnp.float32).reshape(-1)
    l = l / float(params.temperature)
    vocab = int(l.shape[0])
    k = int(params.top_k)
    if 0 < k < vocab:
        # keep everything >= the k-th largest logit (ties kept, so the
        # mask is order-independent and deterministic)
        thresh = jax.lax.top_k(l, k)[0][-1]
        l = jnp.where(l >= thresh, l, -jnp.inf)
    if float(params.top_p) < 1.0:
        # nucleus: stable-sort descending, keep tokens whose PRECEDING
        # cumulative mass is < top_p (the first token always survives)
        order = jnp.argsort(-l)
        probs = jax.nn.softmax(l[order])
        cum = jnp.cumsum(probs)
        keep_sorted = (cum - probs) < float(params.top_p)
        keep = jnp.zeros_like(keep_sorted).at[order].set(keep_sorted)
        l = jnp.where(keep, l, -jnp.inf)
    key = jax.random.fold_in(
        jax.random.PRNGKey(int(params.seed)), int(position)
    )
    return int(np.asarray(jax.random.categorical(key, l)))
