"""Paged KV-cache pool + content-addressed prefix cache (ISSUE 13).

The dense slot-table decoder allocates ``max_slots x seq_len`` KV rows
per layer up front — capacity is burned by the LONGEST possible stream
even when every live stream is short.  This module replaces that memory
model with virtual memory for KV caches (the vLLM/Pallas paged-attention
layout, see /opt/skills/guides/boom_attention_tricks.md §8):

- one static-shape **physical pool** per layer, ``[num_pages, page_len,
  H, hd]``, allocated once;
- a per-slot int32 **page table** ``[max_slots, pages_per_slot]`` maps a
  stream's logical pages to physical pages.  Attention reads go through
  a jit-friendly gather (:func:`~learning_at_home_tpu.models.trunk.
  paged_one_query_attention`); slot capacity is bounded by *tokens in
  flight*, not ``slots x seq_len``;
- physical page 0 is a reserved **scratch page**: unmapped page-table
  entries point at it (gathers read finite garbage that the position
  mask hides) and dead decode rows write their garbage K/V into it
  instead of corrupting live pages.

On top of the pool sits a **content-addressed prefix cache**: after a
prompt finishes prefill, every page fully covered by the prompt is
registered under a chained content hash (page i's key hashes page i-1's
key + page i's token ids — K/V at position j depends only on tokens
``<= j``, so the chain IS the content address).  A later prompt that
walks the same chain maps those physical pages READ-ONLY into its own
page table and skips prefill for the covered tokens; the boundary page
(the first page the new stream will *write* — remaining prompt tail,
then decode tokens) is never shared: a partial content match there is
served copy-on-write into a fresh private page.

Sharing discipline (the "never aliases a writer" invariant, asserted in
:meth:`write_tokens`): a physical page with refcount > 1 is immutable.
Full prompt pages are only written during the prefill that created them
and are registered afterwards; decode writes always land at positions
``>= prompt_len``, past every shareable page.

Ownership: like the decoder that embeds it, a pool instance is
single-threaded by contract — the gateway's ``lah-gw-decode`` thread
owns page tables, the free list and the prefix index exclusively
(docs/CONCURRENCY.md invariant 12).  Counters are plain ints that other
threads may *read* (admission, telemetry) — the same benign monitoring
race as the decoder's live mask.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

_ROOT = b"kv-prefix-root"


# Machine-checked invariants (lah-verify shape: (name, what is asserted)).
# ``kv.*`` rows are enforced by :meth:`PagedKVCache.audit`, run by the
# interleaving explorer after every explored step and by the scheduler's
# quiesce audit; the shared-write ban is asserted inline on every scatter.
VERIFIED_INVARIANTS = (
    ("kv.refcount_conservation",
     "every page's refcount equals its slot-table mappings plus its "
     "prefix-cache hold (plus the scratch pin for page 0)"),
    ("kv.pool_conservation",
     "free-list pages are unreferenced and unique; every non-free page "
     "is referenced — no page is both free and mapped, none leaks"),
    ("kv.scratch_pinned",
     "physical page 0 stays pinned at refcount 1: never allocated, "
     "never freed, never mapped as a slot's logical page"),
    ("kv.no_shared_page_writes",
     "a refcount>1 page is immutable — write_tokens raises on any "
     "write attempt (checked inline, copy-on-write discipline)"),
    ("kv.rollback_private_only",
     "a speculative rollback (truncate_slot) only ever frees PRIVATE "
     "lookahead pages — it raises on any prefix-cache-held or shared "
     "page (checked inline on every truncation)"),
)


class PagePressure(RuntimeError):
    """No free physical page and nothing reclaimable — the caller
    (scheduler/admission) decides whether to requeue, preempt or shed;
    this is backpressure, never a stream error by itself."""


@dataclasses.dataclass
class PrefixEntry:
    """One registered full page of some prompt's KV content."""

    key: bytes  # chained content hash: H(parent.key + tokens)
    parent: bytes  # _ROOT for page 0
    tokens: tuple  # the page_len token ids this page covers
    page_id: int  # physical page holding the K/V (refcount includes us)
    last_used: float = dataclasses.field(default_factory=time.monotonic)


class PagedKVCache:
    """Physical page pool + page tables + prefix index for one decoder."""

    def __init__(
        self,
        *,
        n_layers: int,
        n_heads: int,
        head_dim: int,
        dtype,
        max_slots: int,
        seq_len: int,
        page_len: int = 16,
        num_pages: Optional[int] = None,
        enable_prefix_cache: bool = True,
    ):
        if page_len < 1:
            raise ValueError("page_len must be >= 1")
        self.page_len = int(page_len)
        self.max_slots = int(max_slots)
        self.seq_len = int(seq_len)
        self.pages_per_slot = -(-self.seq_len // self.page_len)  # ceil
        self.padded_seq = self.pages_per_slot * self.page_len
        if num_pages is None:
            # dense-equivalent sizing (+1 for the scratch page): a
            # drop-in pool can always hold what the dense table held.
            # Memory-bound deployments pass fewer pages and lean on
            # admission/preemption.
            num_pages = self.max_slots * self.pages_per_slot + 1
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is scratch)")
        self.num_pages = int(num_pages)
        shape = (self.num_pages, self.page_len, n_heads, head_dim)
        self.k_pools = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        self.v_pools = [jnp.zeros(shape, dtype) for _ in range(n_layers)]
        self.page_table = np.zeros(
            (self.max_slots, self.pages_per_slot), np.int32
        )
        # logical pages present per slot (contiguous from 0)
        self.alloc_count = np.zeros(self.max_slots, np.int32)
        self.refcount = np.zeros(self.num_pages, np.int32)
        self.refcount[0] = 1  # scratch: never allocated, never freed
        self._free: list[int] = list(range(self.num_pages - 1, 0, -1))
        self.enable_prefix_cache = bool(enable_prefix_cache)
        self._entries: dict[bytes, PrefixEntry] = {}
        self._children: dict[bytes, dict[tuple, PrefixEntry]] = {}
        # counters (single-writer on the owning thread; cross-thread
        # reads are benign monitoring)
        self.prefix_hits_total = 0
        self.prefix_hit_tokens_total = 0
        self.prefix_partial_hits_total = 0
        self.prefix_lookups_total = 0
        self.cow_copies_total = 0
        self.pages_reclaimed_total = 0
        self.alloc_failures_total = 0
        self.rollback_pages_total = 0

    # ---- pool accounting ----

    def pages_total(self) -> int:
        return self.num_pages - 1

    def pages_free(self) -> int:
        return len(self._free)

    def pages_used(self) -> int:
        return self.pages_total() - len(self._free)

    def pages_reclaimable(self) -> int:
        """Pages held ONLY by the prefix cache (refcount 1 via their
        entry) — freeable on demand without touching any stream."""
        return sum(
            1 for e in self._entries.values()
            if int(self.refcount[e.page_id]) == 1
        )

    def pages_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.page_len)

    # ---- allocation / mapping (lah-gw-decode thread only) ----

    def _pop_free(self) -> int:
        if not self._free:
            self.reclaim(1)
        if not self._free:
            self.alloc_failures_total += 1
            raise PagePressure(
                f"no free KV pages ({self.pages_used()}/"
                f"{self.pages_total()} in use, 0 reclaimable)"
            )
        return self._free.pop()

    def alloc_slot_page(self, slot: int) -> int:
        """Allocate the slot's NEXT logical page privately."""
        logical = int(self.alloc_count[slot])
        if logical >= self.pages_per_slot:
            raise ValueError(f"slot {slot} already holds every logical page")
        pid = self._pop_free()
        self.refcount[pid] = 1
        self.page_table[slot, logical] = pid
        self.alloc_count[slot] = logical + 1
        return pid

    def map_shared(self, slot: int, entry: PrefixEntry) -> int:
        """Map a prefix-cache page read-only as the slot's next logical
        page (refcount guards it against writes and reclaim)."""
        logical = int(self.alloc_count[slot])
        self.refcount[entry.page_id] += 1
        self.page_table[slot, logical] = entry.page_id
        self.alloc_count[slot] = logical + 1
        entry.last_used = time.monotonic()
        return entry.page_id

    def release_slot(self, slot: int) -> None:
        for logical in range(int(self.alloc_count[slot])):
            self._decref(int(self.page_table[slot, logical]))
        self.page_table[slot, :] = 0
        self.alloc_count[slot] = 0

    def truncate_slot(self, slot: int, n_tokens: int) -> int:
        """Roll a slot's mapping back so it holds exactly the pages
        covering its first ``n_tokens`` positions; trailing logical
        pages return to the free list.  This is the speculative-decode
        rollback: lookahead pages mapped for rejected draft positions
        are released, everything covering committed tokens stays.

        Safety (kv.rollback_private_only, asserted inline): a truncated
        page is always a PRIVATE page — the new position count is at
        least ``prompt_len + 1``, so ``pages_needed(n_tokens)`` strictly
        exceeds the count of registered/shared full prompt pages and the
        truncation range can never reach a prefix-cache hold or a
        refcount>1 mapping.  Hitting one anyway is a refcounting bug,
        never a condition to paper over, so it raises."""
        keep = self.pages_needed(n_tokens)
        held = {e.page_id for e in self._entries.values()}
        released = 0
        for logical in range(int(self.alloc_count[slot]) - 1, keep - 1, -1):
            pid = int(self.page_table[slot, logical])
            if pid in held or int(self.refcount[pid]) != 1:
                raise AssertionError(
                    f"rollback would free non-private page {pid} (slot "
                    f"{slot} logical {logical}, refcount "
                    f"{int(self.refcount[pid])}) — speculative lookahead "
                    "pages must be private (kv.rollback_private_only)"
                )
            self._decref(pid)
            self.page_table[slot, logical] = 0
            self.alloc_count[slot] = logical
            released += 1
        self.rollback_pages_total += released
        return released

    def _decref(self, pid: int) -> None:
        if pid == 0:
            return
        self.refcount[pid] -= 1
        if self.refcount[pid] <= 0:
            self.refcount[pid] = 0
            self._free.append(pid)

    def reclaim(self, n_pages: int) -> int:
        """Evict up to ``n_pages`` LRU *leaf* prefix entries whose page
        nobody maps (refcount 1).  Leaf-first keeps every remaining
        entry reachable from the chain root; parents become leaves as
        their children go."""
        freed = 0
        while freed < n_pages:
            leaves = [
                e for e in self._entries.values()
                if not self._children.get(e.key)
                and int(self.refcount[e.page_id]) == 1
            ]
            if not leaves:
                break
            self._drop_entry(min(leaves, key=lambda e: e.last_used))
            freed += 1
        return freed

    def _drop_entry(self, e: PrefixEntry) -> None:
        del self._entries[e.key]
        kids = self._children.get(e.parent)
        if kids is not None:
            kids.pop(e.tokens, None)
            if not kids:
                del self._children[e.parent]
        self._decref(e.page_id)
        self.pages_reclaimed_total += 1

    # ---- the prefix index ----

    @staticmethod
    def _child_key(parent: bytes, tokens: tuple) -> bytes:
        h = hashlib.blake2b(parent, digest_size=16)
        h.update(np.asarray(tokens, np.int64).tobytes())
        return h.digest()

    def prefix_lookup(self, prompt: Sequence[int]):
        """(full_entries, partial) for a prompt: the chain of fully
        matching registered pages, plus at most one boundary page whose
        content *starts with* the remaining prompt tokens (served
        copy-on-write by the caller).  The match is capped at
        ``len(prompt) - 1``: the last prompt token is always prefilled
        so its logits (the first greedy token) exist."""
        full: list[PrefixEntry] = []
        partial: Optional[tuple[PrefixEntry, int]] = None
        if not self.enable_prefix_cache:
            return full, partial
        self.prefix_lookups_total += 1
        prompt = [int(t) for t in prompt]
        limit = len(prompt) - 1
        parent = _ROOT
        i = 0
        now = time.monotonic()
        while i + self.page_len <= limit:
            kids = self._children.get(parent)
            e = kids.get(tuple(prompt[i:i + self.page_len])) if kids else None
            if e is None:
                break
            e.last_used = now
            full.append(e)
            parent = e.key
            i += self.page_len
        r = limit - i
        if 0 < r < self.page_len:
            want = tuple(prompt[i:i + r])
            for toks, e in (self._children.get(parent) or {}).items():
                if toks[:r] == want:
                    e.last_used = now
                    partial = (e, r)
                    break
        return full, partial

    def register_prefix(self, slot: int, prompt: Sequence[int]) -> int:
        """After a prompt's prefill completes, adopt every full prompt
        page of ``slot`` into the prefix index (pages already mapped
        from the index are simply walked).  Returns entries added."""
        if not self.enable_prefix_cache:
            return 0
        prompt = [int(t) for t in prompt]
        parent = _ROOT
        added = 0
        now = time.monotonic()
        for logical in range(len(prompt) // self.page_len):
            i = logical * self.page_len
            toks = tuple(prompt[i:i + self.page_len])
            kids = self._children.setdefault(parent, {})
            e = kids.get(toks)
            if e is None:
                pid = int(self.page_table[slot, logical])
                if int(self.refcount[pid]) != 1 or pid == 0:
                    # shared without an entry can only mean the entry
                    # raced away (reclaim) — do not adopt a page we do
                    # not exclusively account for
                    break
                key = self._child_key(parent, toks)
                e = PrefixEntry(key, parent, toks, pid, now)
                kids[toks] = e
                self._entries[key] = e
                self.refcount[pid] += 1
                added += 1
            parent = e.key
        if not self._children.get(_ROOT):
            self._children.pop(_ROOT, None)
        return added

    # ---- K/V data plane ----

    def copy_page_rows(self, src_pid: int, dst_pid: int, n_rows: int) -> None:
        """Copy-on-write: clone the first ``n_rows`` K/V rows of a
        shared page into a private page the caller just allocated."""
        for layer in range(len(self.k_pools)):
            self.k_pools[layer] = self.k_pools[layer].at[dst_pid, :n_rows].set(
                self.k_pools[layer][src_pid, :n_rows]
            )
            self.v_pools[layer] = self.v_pools[layer].at[dst_pid, :n_rows].set(
                self.v_pools[layer][src_pid, :n_rows]
            )
        self.cow_copies_total += 1

    def write_tokens(self, layer: int, pids, rows, k, v) -> None:
        """Scatter K/V rows into (physical page, row) coordinates.
        Shared pages are immutable — writing one is a refcounting bug,
        never a race to paper over, so it raises."""
        pids = np.asarray(pids)
        bad = (self.refcount[pids] > 1) & (pids != 0)
        if bad.any():
            raise AssertionError(
                f"write to shared KV page(s) {np.unique(pids[bad])} — "
                "copy-on-write discipline violated"
            )
        pids_j = jnp.asarray(pids, jnp.int32)
        rows_j = jnp.asarray(rows, jnp.int32)
        self.k_pools[layer] = self.k_pools[layer].at[pids_j, rows_j].set(k)
        self.v_pools[layer] = self.v_pools[layer].at[pids_j, rows_j].set(v)

    def audit(self) -> list[str]:
        """Check the ``kv.*`` rows of :data:`VERIFIED_INVARIANTS` against
        the live pool; returns violation strings (empty = clean).  Pure
        accounting — safe to call between any two operations on the
        owning thread (the explorer calls it after every step)."""
        leaks: list[str] = []
        expected = np.zeros(self.num_pages, np.int64)
        expected[0] = 1  # the scratch pin
        for slot in range(self.max_slots):
            for logical in range(int(self.alloc_count[slot])):
                pid = int(self.page_table[slot, logical])
                if pid == 0:
                    leaks.append(
                        f"scratch_pinned: slot {slot} logical {logical} "
                        "maps scratch page 0 as an allocated page"
                    )
                expected[pid] += 1
        for e in self._entries.values():
            expected[e.page_id] += 1
        for pid in range(self.num_pages):
            if int(self.refcount[pid]) != int(expected[pid]):
                leaks.append(
                    f"refcount_conservation: page {pid} refcount "
                    f"{int(self.refcount[pid])} but {int(expected[pid])} "
                    "references exist (slot mappings + prefix holds)"
                )
        free_set = set(self._free)
        if len(free_set) != len(self._free):
            leaks.append(
                "pool_conservation: duplicate page(s) on the free list"
            )
        if 0 in free_set:
            leaks.append("scratch_pinned: scratch page 0 is on the free list")
        for pid in free_set - {0}:
            if int(expected[pid]) or int(self.refcount[pid]):
                leaks.append(
                    f"pool_conservation: free page {pid} is still "
                    "referenced or mapped"
                )
        for pid in range(1, self.num_pages):
            if pid not in free_set and int(self.refcount[pid]) == 0:
                leaks.append(
                    f"pool_conservation: page {pid} leaked — neither "
                    "free nor referenced"
                )
        return leaks

    def stats(self) -> dict:
        return {
            "kv_layout": "paged",
            "kv_page_len": self.page_len,
            "kv_pages_total": self.pages_total(),
            "kv_pages_used": self.pages_used(),
            "kv_pages_reclaimable": self.pages_reclaimable(),
            "prefix_cache": self.enable_prefix_cache,
            "prefix_entries": len(self._entries),
            "prefix_hits_total": self.prefix_hits_total,
            "prefix_hit_tokens_total": self.prefix_hit_tokens_total,
            "prefix_partial_hits_total": self.prefix_partial_hits_total,
            "prefix_lookups_total": self.prefix_lookups_total,
            "cow_copies_total": self.cow_copies_total,
            "pages_reclaimed_total": self.pages_reclaimed_total,
            "alloc_failures_total": self.alloc_failures_total,
            "rollback_pages_total": self.rollback_pages_total,
        }
