"""Self-speculation drafters for the gateway decode loop (ISSUE 17).

A drafter proposes up to ``k`` continuation tokens for one stream from
its committed context (prompt + delivered tokens).  Proposals are pure
*guesses*: the decoder's batched :meth:`~learning_at_home_tpu.models.
swarm_decoder.SwarmKVDecoder.verify_step` recomputes the exact token the
non-speculative decoder would have produced at every drafted position
and accepts only the longest matching prefix, so a bad drafter costs
round-trips, never correctness.  Drafters are therefore STATELESS with
respect to the KV cache — nothing to roll back on rejection, and
preemption-recompute needs no drafter coordination.

Two drafters ship:

- :class:`NGramDrafter` — prompt-copy / suffix-match lookup over the
  committed context.  Zero extra compute and no expert traffic; it wins
  whenever decoding revisits earlier text (repetitive prompts, copy
  tasks, the degenerate loops small greedy models fall into).
- :class:`TruncatedTrunkDrafter` — a truncated-depth forward over the
  first ``draft_layers`` trunk layers with the MoE branch skipped
  entirely.  This reuses the ScMoE shortcut wiring (arXiv:2404.05019,
  PR 7's ``--overlap`` schedule): in the shortcut schedule the MoE
  branch reads the layer *input*, so attention-only shallow layers are
  exactly the local half of the computation — the drafter pays host
  FLOPs but NO network fan-out, which is the resource speculation is
  trying to save.  It samples with the same counter-based RNG
  (models/sampling.py) at the same positions as the verifier, so under
  temperature > 0 draft/target agreement is boosted by shared keys.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from learning_at_home_tpu.models.sampling import SamplingParams, sample_token
from learning_at_home_tpu.models.trunk import (
    attention_core,
    layer_norm,
    output_projection,
    qkv_projections,
)


class NGramDrafter:
    """Longest-suffix-match proposal over the committed context.

    Finds the longest suffix (up to ``max_suffix`` tokens) of the
    context that also occurs earlier, and proposes the tokens that
    followed an earlier occurrence — preferring the most recent
    occurrence with a FULL ``k``-token continuation.  The most recent
    match alone is not enough: in a period-``p`` output loop it sits
    ``p`` positions before the end, so copying only its continuation
    caps proposals at ``p`` tokens (a period-1 loop would never draft
    more than one), wasting the batched verify round-trip; scanning
    back to an occurrence with a full copy window proposes the whole
    ``k``-token loop continuation instead.  Returns ``[]`` when
    nothing matches — an empty proposal degrades to a plain decode
    step, so the fallback is always safe.
    """

    def __init__(self, max_suffix: int = 8):
        if max_suffix < 1:
            raise ValueError("max_suffix must be >= 1")
        self.max_suffix = int(max_suffix)

    def propose(
        self,
        context: Sequence[int],
        k: int,
        sampling: Optional[SamplingParams] = None,
    ) -> list[int]:
        ctx = [int(t) for t in context]
        n = len(ctx)
        if k < 1 or n < 2:
            return []
        for s in range(min(self.max_suffix, n - 1), 0, -1):
            suffix = ctx[-s:]
            best: list[int] = []
            # scan occurrences most-recent-first (exclude the suffix
            # itself); take the first with a full k-token continuation,
            # else the longest partial continuation seen
            for i in range(n - s - 1, -1, -1):
                if ctx[i:i + s] == suffix:
                    out = ctx[i + s:i + s + int(k)]
                    if len(out) >= int(k):
                        return out
                    if len(out) > len(best):
                        best = out
            if best:
                return best
        return []


class TruncatedTrunkDrafter:
    """Shallow attention-only self-drafter over the model's own weights.

    Runs ``k`` autoregressive passes over the last ``window`` context
    tokens through the first ``draft_layers`` layers (attention branch
    only — the MoE fan-out is skipped, which is the point) and projects
    through the shared ``ln_f``/embedding head.  Tokens are drawn by the
    same :func:`~learning_at_home_tpu.models.sampling.sample_token`
    keyed at the same absolute positions the verifier will use.
    """

    def __init__(self, model, params, *, draft_layers: int = 1,
                 window: int = 32):
        cfg = model.cfg
        if not 1 <= draft_layers <= cfg.n_layers:
            raise ValueError(
                f"draft_layers must be in [1, {cfg.n_layers}], got "
                f"{draft_layers}"
            )
        if window < 1:
            raise ValueError("window must be >= 1")
        self.params = params
        self.n_heads = cfg.n_heads
        self.seq_len = int(cfg.seq_len)
        self.draft_layers = int(draft_layers)
        self.window = int(window)

    def propose(
        self,
        context: Sequence[int],
        k: int,
        sampling: Optional[SamplingParams] = None,
    ) -> list[int]:
        toks = [int(t) for t in context]
        if not toks or k < 1:
            return []
        params = self.params
        out: list[int] = []
        for _ in range(int(k)):
            if len(toks) >= self.seq_len:
                break  # the drafted position would be past the pos table
            start = max(0, len(toks) - self.window)
            ids = np.asarray(toks[start:], np.int32)
            x = (
                params["embed"][jnp.asarray(ids)][None]
                + params["pos"][None, start:len(toks)]
            )
            for lp in params["layers"][:self.draft_layers]:
                h = layer_norm(lp["ln1"], x)
                q, kk, v = qkv_projections(lp, h, self.n_heads)
                x = x + output_projection(lp, attention_core(q, kk, v))
                # MoE branch intentionally skipped: the ScMoE shortcut
                # reads the layer input, so attention-only IS the local
                # half — no expert round-trip in the draft path
            x_last = layer_norm(params["ln_f"], x[:, -1])
            logits = x_last @ params["embed"].T
            nxt = sample_token(logits[0], sampling, len(toks))
            out.append(nxt)
            toks.append(nxt)
        return out
