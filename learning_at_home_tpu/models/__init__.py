from learning_at_home_tpu.models.layers import (
    FeedforwardBlock,
    TransformerEncoderBlock,
    NopBlock,
    name_to_block,
    make_expert,
)

__all__ = [
    "FeedforwardBlock",
    "TransformerEncoderBlock",
    "NopBlock",
    "name_to_block",
    "make_expert",
]
