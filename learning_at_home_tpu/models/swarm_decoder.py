"""Incremental (KV-cache) decoding for the SWARM model — the decode core
shared by the serving gateway (gateway/scheduler.py) and the
``generate_lm.py --swarm`` probe.

Pod mode decodes inside one jitted scan (models/transformer.py
``_generate_cached``) because its MoE is a local sharded matmul.  Swarm
mode cannot: every FFN layer is a network fan-out
(``RemoteMixtureOfExperts.dispatch_async``), so the decode step runs
EAGERLY on the host — trunk math in jnp, MoE via the pack-once dispatch —
and the caches live at **static shapes** so streams can join and leave a
running batch (continuous batching) without ever recompiling or
reallocating.  Two KV layouts share every code path above the cache:

- ``kv_layout="dense"`` (default): the original ``[max_slots, S, H, hd]``
  slot table — capacity is burned by the longest POSSIBLE stream;
- ``kv_layout="paged"``: one ``[num_pages, page_len, H, hd]`` pool per
  layer with int32 per-slot page tables (models/kv_pages.py) — capacity
  is bounded by tokens actually in flight, prompts with a shared prefix
  map already-resident pages read-only instead of recomputing them, and
  prefill can run in CHUNKS interleaved with decode.  Decode gathers the
  per-row view through :func:`~learning_at_home_tpu.models.trunk.
  paged_one_query_attention`, which delegates to the identical masked
  softmax — paged decode is bitwise-token-equal to dense (tier-1
  asserted).

Common decode mechanics:

- :meth:`prefill_into_slot` runs a prompt forward for ONE stream and
  writes its K/V into a free slot; under the paged layout it is just
  :meth:`begin_prefill` + an unbounded :meth:`prefill_step`, the pair
  the gateway uses for chunked prefill;
- :meth:`decode_step` advances EVERY live slot by one token in one
  [max_slots]-row trunk pass — per-slot positions ride through
  :func:`~learning_at_home_tpu.models.trunk.one_query_attention` as a
  ``[B,1,1,1]`` mask bound, so streams at different depths share the
  batch; dead rows compute garbage that is never read (dense: their
  rows are re-prefilled before reuse; paged: they write into scratch
  page 0) and are excluded from the MoE fan-out;
- :meth:`evict` frees a slot immediately (no batch-drain barrier);
  paged eviction releases the slot's pages back to the pool.

The MoE fan-out goes through a pluggable ``moe_dispatch`` hook: the
default fires one pack-once dispatch per call; the gateway injects
``ExpertCoalescer.dispatch`` (gateway/coalesce.py) which groups rows of
streams with overlapping expert sets into shared dispatches.  The hook
only ever receives LIVE rows, so correctness never depends on it.

Ownership: a decoder instance is single-threaded by contract — the
gateway's ``lah-gw-decode`` thread owns it (and its page pool)
exclusively (docs/CONCURRENCY.md invariant 12); tests and generate_lm
drive it from one thread.

Decoding is deterministic for GREEDY and SAMPLED streams alike: the
token at absolute sequence index ``i`` is drawn under the counter-based
key ``(stream_seed, i)`` (models/sampling.py), so recompute-after-
preemption, coalescing and any prefill chunking reproduce identical
tokens by construction — the property the bitwise/parity tests and the
A/B gate on.  ``temperature 0`` (the default) short-circuits to argmax
and stays bitwise identical to the original greedy decoder.

That same determinism makes EXACT self-speculative decoding possible:
:meth:`verify_step` takes drafted continuations for many streams,
writes all drafted positions, runs ONE multi-row trunk pass (one
coalesced expert fan-out per layer instead of one per token), re-draws
the token every drafted position would have produced, accepts the
longest matching prefix plus the bonus sample, and rolls the KV pages
back past the first rejection (:meth:`PagedKVCache.truncate_slot`) —
output is token-identical to non-speculative decoding, only the number
of expert round-trips changes.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from learning_at_home_tpu.models.kv_pages import PagedKVCache, PagePressure
from learning_at_home_tpu.models.sampling import SamplingParams, sample_token
from learning_at_home_tpu.models.trunk import (
    attention_core,
    layer_norm,
    one_query_attention,
    output_projection,
    paged_one_query_attention,
    qkv_projections,
)

logger = logging.getLogger(__name__)


def default_moe_dispatch(layer, moe, gate_params, x_rows, row_streams):
    """One pack-once dispatch for all rows of one decode/prefill call —
    gate in jnp (differentiability is irrelevant here, but the math must
    match training's :meth:`RemoteMixtureOfExperts.__call__` exactly,
    hence the shared ``gate_logits``), fire, join, combine.
    ``row_streams`` is unused: this is the ungrouped baseline the
    coalescer is benched and tested against."""
    x_rows = jnp.asarray(x_rows)
    logits_concat = moe.gate_logits(gate_params, x_rows)
    fut = moe.dispatch_async(
        np.asarray(x_rows), np.asarray(logits_concat), store_session=False
    )
    y, idx, mask, _cid = fut.join()
    return moe._combine(y, idx, mask, logits_concat)


class SwarmKVDecoder:
    """Slot-table KV-cache decoder over a ``SwarmDMoETransformerLM``.

    ``max_slots`` concurrent streams, each up to ``seq_len`` total
    positions (prompt + generated).  All arrays are allocated once at
    construction; stream churn mutates per-slot scalars and overwrites
    cache rows (dense) or remaps page tables (paged) in place.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        max_seq_len: Optional[int] = None,
        moe_dispatch: Optional[Callable] = None,
        kv_layout: str = "dense",
        page_len: int = 16,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
    ):
        cfg = model.cfg
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got {kv_layout!r}"
            )
        self.model = model
        self.params = params
        self.max_slots = int(max_slots)
        self.seq_len = int(max_seq_len or cfg.seq_len)
        if self.seq_len > cfg.seq_len:
            raise ValueError(
                f"max_seq_len {self.seq_len} exceeds the model's position "
                f"table ({cfg.seq_len})"
            )
        hd = cfg.d_model // cfg.n_heads
        self.kv_layout = kv_layout
        if kv_layout == "paged":
            self.kv: Optional[PagedKVCache] = PagedKVCache(
                n_layers=cfg.n_layers,
                n_heads=cfg.n_heads,
                head_dim=hd,
                dtype=cfg.dtype,
                max_slots=self.max_slots,
                seq_len=self.seq_len,
                page_len=page_len,
                num_pages=num_pages,
                enable_prefix_cache=prefix_cache,
            )
            self.k_caches = self.v_caches = None
        else:
            self.kv = None
            shape = (self.max_slots, self.seq_len, cfg.n_heads, hd)
            self.k_caches = [
                jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)
            ]
            self.v_caches = [
                jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)
            ]
        # per-slot scalars (host side — only the owning thread touches them)
        self.pos = np.zeros(self.max_slots, np.int32)  # cached positions == t
        self.last_tok = np.zeros(self.max_slots, np.int32)
        self.live = np.zeros(self.max_slots, bool)
        # mid-prefill slots (paged chunked prefill only): hold pages and a
        # slot but are not yet decodable
        self.prefilling = np.zeros(self.max_slots, bool)
        self._prefill_prompt: list = [None] * self.max_slots
        self.stream_ids: list = [None] * self.max_slots
        # per-slot SamplingParams (None = greedy, the argmax fast path)
        self.sampling: list = [None] * self.max_slots
        self._moe_dispatch = moe_dispatch or default_moe_dispatch
        self.prefills_total = 0
        self.prefill_chunks_total = 0
        self.decode_steps_total = 0
        self.verify_rounds_total = 0
        # most recent verify_step outcome, one record per slot — the
        # scheduler audit recomputes longest-prefix acceptance from it
        # (scheduler.spec_prefix_accept)
        self.last_verify: list = []

    # ---- slot bookkeeping ----

    @property
    def supports_chunked_prefill(self) -> bool:
        return self.kv is not None

    def free_slots(self) -> list[int]:
        return [
            i for i in range(self.max_slots)
            if not self.live[i] and not self.prefilling[i]
        ]

    def live_slots(self) -> list[tuple[int, object]]:
        """(slot, stream_id) for every DECODING slot, slot order
        (mid-prefill slots are not yet decodable)."""
        return [
            (i, self.stream_ids[i])
            for i in range(self.max_slots)
            if self.live[i]
        ]

    def prefilling_slots(self) -> list[tuple[int, object]]:
        """(slot, stream_id) for every mid-prefill slot, slot order."""
        return [
            (i, self.stream_ids[i])
            for i in range(self.max_slots)
            if self.prefilling[i]
        ]

    def busy_slots(self) -> list[int]:
        """Slots live OR mid-prefill — the decoder-side ownership set the
        scheduler's :meth:`SlotScheduler.audit` reconciles against its
        stream table (slot-table leak freedom)."""
        return [
            int(s) for s in np.nonzero(self.live | self.prefilling)[0]
        ]

    def at_capacity(self, slot: int) -> bool:
        """True when the slot has no cache row left for another token."""
        return int(self.pos[slot]) >= self.seq_len

    def evict(self, slot: int) -> None:
        """Free a slot immediately (decoding OR mid-prefill).  Cache
        content is NOT zeroed: dense rows are overwritten by the next
        prefill and masked until then; paged pages go back to the free
        list (or stay resident for the prefix cache if registered)."""
        self.live[slot] = False
        self.prefilling[slot] = False
        self._prefill_prompt[slot] = None
        self.stream_ids[slot] = None
        self.sampling[slot] = None
        self.pos[slot] = 0
        if self.kv is not None:
            self.kv.release_slot(slot)

    # ---- paged capacity surface (read by scheduler/admission) ----

    def pages_needed(self, prompt_len: int, max_new_tokens: int = 0) -> int:
        """Physical pages a stream of this shape will occupy at peak
        (0 under the dense layout — admission falls back to slots)."""
        if self.kv is None:
            return 0
        total = min(int(prompt_len) + int(max_new_tokens), self.seq_len)
        return self.kv.pages_needed(total)

    def free_page_headroom(self) -> Optional[int]:
        """Free + reclaimable pages minus one-per-active-slot reserve
        (every live/prefilling stream may need one more page within a
        step).  None under the dense layout.  Read cross-thread by
        admission — plain-int reads, the same benign monitoring race as
        the live mask."""
        if self.kv is None:
            return None
        active = int((self.live | self.prefilling).sum())
        return (
            self.kv.pages_free() + self.kv.pages_reclaimable() - active
        )

    def kv_stats(self) -> dict:
        if self.kv is None:
            return {"kv_layout": "dense"}
        return self.kv.stats()

    # ---- prefill: one stream's prompt forward into a free slot ----

    def _check_prompt(self, slot: int, prompt_ids) -> np.ndarray:
        if self.live[slot] or self.prefilling[slot]:
            raise ValueError(f"slot {slot} is occupied")
        prompt = np.asarray(prompt_ids, np.int32)
        p = int(prompt.shape[0])
        if not 0 < p < self.seq_len:
            raise ValueError(
                f"prompt length {p} must be in [1, {self.seq_len - 1}] "
                "(one free position is needed to decode)"
            )
        return prompt

    def prefill_into_slot(self, slot: int, prompt_ids, stream_id=None,
                          sampling: Optional[SamplingParams] = None) -> int:
        """Full forward over one prompt; K/V written into ``slot``;
        returns the first token (argmax, or the counter-keyed draw when
        ``sampling`` has temperature > 0).  The trunk math is exactly
        ``SwarmDMoETransformerLM.apply`` (trunk.py helpers), so a decoder
        parity test against a re-forward holds to numerical noise.
        Paged layout: one unbounded chunk through the chunked-prefill
        path (and the prefix cache still applies)."""
        if self.kv is not None:
            self.begin_prefill(
                slot, prompt_ids, stream_id=stream_id, sampling=sampling
            )
            tok = None
            while tok is None:
                _consumed, tok = self.prefill_step(slot, self.seq_len)
            return tok
        prompt = self._check_prompt(slot, prompt_ids)
        p = int(prompt.shape[0])
        cfg = self.model.cfg
        params = self.params
        x = params["embed"][jnp.asarray(prompt)][None] + params["pos"][None, :p]
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            x = x + output_projection(lp, attention_core(q, k, v))
            self.k_caches[i] = self.k_caches[i].at[slot, :p].set(k[0])
            self.v_caches[i] = self.v_caches[i].at[slot, :p].set(v[0])
            moe_in = layer_norm(lp["ln2"], x).reshape(p, cfg.d_model)
            y = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in, [stream_id] * p
            )
            x = x + jnp.asarray(y).reshape(1, p, cfg.d_model).astype(x.dtype)
        x_last = layer_norm(params["ln_f"], x[:, -1])
        logits = x_last @ params["embed"].T
        # the first generated token sits at absolute index p — that is
        # its counter-RNG key position (greedy: plain argmax)
        tok = sample_token(logits[0], sampling, p)
        self.pos[slot] = p
        self.last_tok[slot] = tok
        self.live[slot] = True
        self.stream_ids[slot] = stream_id
        self.sampling[slot] = sampling
        self.prefills_total += 1
        return tok

    def begin_prefill(self, slot: int, prompt_ids, stream_id=None,
                      sampling: Optional[SamplingParams] = None) -> int:
        """Claim ``slot`` for a prompt under the paged layout and serve
        whatever the prefix cache already holds: fully matching pages
        are mapped read-only into the slot's page table, a partial match
        on the boundary page is copied into a fresh private page
        (copy-on-write — shared pages are never written).  Returns the
        number of prompt tokens whose prefill is skipped; the rest is
        computed by :meth:`prefill_step` calls.  Raises
        :class:`PagePressure` (slot left clean) if the boundary copy
        cannot get a page."""
        if self.kv is None:
            raise ValueError("begin_prefill requires kv_layout='paged'")
        prompt = self._check_prompt(slot, prompt_ids)
        prompt_list = [int(t) for t in prompt]
        full, partial = self.kv.prefix_lookup(prompt_list)
        matched = 0
        try:
            for e in full:
                self.kv.map_shared(slot, e)
            matched = len(full) * self.kv.page_len
            if partial is not None:
                e, r = partial
                dst = self.kv.alloc_slot_page(slot)
                self.kv.copy_page_rows(e.page_id, dst, r)
                matched += r
                self.kv.prefix_partial_hits_total += 1
        except PagePressure:
            self.kv.release_slot(slot)
            raise
        if matched:
            self.kv.prefix_hits_total += 1
            self.kv.prefix_hit_tokens_total += matched
        self.prefilling[slot] = True
        self._prefill_prompt[slot] = prompt_list
        self.pos[slot] = matched
        self.stream_ids[slot] = stream_id
        self.sampling[slot] = sampling
        return matched

    def prefill_step(self, slot: int, max_tokens: int):
        """Advance ``slot``'s prefill by up to ``max_tokens`` prompt
        tokens in ONE trunk pass (multi-query attention over the paged
        cache; K/V are written before the gather so within-chunk
        causality holds).  Returns ``(consumed, first_token_or_None)``
        — the token is produced when the prompt completes, at which
        point the slot turns live and its full pages are registered in
        the prefix cache.  Raises :class:`PagePressure` if the chunk
        needs a page the pool cannot supply; already-written pages stay
        mapped, so the call is retryable (or the scheduler preempts)."""
        if self.kv is None:
            raise ValueError("prefill_step requires kv_layout='paged'")
        if not self.prefilling[slot]:
            raise ValueError(f"slot {slot} is not mid-prefill")
        if max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        prompt = self._prefill_prompt[slot]
        p = len(prompt)
        start = int(self.pos[slot])
        c = min(int(max_tokens), p - start)
        pages = self.kv.pages_needed(start + c)
        while int(self.kv.alloc_count[slot]) < pages:
            self.kv.alloc_slot_page(slot)  # may raise PagePressure
        cfg = self.model.cfg
        params = self.params
        chunk = prompt[start:start + c]
        positions = np.arange(start, start + c, dtype=np.int32)
        pids = self.kv.page_table[slot, positions // self.kv.page_len]
        rows = positions % self.kv.page_len
        pt_row = jnp.asarray(self.kv.page_table[slot:slot + 1])
        t_q = jnp.asarray(positions)[None, None, :, None]  # [1,1,C,1]
        x = (
            params["embed"][jnp.asarray(np.asarray(chunk, np.int32))][None]
            + params["pos"][None, start:start + c]
        )
        sid = self.stream_ids[slot]
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            self.kv.write_tokens(i, pids, rows, k[0], v[0])
            x = x + paged_one_query_attention(
                lp, q, self.kv.k_pools[i], self.kv.v_pools[i], pt_row, t_q
            )
            moe_in = layer_norm(lp["ln2"], x).reshape(c, cfg.d_model)
            y = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in, [sid] * c
            )
            x = x + jnp.asarray(y).reshape(1, c, cfg.d_model).astype(x.dtype)
        self.pos[slot] = start + c
        self.prefill_chunks_total += 1
        if start + c < p:
            return c, None
        x_last = layer_norm(params["ln_f"], x[:, -1])
        logits = x_last @ params["embed"].T
        # key position p: the token produced by a p-token prompt sits at
        # absolute index p regardless of how the prefill was chunked
        tok = sample_token(logits[0], self.sampling[slot], p)
        self.kv.register_prefix(slot, prompt)
        self.last_tok[slot] = tok
        self.live[slot] = True
        self.prefilling[slot] = False
        self._prefill_prompt[slot] = None
        self.prefills_total += 1
        return c, tok

    def ensure_decode_pages(self) -> list[int]:
        """Map a physical page for every live slot's next decode
        position; returns the slots that could NOT get one after
        reclaim (page pressure) — the scheduler preempts those before
        calling :meth:`decode_step`.  No-op under the dense layout."""
        if self.kv is None:
            return []
        lacking = []
        for s in np.nonzero(self.live)[0]:
            s = int(s)
            if self.at_capacity(s):
                continue
            logical = int(self.pos[s]) // self.kv.page_len
            while int(self.kv.alloc_count[s]) <= logical:
                try:
                    self.kv.alloc_slot_page(s)
                except PagePressure:
                    lacking.append(s)
                    break
        return lacking

    # ---- decode: one token for every live slot in one batch ----

    def decode_step(self) -> np.ndarray:
        """Advance every live slot by one token.  Returns the [max_slots]
        int32 next-token array — entries at dead slots are garbage.  The
        trunk runs at the static [max_slots] batch (dead rows compute on
        position-0 garbage, never read; under the paged layout their
        writes land in scratch page 0); the MoE fan-out sees only the
        live rows."""
        live_rows = np.nonzero(self.live)[0]
        if live_rows.size == 0:
            return np.zeros(self.max_slots, np.int32)
        if any(self.at_capacity(int(s)) for s in live_rows):
            raise ValueError("a live slot is at capacity — evict it first")
        cfg = self.model.cfg
        params = self.params
        b = self.max_slots
        t = np.where(self.live, self.pos, 0).astype(np.int32)
        t_j = jnp.asarray(t)
        if self.kv is not None:
            logical = np.minimum(
                t // self.kv.page_len, self.kv.pages_per_slot - 1
            )
            if (self.live & (self.kv.alloc_count <= logical)).any():
                raise ValueError(
                    "a live slot has no KV page for its decode position — "
                    "call ensure_decode_pages() first"
                )
            pids = np.where(
                self.live,
                self.kv.page_table[np.arange(b), logical],
                0,
            ).astype(np.int32)
            rows = np.where(self.live, t % self.kv.page_len, 0).astype(
                np.int32
            )
            pt = jnp.asarray(self.kv.page_table)
        rows_idx = jnp.arange(b)
        x = params["embed"][jnp.asarray(self.last_tok)] + params["pos"][t_j]
        x = x[:, None, :]  # [B, 1, d]
        live_j = jnp.asarray(live_rows)
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            if self.kv is not None:
                self.kv.write_tokens(i, pids, rows, k[:, 0], v[:, 0])
                x = x + paged_one_query_attention(
                    lp, q, self.kv.k_pools[i], self.kv.v_pools[i], pt,
                    t_j[:, None, None, None],
                )
            else:
                self.k_caches[i] = (
                    self.k_caches[i].at[rows_idx, t_j].set(k[:, 0])
                )
                self.v_caches[i] = (
                    self.v_caches[i].at[rows_idx, t_j].set(v[:, 0])
                )
                x = x + one_query_attention(
                    lp, q, self.k_caches[i], self.v_caches[i],
                    t_j[:, None, None, None],
                )
            moe_in = layer_norm(lp["ln2"], x).reshape(b, cfg.d_model)
            y_rows = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in[live_j],
                [self.stream_ids[int(r)] for r in live_rows],
            )
            moe_out = (
                jnp.zeros((b, cfg.d_model), x.dtype)
                .at[live_j].set(jnp.asarray(y_rows).astype(x.dtype))
            )
            x = x + moe_out[:, None, :]
        x = layer_norm(params["ln_f"], x)
        logits = x[:, 0] @ params["embed"].T
        nxt = np.array(jnp.argmax(logits, axis=-1), np.int32)  # writable
        # sampled rows override their argmax entry per-row; greedy rows
        # keep the vectorized argmax value bitwise untouched.  A slot at
        # position ``pos`` decodes the token at absolute index pos+1 —
        # its counter-RNG key position.
        for s in live_rows:
            s = int(s)
            sp = self.sampling[s]
            if sp is not None and not sp.greedy:
                nxt[s] = sample_token(logits[s], sp, int(self.pos[s]) + 1)
        self.last_tok[self.live] = nxt[self.live]
        self.pos[self.live] += 1
        self.decode_steps_total += 1
        return nxt

    # ---- speculative decode: k drafted tokens per swarm round-trip ----

    def ensure_lookahead_pages(self, slot: int, k: int) -> int:
        """Map physical pages covering positions ``pos .. pos+k`` of a
        live slot (the rows a k-draft :meth:`verify_step` writes) and
        return the largest ``k' <= k`` actually covered — page pressure
        clamps the proposal instead of failing the round.  Extra pages
        kept for a clamped/rejected draft are returned to the pool by
        the rollback inside :meth:`verify_step`.  Under the dense layout
        every position is preallocated, so ``k`` comes straight back.
        The caller must already have secured the page for position
        ``pos`` itself (:meth:`ensure_decode_pages`)."""
        if self.kv is None:
            return int(k)
        pos = int(self.pos[slot])
        top = min(pos + int(k), self.seq_len - 1)
        want = top // self.kv.page_len  # logical page of the last row
        while int(self.kv.alloc_count[slot]) <= want:
            try:
                self.kv.alloc_slot_page(slot)
            except PagePressure:
                break
        covered = int(self.kv.alloc_count[slot]) * self.kv.page_len - 1
        return max(0, min(int(k), covered - pos))

    def verify_step(self, proposals: dict) -> dict:
        """Advance every slot in ``proposals`` by 1..k+1 tokens in ONE
        trunk pass — the speculative replacement for :meth:`decode_step`.

        ``proposals`` maps slot -> drafted token list (possibly empty —
        an empty proposal is exactly a plain decode row).  For a slot at
        position ``pos`` with last token ``t`` and drafts ``d_0..d_{k-1}``
        the pass runs k+1 rows with inputs ``[t, d_0, .., d_{k-1}]`` at
        positions ``pos .. pos+k`` (K/V written before the gather, so
        within-pass causality holds exactly as in chunked prefill).  Row
        ``j`` yields the sample ``s_j`` the NON-speculative decoder
        would have produced at absolute index ``pos+j+1`` given the
        drafted context; acceptance is the longest prefix with
        ``d_j == s_j``, and the bonus sample past it is always valid
        because its row saw only accepted context — so the slot commits
        ``s_0..s_a`` (a = accepted count) and the output is
        token-identical to decoding one-by-one.  Rejected lookahead
        pages are rolled back via :meth:`PagedKVCache.truncate_slot`.

        All rows are live, so the MoE hook sees one flattened row batch
        per layer — k tokens per stream cost ONE coalesced expert
        fan-out per layer instead of k.

        Returns ``{slot: {"tokens": [..], "accepted": a, "proposed": k}}``.
        """
        if not proposals:
            return {}
        slots = sorted(int(s) for s in proposals)
        row_slot: list[int] = []
        row_tok: list[int] = []
        row_pos: list[int] = []
        for s in slots:
            if not self.live[s]:
                raise ValueError(f"slot {s} is not live")
            drafts = [int(t) for t in proposals[s]]
            pos = int(self.pos[s])
            if pos + len(drafts) > self.seq_len - 1:
                raise ValueError(
                    f"slot {s}: {len(drafts)} drafts at position {pos} "
                    f"exceed the cache ({self.seq_len} positions)"
                )
            if self.kv is not None:
                want = (pos + len(drafts)) // self.kv.page_len
                if int(self.kv.alloc_count[s]) <= want:
                    raise ValueError(
                        f"slot {s} has no KV page for its lookahead — "
                        "call ensure_lookahead_pages() first"
                    )
            for j, tok in enumerate([int(self.last_tok[s])] + drafts):
                row_slot.append(s)
                row_tok.append(tok)
                row_pos.append(pos + j)
        cfg = self.model.cfg
        params = self.params
        r = len(row_tok)
        row_slot_np = np.asarray(row_slot, np.int32)
        row_pos_np = np.asarray(row_pos, np.int32)
        pos_j = jnp.asarray(row_pos_np)
        if self.kv is not None:
            pids = self.kv.page_table[
                row_slot_np, row_pos_np // self.kv.page_len
            ].astype(np.int32)
            rows = (row_pos_np % self.kv.page_len).astype(np.int32)
            pt_rows = jnp.asarray(self.kv.page_table[row_slot_np])
        else:
            slot_j = jnp.asarray(row_slot_np)
        x = (
            params["embed"][jnp.asarray(np.asarray(row_tok, np.int32))]
            + params["pos"][pos_j]
        )
        x = x[:, None, :]  # [R, 1, d]
        row_streams = [self.stream_ids[s] for s in row_slot]
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            if self.kv is not None:
                self.kv.write_tokens(i, pids, rows, k[:, 0], v[:, 0])
                x = x + paged_one_query_attention(
                    lp, q, self.kv.k_pools[i], self.kv.v_pools[i],
                    pt_rows, pos_j[:, None, None, None],
                )
            else:
                self.k_caches[i] = (
                    self.k_caches[i].at[slot_j, pos_j].set(k[:, 0])
                )
                self.v_caches[i] = (
                    self.v_caches[i].at[slot_j, pos_j].set(v[:, 0])
                )
                x = x + one_query_attention(
                    lp, q, self.k_caches[i][slot_j],
                    self.v_caches[i][slot_j],
                    pos_j[:, None, None, None],
                )
            moe_in = layer_norm(lp["ln2"], x).reshape(r, cfg.d_model)
            y_rows = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in, row_streams
            )
            x = x + jnp.asarray(y_rows).reshape(
                r, 1, cfg.d_model
            ).astype(x.dtype)
        x = layer_norm(params["ln_f"], x)
        logits = np.asarray(x[:, 0] @ params["embed"].T)
        out: dict = {}
        self.last_verify = []
        row = 0
        for s in slots:
            drafts = [int(t) for t in proposals[s]]
            pos = int(self.pos[s])
            sp = self.sampling[s]
            samples = [
                sample_token(logits[row + j], sp, pos + j + 1)
                for j in range(len(drafts) + 1)
            ]
            row += len(drafts) + 1
            a = 0
            while a < len(drafts) and drafts[a] == samples[a]:
                a += 1
            tokens = samples[:a + 1]  # accepted drafts + the bonus draw
            self.pos[s] = pos + a + 1
            self.last_tok[s] = tokens[-1]
            if self.kv is not None:
                self.kv.truncate_slot(s, int(self.pos[s]))
            out[s] = {
                "tokens": tokens, "accepted": a, "proposed": len(drafts)
            }
            self.last_verify.append({
                "slot": s, "stream_id": self.stream_ids[s],
                "drafts": drafts, "samples": samples,
                "accepted": a, "tokens": list(tokens),
            })
        self.verify_rounds_total += 1
        return out

    # ---- convenience: closed-loop batch generation ----

    def generate(
        self, prompts: Sequence[Sequence[int]], max_new_tokens: int,
        sampling: Optional[Sequence] = None,
    ) -> list[list[int]]:
        """Decode a fixed batch of prompts to completion (no mid-flight
        joins) — the ``generate_lm.py --swarm`` path and the parity
        tests.  Requires an empty decoder with ``len(prompts) <=
        max_slots``.  ``sampling`` is an optional per-prompt list of
        :class:`SamplingParams` (None entries = greedy)."""
        if len(prompts) > len(self.free_slots()):
            raise ValueError(
                f"{len(prompts)} prompts need {len(prompts)} free slots, "
                f"have {len(self.free_slots())}"
            )
        if sampling is None:
            sampling = [None] * len(prompts)
        slots = []
        outs: list[list[int]] = []
        for sid, prompt in enumerate(prompts):
            slot = self.free_slots()[0]
            tok = self.prefill_into_slot(
                slot, prompt, stream_id=sid, sampling=sampling[sid]
            )
            slots.append(slot)
            outs.append([tok])
        for _ in range(max_new_tokens - 1):
            active = [s for s in slots if self.live[s]]
            if not active:
                break
            lacking = self.ensure_decode_pages()
            if lacking:
                raise PagePressure(
                    f"slots {lacking} cannot get a decode page — the pool "
                    "is undersized for this closed-loop batch"
                )
            nxt = self.decode_step()
            for sid, slot in enumerate(slots):
                if self.live[slot]:
                    outs[sid].append(int(nxt[slot]))
                    if (
                        len(outs[sid]) >= max_new_tokens
                        or self.at_capacity(slot)
                    ):
                        self.evict(slot)
        for slot in slots:
            if self.live[slot]:
                self.evict(slot)
        return outs
