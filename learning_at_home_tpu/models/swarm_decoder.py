"""Incremental (KV-cache) decoding for the SWARM model — the decode core
shared by the serving gateway (gateway/scheduler.py) and the
``generate_lm.py --swarm`` probe.

Pod mode decodes inside one jitted scan (models/transformer.py
``_generate_cached``) because its MoE is a local sharded matmul.  Swarm
mode cannot: every FFN layer is a network fan-out
(``RemoteMixtureOfExperts.dispatch_async``), so the decode step runs
EAGERLY on the host — trunk math in jnp, MoE via the pack-once dispatch —
and the caches live at **static shapes** ``[max_slots, S, H, hd]`` so
streams can join and leave a running batch (continuous batching) without
ever recompiling or reallocating:

- :meth:`prefill_into_slot` runs the full prompt forward for ONE stream
  and writes its K/V rows into a free slot;
- :meth:`decode_step` advances EVERY live slot by one token in one
  [max_slots]-row trunk pass — per-slot positions ride through
  :func:`~learning_at_home_tpu.models.trunk.one_query_attention` as a
  ``[B,1,1,1]`` mask bound, so streams at different depths share the
  batch; dead rows compute garbage that is never read (their slots are
  re-prefilled before reuse) and are excluded from the MoE fan-out;
- :meth:`evict` frees a slot immediately (no batch-drain barrier).

The MoE fan-out goes through a pluggable ``moe_dispatch`` hook: the
default fires one pack-once dispatch per call; the gateway injects
``ExpertCoalescer.dispatch`` (gateway/coalesce.py) which groups rows of
streams with overlapping expert sets into shared dispatches.  The hook
only ever receives LIVE rows, so correctness never depends on it.

Ownership: a decoder instance is single-threaded by contract — the
gateway's ``lah-gw-decode`` thread owns it exclusively
(docs/CONCURRENCY.md); tests and generate_lm drive it from one thread.

Greedy decoding only (temperature 0): serving determinism is what the
coalescing bitwise tests and the A/B gate on.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from learning_at_home_tpu.models.trunk import (
    attention_core,
    layer_norm,
    one_query_attention,
    output_projection,
    qkv_projections,
)

logger = logging.getLogger(__name__)


def default_moe_dispatch(layer, moe, gate_params, x_rows, row_streams):
    """One pack-once dispatch for all rows of one decode/prefill call —
    gate in jnp (differentiability is irrelevant here, but the math must
    match training's :meth:`RemoteMixtureOfExperts.__call__` exactly),
    fire, join, combine.  ``row_streams`` is unused: this is the
    ungrouped baseline the coalescer is benched and tested against."""
    x_rows = jnp.asarray(x_rows)
    logits_concat = jnp.concatenate(
        [x_rows @ gate_params[f"w{d}"] for d in range(moe.n_dims)], axis=-1
    )
    fut = moe.dispatch_async(
        np.asarray(x_rows), np.asarray(logits_concat), store_session=False
    )
    y, idx, mask, _cid = fut.join()
    return moe._combine(y, idx, mask, logits_concat)


class SwarmKVDecoder:
    """Slot-table KV-cache decoder over a ``SwarmDMoETransformerLM``.

    ``max_slots`` concurrent streams, each up to ``seq_len`` total
    positions (prompt + generated).  All arrays are allocated once at
    construction; stream churn mutates per-slot scalars and overwrites
    cache rows in place.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        max_seq_len: Optional[int] = None,
        moe_dispatch: Optional[Callable] = None,
    ):
        cfg = model.cfg
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        self.model = model
        self.params = params
        self.max_slots = int(max_slots)
        self.seq_len = int(max_seq_len or cfg.seq_len)
        if self.seq_len > cfg.seq_len:
            raise ValueError(
                f"max_seq_len {self.seq_len} exceeds the model's position "
                f"table ({cfg.seq_len})"
            )
        hd = cfg.d_model // cfg.n_heads
        shape = (self.max_slots, self.seq_len, cfg.n_heads, hd)
        self.k_caches = [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)]
        self.v_caches = [jnp.zeros(shape, cfg.dtype) for _ in range(cfg.n_layers)]
        # per-slot scalars (host side — only the owning thread touches them)
        self.pos = np.zeros(self.max_slots, np.int32)  # cached positions == t
        self.last_tok = np.zeros(self.max_slots, np.int32)
        self.live = np.zeros(self.max_slots, bool)
        self.stream_ids: list = [None] * self.max_slots
        self._moe_dispatch = moe_dispatch or default_moe_dispatch
        self.prefills_total = 0
        self.decode_steps_total = 0

    # ---- slot bookkeeping ----

    def free_slots(self) -> list[int]:
        return [i for i in range(self.max_slots) if not self.live[i]]

    def live_slots(self) -> list[tuple[int, object]]:
        """(slot, stream_id) for every occupied slot, slot order."""
        return [
            (i, self.stream_ids[i])
            for i in range(self.max_slots)
            if self.live[i]
        ]

    def at_capacity(self, slot: int) -> bool:
        """True when the slot has no cache row left for another token."""
        return int(self.pos[slot]) >= self.seq_len

    def evict(self, slot: int) -> None:
        """Free a slot immediately.  Cache rows are NOT zeroed: the next
        prefill overwrites positions [0, p) and every decode step's
        attention masks positions > t, so stale rows are unreachable."""
        self.live[slot] = False
        self.stream_ids[slot] = None

    # ---- prefill: one stream's prompt forward into a free slot ----

    def prefill_into_slot(self, slot: int, prompt_ids, stream_id=None) -> int:
        """Full forward over one prompt; K/V written into ``slot``;
        returns the first greedy token.  The trunk math is exactly
        ``SwarmDMoETransformerLM.apply`` (trunk.py helpers), so a decoder
        parity test against a re-forward holds to numerical noise."""
        if self.live[slot]:
            raise ValueError(f"slot {slot} is occupied")
        prompt = np.asarray(prompt_ids, np.int32)
        p = int(prompt.shape[0])
        if not 0 < p < self.seq_len:
            raise ValueError(
                f"prompt length {p} must be in [1, {self.seq_len - 1}] "
                "(one free position is needed to decode)"
            )
        cfg = self.model.cfg
        params = self.params
        x = params["embed"][jnp.asarray(prompt)][None] + params["pos"][None, :p]
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            x = x + output_projection(lp, attention_core(q, k, v))
            self.k_caches[i] = self.k_caches[i].at[slot, :p].set(k[0])
            self.v_caches[i] = self.v_caches[i].at[slot, :p].set(v[0])
            moe_in = layer_norm(lp["ln2"], x).reshape(p, cfg.d_model)
            y = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in, [stream_id] * p
            )
            x = x + jnp.asarray(y).reshape(1, p, cfg.d_model).astype(x.dtype)
        x_last = layer_norm(params["ln_f"], x[:, -1])
        logits = x_last @ params["embed"].T
        tok = int(np.asarray(jnp.argmax(logits, axis=-1))[0])
        self.pos[slot] = p
        self.last_tok[slot] = tok
        self.live[slot] = True
        self.stream_ids[slot] = stream_id
        self.prefills_total += 1
        return tok

    # ---- decode: one token for every live slot in one batch ----

    def decode_step(self) -> np.ndarray:
        """Advance every live slot by one token.  Returns the [max_slots]
        int32 next-token array — entries at dead slots are garbage.  The
        trunk runs at the static [max_slots] batch (dead rows compute on
        position-0 garbage, never read); the MoE fan-out sees only the
        live rows."""
        live_rows = np.nonzero(self.live)[0]
        if live_rows.size == 0:
            return np.zeros(self.max_slots, np.int32)
        if any(self.at_capacity(int(s)) for s in live_rows):
            raise ValueError("a live slot is at capacity — evict it first")
        cfg = self.model.cfg
        params = self.params
        b = self.max_slots
        t = np.where(self.live, self.pos, 0).astype(np.int32)
        t_j = jnp.asarray(t)
        rows_idx = jnp.arange(b)
        x = params["embed"][jnp.asarray(self.last_tok)] + params["pos"][t_j]
        x = x[:, None, :]  # [B, 1, d]
        live_j = jnp.asarray(live_rows)
        for i, lp in enumerate(params["layers"]):
            h = layer_norm(lp["ln1"], x)
            q, k, v = qkv_projections(lp, h, cfg.n_heads)
            self.k_caches[i] = self.k_caches[i].at[rows_idx, t_j].set(k[:, 0])
            self.v_caches[i] = self.v_caches[i].at[rows_idx, t_j].set(v[:, 0])
            x = x + one_query_attention(
                lp, q, self.k_caches[i], self.v_caches[i],
                t_j[:, None, None, None],
            )
            moe_in = layer_norm(lp["ln2"], x).reshape(b, cfg.d_model)
            y_rows = self._moe_dispatch(
                i, self.model.moes[i], lp["gate"], moe_in[live_j],
                [self.stream_ids[int(r)] for r in live_rows],
            )
            moe_out = (
                jnp.zeros((b, cfg.d_model), x.dtype)
                .at[live_j].set(jnp.asarray(y_rows).astype(x.dtype))
            )
            x = x + moe_out[:, None, :]
        x = layer_norm(params["ln_f"], x)
        logits = x[:, 0] @ params["embed"].T
        nxt = np.asarray(jnp.argmax(logits, axis=-1), np.int32)
        self.last_tok[self.live] = nxt[self.live]
        self.pos[self.live] += 1
        self.decode_steps_total += 1
        return nxt

    # ---- convenience: closed-loop batch generation ----

    def generate(
        self, prompts: Sequence[Sequence[int]], max_new_tokens: int
    ) -> list[list[int]]:
        """Decode a fixed batch of prompts to completion (no mid-flight
        joins) — the ``generate_lm.py --swarm`` path and the parity
        tests.  Requires an empty decoder with ``len(prompts) <=
        max_slots``."""
        if len(prompts) > len(self.free_slots()):
            raise ValueError(
                f"{len(prompts)} prompts need {len(prompts)} free slots, "
                f"have {len(self.free_slots())}"
            )
        slots = []
        outs: list[list[int]] = []
        for sid, prompt in enumerate(prompts):
            slot = self.free_slots()[0]
            tok = self.prefill_into_slot(slot, prompt, stream_id=sid)
            slots.append(slot)
            outs.append([tok])
        for _ in range(max_new_tokens - 1):
            active = [s for s in slots if self.live[s]]
            if not active:
                break
            nxt = self.decode_step()
            for sid, slot in enumerate(slots):
                if self.live[slot]:
                    outs[sid].append(int(nxt[slot]))
                    if (
                        len(outs[sid]) >= max_new_tokens
                        or self.at_capacity(slot)
                    ):
                        self.evict(slot)
        for slot in slots:
            if self.live[slot]:
                self.evict(slot)
        return outs
