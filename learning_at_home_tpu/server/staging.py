"""Reusable host staging buffers for off-loop batch stacking.

Batch stacking used to run on the asyncio networking loop
(``TaskPool._dispatch``: ``np.concatenate`` + zero-pad per batch, blocking
every connection while host memory churned).  It now runs on the Runtime's
device thread, copying task rows into **preallocated per-bucket buffers**
drawn from this pool — steady-state serving allocates nothing per batch.

Lifecycle contract (enforced by the Runtime, tested in
``tests/test_task_pool_runtime.py``):

- a buffer is checked out for exactly one :class:`BatchJob` and is NOT
  returned until that job's outputs are materialized — two in-flight
  batches of the same bucket never share a buffer, even across pools;
- padding rows are re-zeroed on every checkout (a recycled buffer holds
  the previous batch's rows);
- outputs that alias a staging buffer (a pure-numpy ``process_fn``
  returning its input) are copied before the buffer is recycled.
"""

from __future__ import annotations

import threading

import numpy as np

from learning_at_home_tpu.utils import sanitizer

# keep at most this many idle buffers per (shape, dtype) key: double
# buffering needs 2; a small surplus absorbs pool churn without letting
# a one-off giant bucket pin host memory forever
MAX_FREE_PER_KEY = 4


class StagingBuffers:
    """Free-lists of host arrays keyed by (shape, dtype), with telemetry.

    Thread-safe, though in practice acquire/release both run on the one
    Runtime thread.  ``allocated`` counts fresh ``np.empty`` calls;
    ``reused`` counts checkouts served from the free list — their ratio is
    the steady-state reuse fraction surfaced in server stats.
    """

    def __init__(self, max_free_per_key: int = MAX_FREE_PER_KEY):
        self.max_free_per_key = max_free_per_key
        self._free: dict[tuple, list[np.ndarray]] = {}
        self._lock = sanitizer.lock("server.staging")
        self.allocated = 0
        self.reused = 0

    @staticmethod
    def _key(shape: tuple, dtype) -> tuple:
        return (tuple(int(d) for d in shape), np.dtype(dtype).str)

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        """Check out one buffer of exactly ``shape``/``dtype`` (contents
        undefined — the caller overwrites real rows and zeroes the pad)."""
        key = self._key(shape, dtype)
        with self._lock:
            free = self._free.get(key)
            if free:
                self.reused += 1
                return free.pop()
            self.allocated += 1
        return np.empty(shape, dtype)

    def release(self, buffers) -> None:
        """Return checked-out buffers to their free lists."""
        for buf in buffers:
            key = self._key(buf.shape, buf.dtype)
            with self._lock:
                free = self._free.setdefault(key, [])
                if len(free) < self.max_free_per_key:
                    free.append(buf)

    def stats(self) -> dict:
        with self._lock:
            total = self.allocated + self.reused
            return {
                "allocated": self.allocated,
                "reused": self.reused,
                "reuse_fraction": round(self.reused / total, 4) if total else 0.0,
                "idle_buffers": sum(len(v) for v in self._free.values()),
            }
