"""TaskPool: cross-request dynamic batching with static-shape bucketing.

Contract from the reference's ``hivemind/server/task_pool.py`` (SURVEY.md §2
[BJ]; unverifiable refs, mount empty): accept per-request tasks, each tied to
a future; accumulate into batches up to ``max_batch_size``; oldest-first
priority; hand formed batches to the Runtime and scatter results back.

TPU-native deltas:

- **asyncio, not processes**: tasks arrive on the server's event loop from
  connection handlers; the pool manager is a coroutine.  XLA dispatch
  releases the GIL, so process isolation buys nothing here.
- **Static shapes**: XLA compiles one program per shape.  Arbitrary batch
  sizes would recompile per request, so formed batches are padded up to a
  power-of-two row bucket (≤ ``max_batch_size``).  One compile per bucket,
  amortized forever; padding waste is tracked in :attr:`padded_rows` /
  :attr:`total_rows` and surfaces in the benchmark metrics (SURVEY.md §7
  "hard parts").
- **Off-loop stacking**: the pool manager only FORMS batches (picks tasks,
  computes row spans and the padded bucket — pure metadata).  The actual
  ``np.concatenate``-equivalent — copying task rows into a padded staging
  buffer — happens on the Runtime's device thread via :meth:`BatchJob.stack`,
  so the event loop never blocks on per-batch host memory traffic and the
  copy overlaps the previous batch's device execution.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import numpy as np

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.serialization import LazyDecode

logger = logging.getLogger(__name__)


def _as_task_tensor(t):
    """Batch-formation view of a task tensor: quantized wire payloads
    (``LazyDecode``) expose shape/dtype for validation but are NOT
    materialized here — their dequantize runs on the Runtime thread,
    directly into the staging buffer (``BatchJob.stack``)."""
    return t if isinstance(t, LazyDecode) else np.asarray(t)


def bucket_rows(n: int, max_batch_size: int) -> int:
    """Smallest power-of-two ≥ n, clamped to max_batch_size (the clamp also
    covers non-power-of-two max_batch_size: 600 rows with max 1000 buckets
    to 1000, never 1024)."""
    if n >= max_batch_size:
        return max_batch_size
    return min(1 << (n - 1).bit_length(), max_batch_size) if n > 1 else 1


@dataclass(order=True)
class BatchJob:
    """One formed batch, queued for the Runtime's device thread.

    Carries the RAW per-task tensors; stacking/padding into one batch
    array happens in :meth:`stack` on the Runtime thread, never on the
    event loop.
    """

    priority: float  # oldest task's arrival time → earliest runs first
    seq: int
    pool: "TaskPool" = field(compare=False)
    task_tensors: list = field(compare=False)  # one tuple of arrays per task
    row_spans: list = field(compare=False)  # (task_future, start, stop)
    n_rows: int = field(compare=False)  # real rows before padding
    target_rows: int = field(compare=False, default=0)  # padded bucket size
    # per-input batch dtypes (np.result_type-promoted across tasks, like
    # the old np.concatenate path); None → take the first task's dtypes
    dtypes: Optional[list] = field(compare=False, default=None)
    formed_at: float = field(compare=False, default=0.0)
    # distributed-tracing ids, one per task (None for untraced requests);
    # the Runtime stamps its stage spans when the batch carries exactly
    # one distinct trace — a merged multi-trainer batch has no single
    # owner and stays unstamped
    traces: list = field(compare=False, default_factory=list)

    @sanitizer.runs_on("runtime", site="BatchJob.stack")
    def stack(self, staging) -> tuple[list, list]:
        """Copy task rows into padded staging buffers (Runtime thread).

        Returns ``(inputs, buffers)``: the stacked input arrays and the
        staging buffers to release once outputs are materialized.  A
        single task already filling its bucket passes through zero-copy
        (no buffer checked out).
        """
        if len(self.task_tensors) == 1 and self.target_rows == self.n_rows:
            # zero-copy pass-through for raw tensors; a quantized payload
            # decodes HERE (Runtime thread) — never on the event loop
            return [
                t.decode() if isinstance(t, LazyDecode) else t
                for t in self.task_tensors[0]
            ], []
        buffers: list = []
        inputs: list = []
        for i in range(len(self.task_tensors[0])):
            first = self.task_tensors[0][i]
            dtype = self.dtypes[i] if self.dtypes is not None else first.dtype
            buf = staging.acquire(
                (self.target_rows, *first.shape[1:]), dtype
            )
            buffers.append(buf)
            off = 0
            for tensors in self.task_tensors:
                part = tensors[i]
                if isinstance(part, LazyDecode):
                    # dequantize straight into the staging rows: the wire
                    # payload's only f32 materialization is the batch
                    # buffer itself
                    part.decode_into(buf[off : off + part.shape[0]])
                else:
                    buf[off : off + part.shape[0]] = part
                off += part.shape[0]
            if off < self.target_rows:
                buf[off:] = 0  # recycled buffers hold the previous batch
            inputs.append(buf)
        return inputs, buffers


@dataclass
class _Task:
    tensors: tuple
    future: asyncio.Future
    arrived: float
    n_rows: int
    trace: Optional[str] = None


class TaskPool:
    """Batches tasks for ONE expert computation (forward OR backward).

    ``process_fn(inputs) -> list[np.ndarray]`` runs on the Runtime thread.
    """

    _seq = itertools.count()

    def __init__(
        self,
        process_fn: Callable[[Sequence[np.ndarray]], Sequence[Any]],
        name: str,
        max_batch_size: int = 1024,
        batch_timeout: float = 0.002,
        pad_buckets: bool = True,
        serial_key: Optional[str] = None,
        warm_buckets: Sequence[int] | Callable[[], Sequence[int]] = (),
    ):
        self.process_fn = process_fn
        self.name = name
        self.max_batch_size = max_batch_size
        self.batch_timeout = batch_timeout
        self.pad_buckets = pad_buckets
        # jobs sharing a serial_key are never overlapped by the Runtime's
        # double buffering (forward and backward of one expert both touch
        # its params — backward DONATES them); defaults to this pool alone
        self.serial_key = serial_key if serial_key is not None else name
        self._tasks: asyncio.Queue[_Task] = asyncio.Queue()
        self._carry: Optional[_Task] = None  # oldest task that didn't fit
        self._manager_task: Optional[asyncio.Task] = None
        # padding-waste + latency telemetry (north-star metric plumbing)
        self.total_rows = 0
        self.padded_rows = 0
        self.batches_formed = 0
        # per-bucket batch counts: a bucket's FIRST batch compiles an XLA
        # program (unless AOT-warmed), the rest hit the executable cache.
        # warm_buckets may be a CALLABLE, resolved live at bucket_stats()
        # time so warmup performed after pool construction still counts
        self.bucket_batches: dict[int, int] = {}
        self.warm_buckets = warm_buckets
        self.stack_time = 0.0  # accumulated by the Runtime (its thread)

    async def submit_task(
        self, *tensors: np.ndarray, trace: Optional[str] = None
    ) -> list[np.ndarray]:
        """Submit one task (row-batch of tensors); await its outputs.
        ``trace`` (distributed tracing) rides along so the Runtime can
        stamp this batch's stage spans with the originating request."""
        n_rows = int(tensors[0].shape[0])
        if n_rows > self.max_batch_size:
            raise ValueError(
                f"task of {n_rows} rows exceeds max_batch_size="
                f"{self.max_batch_size} for pool {self.name}"
            )
        future: asyncio.Future = asyncio.get_running_loop().create_future()
        await self._tasks.put(
            _Task(tuple(tensors), future, time.monotonic(), n_rows, trace)
        )
        return await future

    def start(self, runtime) -> None:
        """Begin forming batches and feeding them to ``runtime``."""
        self._manager_task = asyncio.get_running_loop().create_task(
            self._manager(runtime), name=f"pool-manager-{self.name}"
        )

    def shutdown(self) -> None:
        if self._manager_task is not None:
            self._manager_task.cancel()

    async def _manager(self, runtime) -> None:
        loop = asyncio.get_running_loop()
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                first = await self._tasks.get()
            batch = [first]
            rows = first.n_rows
            deadline = loop.time() + self.batch_timeout
            # Greedily absorb concurrent tasks until the bucket is full or
            # the grace window closes — this is the cross-request batching.
            while rows < self.max_batch_size:
                remaining = deadline - loop.time()
                try:
                    if remaining <= 0:
                        task = self._tasks.get_nowait()
                    else:
                        task = await asyncio.wait_for(self._tasks.get(), remaining)
                except (asyncio.TimeoutError, asyncio.QueueEmpty):
                    break
                if rows + task.n_rows > self.max_batch_size:
                    # doesn't fit: hold it as the HEAD of the next batch so
                    # oldest-first ordering survives (re-enqueueing would send
                    # it behind newer arrivals → starvation of large tasks)
                    self._carry = task
                    break
                batch.append(task)
                rows += task.n_rows
            try:
                self._dispatch(batch, rows, runtime)
            except Exception as e:
                # a malformed task (wrong arity/shape/dtype) must fail ITS
                # batch, not kill the manager — that would silently hang
                # every future request to this expert
                logger.exception("failed to form batch in pool %s", self.name)
                for t in batch:
                    if not t.future.done():
                        t.future.set_exception(
                            ValueError(f"batch formation failed: {e}")
                        )

    def _dispatch(self, batch: list[_Task], rows: int, runtime) -> None:
        """Form the job — METADATA ONLY.  No tensor bytes move here: the
        event loop must stay free to serve other connections while the
        Runtime thread does the stacking (and overlaps it with the
        previous batch's device execution)."""
        target = bucket_rows(rows, self.max_batch_size) if self.pad_buckets else rows
        # validate task compatibility up front so a malformed task fails
        # ITS batch here (old np.concatenate semantics: tail-shape or
        # arity mismatch raises; dtype differences PROMOTE via
        # np.result_type, e.g. a stray f64 task widens the batch) instead
        # of surfacing later as a runtime-side stacking error
        first = [_as_task_tensor(t) for t in batch[0].tensors]
        tasks = [tuple(first)]
        dtypes = [np.dtype(a.dtype) for a in first]
        for t in batch[1:]:
            if len(t.tensors) != len(first):
                raise ValueError(
                    f"task arity {len(t.tensors)} != batch arity {len(first)}"
                )
            coerced = []
            for i, tensor in enumerate(t.tensors):
                arr = _as_task_tensor(tensor)
                if arr.shape[1:] != first[i].shape[1:]:
                    raise ValueError(
                        f"task tensor {i} is {arr.dtype}{arr.shape}, batch "
                        f"expects (*, {first[i].shape[1:]})"
                    )
                if arr.dtype != dtypes[i]:
                    dtypes[i] = np.result_type(dtypes[i], arr.dtype)
                coerced.append(arr)
            tasks.append(tuple(coerced))
        spans, start = [], 0
        for t in batch:
            spans.append((t.future, start, start + t.n_rows))
            start += t.n_rows
        self.total_rows += rows
        self.padded_rows += target - rows
        self.batches_formed += 1
        self.bucket_batches[target] = self.bucket_batches.get(target, 0) + 1
        job = BatchJob(
            priority=batch[0].arrived,
            seq=next(self._seq),
            pool=self,
            task_tensors=tasks,
            row_spans=spans,
            n_rows=rows,
            target_rows=target,
            dtypes=dtypes,
            formed_at=time.monotonic(),
            traces=[t.trace for t in batch],
        )
        runtime.submit(job)

    # called back on the event loop by the Runtime after device execution
    def deliver(self, job: BatchJob, outputs, error: Optional[BaseException]) -> None:
        for future, start, stop in job.row_spans:
            if future.cancelled():
                continue
            if error is not None:
                future.set_exception(error)
            else:
                future.set_result([np.asarray(o[start:stop]) for o in outputs])

    @property
    def padding_waste(self) -> float:
        total = self.total_rows + self.padded_rows
        return self.padded_rows / total if total else 0.0

    def bucket_stats(self) -> dict:
        """Per-bucket batch counts with compile/hit accounting: a bucket's
        first batch pays an XLA compile (unless AOT-warmed at startup),
        every later batch hits the executable cache."""
        warm = (
            self.warm_buckets() if callable(self.warm_buckets)
            else self.warm_buckets
        )
        warm = frozenset(int(b) for b in warm)
        cold = sum(1 for b in self.bucket_batches if b not in warm)
        return {
            "batches_per_bucket": dict(sorted(self.bucket_batches.items())),
            "cold_compiles": cold,
            "cache_hits": self.batches_formed - cold,
        }
