"""Chaos injection: emulate WAN latency, stragglers, and failures locally.

The reference's experiment scripts inject latency and failures to emulate
commodity-internet churn (SURVEY.md §5.3d, [BJ] config 4).  Here chaos is
a server-side hook: every RPC reply can be delayed (base latency + jitter),
turned into a straggler (long delay — exercises the client's
``timeout_after_k_min`` grace path), or dropped (no reply — exercises the
RPC timeout path).  Deterministic under a seed so experiments reproduce.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Optional


@dataclasses.dataclass
class ChaosConfig:
    """All times in seconds; probabilities in [0, 1]."""

    base_latency: float = 0.0  # added to every reply
    jitter: float = 0.0  # uniform extra in [0, jitter]
    straggler_prob: float = 0.0  # chance of a long stall instead
    straggler_delay: float = 1.0
    drop_prob: float = 0.0  # chance the reply is never sent
    # emulated link bandwidth in bytes/sec (0 = unlimited): each reply is
    # additionally delayed by (request+reply bytes) / bandwidth.  Loopback
    # moves bytes at memcpy speed, so payload-size effects (and the value
    # of wire compression — client/moe.py ``wire_dtype``) are invisible
    # without this; ~12.5e6 (100 Mbit/s) models commodity WAN peers
    bandwidth_bps: float = 0.0
    # averaging data plane (the ``avg_part`` replies of the trainer-side
    # group all-reduce — averaging/handler.py): dropped frames exercise
    # the sender's per-part timeout → degraded-round path, delays model a
    # slow WAN peer without killing it.  Matchmaking control frames are
    # never chaos'd (experiments measure reduction fault tolerance, not
    # rendezvous flake).
    averaging_drop_prob: float = 0.0
    averaging_base_latency: float = 0.0
    averaging_jitter: float = 0.0
    seed: Optional[int] = None

    def make(self) -> "ChaosInjector":
        return ChaosInjector(self)


class ChaosInjector:
    def __init__(self, config: ChaosConfig):
        self.config = config
        self.rng = random.Random(config.seed)
        self.injected_delays = 0
        self.injected_stragglers = 0
        self.injected_drops = 0
        self.injected_averaging_drops = 0
        self.injected_averaging_delays = 0

    async def before_reply(self, nbytes: int = 0) -> bool:
        """Apply chaos; returns False if the reply must be dropped.
        ``nbytes``: request+reply payload size for the bandwidth model."""
        c = self.config
        if c.drop_prob and self.rng.random() < c.drop_prob:
            self.injected_drops += 1
            return False
        bw_delay = nbytes / c.bandwidth_bps if c.bandwidth_bps else 0.0
        if c.straggler_prob and self.rng.random() < c.straggler_prob:
            self.injected_stragglers += 1
            await asyncio.sleep(c.straggler_delay + bw_delay)
            return True
        delay = (
            c.base_latency
            + (self.rng.random() * c.jitter if c.jitter else 0.0)
            + bw_delay
        )
        if delay > 0:
            self.injected_delays += 1
            await asyncio.sleep(delay)
        return True

    async def before_averaging_reply(self, nbytes: int = 0) -> bool:
        """Chaos for averaging ``avg_part`` replies; returns False when
        the reply must be dropped (the sender sees a part timeout)."""
        c = self.config
        if c.averaging_drop_prob and self.rng.random() < c.averaging_drop_prob:
            self.injected_averaging_drops += 1
            return False
        delay = c.averaging_base_latency + (
            self.rng.random() * c.averaging_jitter if c.averaging_jitter
            else 0.0
        )
        if c.bandwidth_bps:
            delay += nbytes / c.bandwidth_bps
        if delay > 0:
            self.injected_averaging_delays += 1
            await asyncio.sleep(delay)
        return True
