"""Chaos injection: emulate WAN latency, stragglers, and failures locally.

The reference's experiment scripts inject latency and failures to emulate
commodity-internet churn (SURVEY.md §5.3d, [BJ] config 4).  Here chaos is
a server-side hook: every RPC reply can be delayed (base latency + jitter),
turned into a straggler (long delay — exercises the client's
``timeout_after_k_min`` grace path), or dropped (no reply — exercises the
RPC timeout path).  Deterministic under a seed so experiments reproduce.
"""

from __future__ import annotations

import asyncio
import dataclasses
import random
from typing import Optional


@dataclasses.dataclass
class ChaosConfig:
    """All times in seconds; probabilities in [0, 1]."""

    base_latency: float = 0.0  # added to every reply
    jitter: float = 0.0  # uniform extra in [0, jitter]
    straggler_prob: float = 0.0  # chance of a long stall instead
    straggler_delay: float = 1.0
    drop_prob: float = 0.0  # chance the reply is never sent
    seed: Optional[int] = None

    def make(self) -> "ChaosInjector":
        return ChaosInjector(self)


class ChaosInjector:
    def __init__(self, config: ChaosConfig):
        self.config = config
        self.rng = random.Random(config.seed)
        self.injected_delays = 0
        self.injected_stragglers = 0
        self.injected_drops = 0

    async def before_reply(self) -> bool:
        """Apply chaos; returns False if the reply must be dropped."""
        c = self.config
        if c.drop_prob and self.rng.random() < c.drop_prob:
            self.injected_drops += 1
            return False
        if c.straggler_prob and self.rng.random() < c.straggler_prob:
            self.injected_stragglers += 1
            await asyncio.sleep(c.straggler_delay)
            return True
        delay = c.base_latency + (self.rng.random() * c.jitter if c.jitter else 0.0)
        if delay > 0:
            self.injected_delays += 1
            await asyncio.sleep(delay)
        return True
