"""Server: the top-level expert-hosting peer process.

Contract from the reference's ``hivemind/server/__init__.py`` (SURVEY.md §2
[BJ]; unverifiable refs, mount empty): owns a DHT node handle, N
ExpertBackends, connection handling, and the Runtime; periodically
re-declares its experts to the DHT (the liveness heartbeat that, combined
with record expiry, forms the failure detector).

TPU-native architecture (one process, three execution domains):

- **event loop** (BackgroundLoop thread): TCP accept, RPC parse, task
  pools, DHT client calls — all non-blocking;
- **Runtime thread**: the single device consumer executing jitted expert
  programs (XLA releases the GIL while running);
- **main thread**: owns lifecycle (start/shutdown), free for user code.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import os
import threading
import time
from typing import Any, Optional

import jax
import optax

from learning_at_home_tpu.server import lifecycle
from learning_at_home_tpu.server.connection_handler import ConnectionHandler
from learning_at_home_tpu.server.expert_backend import ExpertBackend
from learning_at_home_tpu.server.lifecycle import HandoffReceiver
from learning_at_home_tpu.server.runtime import Runtime
from learning_at_home_tpu.server.task_pool import TaskPool
from learning_at_home_tpu.utils import flight, sanitizer
from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop

logger = logging.getLogger(__name__)


class Server:
    """Hosts a set of ExpertBackends behind the framed tensor RPC protocol."""

    def __init__(
        self,
        experts: dict[str, ExpertBackend],
        host: str = "0.0.0.0",
        port: int = 0,
        dht: Any = None,
        update_period: float = 15.0,
        batch_timeout: float = 0.002,
        chaos: Any = None,
        transport: str = "asyncio",
        telemetry_prefix: str = "swarm",
    ):
        if transport not in ("asyncio", "native"):
            raise ValueError(f"transport must be 'asyncio' or 'native', got {transport!r}")
        self.transport = transport
        self._pump = None
        self._native_threads: list[threading.Thread] = []
        self._native_stop = threading.Event()
        # conn_id -> tail future; single-dispatcher-thread state (the one
        # native worker is the only reader/writer, so no lock is needed —
        # and a SINGLE popper is what makes per-connection reply order a
        # guarantee: pop, chain-link, and callback-attach happen in
        # program order on one thread, while all actual dispatch runs on
        # the asyncio loop, so extra poppers add no concurrency anyway)
        self._native_chains: dict[int, Any] = {}
        self.experts = dict(experts)
        self.host, self._requested_port = host, port
        self.dht = dht
        self.chaos = chaos.make() if hasattr(chaos, "make") else chaos
        self.update_period = update_period
        self.batch_timeout = batch_timeout
        # replica installs in flight (serving-loop state: single-threaded
        # there, so a set is race-free without a lock)
        self._replicas_installing: set[str] = set()
        self.runtime = Runtime()
        self.forward_pools: dict[str, TaskPool] = {}
        self.backward_pools: dict[str, TaskPool] = {}
        for uid, backend in self.experts.items():
            # forward and backward pools share serial_key=uid: the Runtime's
            # double buffering may overlap DIFFERENT experts' jobs, but a
            # backward donates this expert's param buffers while a forward
            # reads them — same-expert jobs must never be in flight together
            # a callable so warmup run AFTER Server construction still
            # registers in the pools' cold-compile telemetry
            warm = lambda b=backend: getattr(b, "warm_buckets", ())
            self.forward_pools[uid] = TaskPool(
                backend.forward,
                f"{uid}.forward",
                max_batch_size=backend.max_batch_size,
                batch_timeout=batch_timeout,
                serial_key=uid,
                warm_buckets=warm,
            )
            self.backward_pools[uid] = TaskPool(
                lambda tensors, b=backend: b.backward(
                    tensors[: b.n_inputs], tensors[b.n_inputs :]
                ),
                f"{uid}.backward",
                max_batch_size=backend.max_batch_size,
                batch_timeout=batch_timeout,
                serial_key=uid,
                warm_buckets=warm,
            )
        self._loop: Optional[BackgroundLoop] = None
        self._tcp_server: Optional[asyncio.base_events.Server] = None
        self._ready = threading.Event()
        self.port: Optional[int] = None
        # observability (ISSUE 4): every server hosts a tiny metrics
        # endpoint (Prometheus + JSON + chrome trace) on its own loop and
        # advertises it under the telemetry.<prefix> DHT key — same
        # TTL-as-failure-detector contract as expert heartbeats
        self.telemetry_prefix = telemetry_prefix
        self.metrics_server: Any = None
        self.metrics_port: Optional[int] = None
        self._metrics_loop: Optional[BackgroundLoop] = None
        # dynamic expert replication (ISSUE 8): per-expert queue-depth
        # EMAs sampled on the serving loop; experts whose EMA crosses the
        # hot threshold are advertised under ``replicas.wanted.<prefix>``
        # so the rebalancer (tools/lah_rebalance.py) can assign replicas
        # to a less-loaded server.  ``_replica_recipe`` (set by
        # Server.create) is how this server builds a replica backend on
        # request; ``replica_checkpoint_root`` — and ONLY it, never a
        # peer-supplied path — is where add_replica looks for a warmer
        # start than the uid's deterministic crc32 init.
        self._queue_ema: dict[str, float] = {}
        try:
            self.hot_depth_threshold = float(
                os.environ.get("LAH_REPLICA_HOT_DEPTH", "8")
            )
        except ValueError:
            self.hot_depth_threshold = 8.0
        self._replica_recipe: Optional[dict] = None
        self.replica_checkpoint_root: Optional[str] = None
        self.replica_uids: set[str] = set()
        self._replica_syncs: dict[str, "ReplicaSync"] = {}
        # elastic lifecycle (ISSUE 9): SERVING -> DRAINING -> DRAINED.
        # The flag is written by the lah-drain thread (under the
        # lifecycle lock) and only READ by the serving loop's heartbeat
        # task and the handoff handler — plain attribute reads, no lock
        # on the loop (docs/CONCURRENCY.md invariant 10).
        self.lifecycle_state: str = lifecycle.SERVING
        self.started_at = time.monotonic()
        self.restarts = 0  # set by the CLI from the checkpoint root
        self.draining_since: Optional[float] = None
        self.migrated_in: set[str] = set()  # uids received via handoff
        # placement actuation (ISSUE 16): outbound single-expert moves
        # executed by the ``migrate`` RPC's lah-migrate thread; at most
        # one in flight per server (the uid mid-move, else None)
        self.migrations_out = 0
        self.migration_failures = 0
        self._migration_uid: Optional[str] = None
        self.handoff = HandoffReceiver(self)
        self._lifecycle_lock = sanitizer.lock("server.lifecycle")
        self._drain_thread: Optional[threading.Thread] = None
        self._drained = threading.Event()
        self.drain_summary: Optional[dict] = None
        self.checkpoint_manager: Any = None
        self._register_metrics_collector()

    def _register_metrics_collector(self) -> None:
        """Expose this server's always-on headline counters through the
        process metrics registry — scrape-time attribute reads only, and
        weakref-pruned once the server is garbage-collected."""
        import weakref

        from learning_at_home_tpu.utils.metrics import registry

        ref = weakref.ref(self)

        def _collect():
            srv = ref()
            return None if srv is None else srv._headline_metrics()

        self._collector_key = f"server-{id(self)}"
        registry.register_collector(self._collector_key, _collect)

    def _headline_metrics(self) -> dict:
        """The ~10 always-on production counters (ISSUE 4 satellite):
        runtime pipeline, padding waste, staging reuse, bucket compiles,
        expert updates — plain int/float reads, no locks, no spans."""
        rt = self.runtime
        staging = rt.staging.stats()
        rows = padded = batches = cold = hits = 0
        for pool_map in (self.forward_pools, self.backward_pools):
            for p in pool_map.values():
                rows += p.total_rows
                padded += p.padded_rows
                batches += p.batches_formed
                bs = p.bucket_stats()
                cold += bs["cold_compiles"]
                hits += bs["cache_hits"]
        return {
            "lah_server_experts_total": len(self.experts),
            "lah_server_updates_total": sum(
                b.update_count for b in self.experts.values()
            ),
            "lah_server_jobs_processed_total": rt.jobs_processed,
            "lah_server_jobs_overlapped_total": rt.jobs_overlapped,
            "lah_server_queue_depth": rt.queue_depth,
            "lah_server_queue_depth_max": rt.queue_depth_max,
            "lah_server_stack_seconds_total": rt.stack_time,
            "lah_server_materialize_seconds_total": rt.materialize_time,
            "lah_server_device_seconds_total": rt.device_time,
            "lah_server_staging_allocated_total": staging["allocated"],
            "lah_server_staging_reused_total": staging["reused"],
            "lah_server_rows_total": rows,
            "lah_server_padded_rows_total": padded,
            "lah_server_batches_formed_total": batches,
            "lah_server_bucket_cold_compiles_total": cold,
            "lah_server_bucket_cache_hits_total": hits,
            # replication observability (ISSUE 8): replicas this server
            # hosts on behalf of other hosters, and experts currently
            # over the hot queue-depth threshold
            "lah_server_replica_experts_total": len(self.replica_uids),
            "lah_server_hot_experts": sum(
                1 for v in self._snap_queue_ema().values()
                if v >= self.hot_depth_threshold
            ),
            # lifecycle observability (ISSUE 9): drain state, peer age,
            # restart-from-checkpoint count, verified migrations in
            "lah_server_draining": (
                0.0 if self.lifecycle_state == lifecycle.SERVING else 1.0
            ),
            "lah_server_uptime_seconds": time.monotonic() - self.started_at,
            "lah_server_restarts_total": self.restarts,
            "lah_server_handoffs_received_total": self.handoff.received,
            # placement actuation (ISSUE 16): outbound expert moves this
            # server executed for the rebalancer, and moves whose
            # handoff failed (source copy kept — a failed move is no move)
            "lah_placement_migrations_out_total": self.migrations_out,
            "lah_placement_migration_failures_total": (
                self.migration_failures
            ),
        }

    def _snap_queue_ema(self) -> dict:
        # the serving loop replaces entries in place; scrape threads
        # copy-with-retry like every other telemetry read
        for _ in range(4):
            try:
                return dict(self._queue_ema)
            except RuntimeError:
                continue
        return {}

    # ---- lifecycle ----

    @classmethod
    def create(
        cls,
        num_experts: int = 4,
        expert_cls: str = "ffn",
        hidden_dim: int = 1024,
        expert_prefix: str = "expert",
        expert_offset: int = 0,
        optimizer: Optional[optax.GradientTransformation] = None,
        max_batch_size: int = 1024,
        warmup=False,
        seed: int = 0,
        start: bool = True,
        expert_uids=None,
        **server_kwargs,
    ) -> "Server":
        """Build a server from the expert zoo and (optionally) start it —
        the reference's ``Server.create`` convenience (SURVEY.md §3.3).

        Expert UIDs are ``{prefix}.{offset+i}``, OR pass ``expert_uids``
        (an explicit iterable) to host arbitrary uids — params then seed
        stably per uid (crc32) so every process that ever hosts a uid
        initializes identical weights.  ``warmup`` AOT-precompiles batch
        buckets before returning (recommended for serving): ``True`` = all
        power-of-two buckets, or a list of explicit bucket sizes."""
        import zlib

        from learning_at_home_tpu.models import make_expert
        from learning_at_home_tpu.models.layers import sample_inputs

        optimizer = optimizer if optimizer is not None else optax.adam(1e-3)
        if expert_uids is not None:
            uid_keys = [
                (uid, jax.random.PRNGKey(zlib.crc32(uid.encode()) & 0x7FFFFFFF))
                for uid in expert_uids
            ]
        else:
            uid_keys = [
                (f"{expert_prefix}.{i}", jax.random.PRNGKey(seed + i))
                for i in range(expert_offset, expert_offset + num_experts)
            ]
        experts = {}
        n_wire_inputs = len(sample_inputs(expert_cls, hidden_dim))
        for uid, key in uid_keys:
            apply_fn, params = make_expert(expert_cls, hidden_dim, key)
            experts[uid] = ExpertBackend(
                uid, apply_fn, params, optimizer,
                max_batch_size=max_batch_size, n_inputs=n_wire_inputs,
            )
        if warmup:
            import time as _time

            t0 = _time.monotonic()
            sample = sample_inputs(expert_cls, hidden_dim, rows=1)
            buckets = None if warmup is True else list(warmup)
            n = sum(
                backend.warmup(sample, buckets=buckets)
                for backend in experts.values()
            )
            logger.info(
                "warmed %d programs in %.1fs", n, _time.monotonic() - t0
            )
        server = cls(experts, **server_kwargs)
        # everything needed to build ANOTHER expert of this zoo on demand
        # — the replica path (add_replica) constructs backends from this
        server._replica_recipe = {
            "expert_cls": expert_cls,
            "hidden_dim": hidden_dim,
            "optimizer": optimizer,
            "max_batch_size": max_batch_size,
            "n_inputs": n_wire_inputs,
            # whether THIS server's experts were crc32-uid-seeded (the
            # cross-process identical-init contract replicas rely on) —
            # _make_replica_backend warns when a replica's crc32 init
            # cannot be assumed to match the hoster's.  A server booted
            # EMPTY (the rebalancer's replica-host pattern) carries no
            # conflicting evidence and stays on the crc32 contract.
            "uid_seeded": expert_uids is not None or not uid_keys,
        }
        if start:
            server.run_in_background()
        return server

    def run_in_background(self, await_ready: bool = True) -> "Server":
        assert self._loop is None, "server already started"
        self._start_metrics_endpoint()
        self._loop = BackgroundLoop(name="lah-server")
        self.runtime.attach_loop(self._loop.loop)
        self.runtime.start()
        self._loop.run(self._start_async())
        if self.metrics_server is not None:
            # known only after the RPC socket binds; purely informational
            self.metrics_server.meta["rpc_port"] = self.port
        if await_ready:
            self._ready.wait(timeout=30)
        return self

    def _start_metrics_endpoint(self) -> None:
        """Per-server observability endpoint (always on — an idle
        endpoint costs one listening socket; scrapes do the work).  It
        lives on its OWN loop thread: a /trace or /metrics.json scrape
        can serialize megabytes of JSON, and that must never stall the
        RPC serving loop a dispatch-latency investigation is probing."""
        from learning_at_home_tpu.utils.metrics import MetricsHTTPServer

        self.metrics_server = MetricsHTTPServer(
            meta={"role": "server"}, extra_fn=self._telemetry_extra,
        )
        self._metrics_loop = BackgroundLoop(name="lah-metrics")
        try:
            self.metrics_port = self._metrics_loop.run(
                self.metrics_server.start(self.host), timeout=10
            )
        except Exception:
            logger.exception("metrics endpoint failed to start; serving blind")
            self._metrics_loop.shutdown()
            self.metrics_server = self.metrics_port = self._metrics_loop = None

    async def _start_async(self) -> None:
        handler = ConnectionHandler(self)
        if self.transport == "native":
            # GIL-free C++ epoll data plane (native/framepump.cpp): Python
            # worker threads only see whole frames and bridge them onto the
            # event loop for task-pool dispatch
            from learning_at_home_tpu.native import FramePump

            self._pump = FramePump(self.host, self._requested_port)
            self.port = self._pump.port
            t = threading.Thread(
                target=self._native_worker,
                args=(handler,),
                name="lah-native-io",
                daemon=True,
            )
            t.start()
            self._native_threads.append(t)
        else:
            self._tcp_server = await asyncio.start_server(
                handler.handle_connection, self.host, self._requested_port
            )
            self.port = self._tcp_server.sockets[0].getsockname()[1]
        for pool in (*self.forward_pools.values(), *self.backward_pools.values()):
            pool.start(self.runtime)
        asyncio.get_running_loop().create_task(
            self._monitor_load_forever(), name="load-monitor"
        )
        if self.dht is not None:
            asyncio.get_running_loop().create_task(
                self._declare_experts_forever(), name="dht-heartbeat"
            )
        logger.info(
            "server listening on %s:%d with %d experts (metrics on :%s)",
            self.host,
            self.port,
            len(self.experts),
            self.metrics_port,
        )
        self._ready.set()

    def _telemetry_extra(self) -> dict:
        """Per-request payload merged into ``/metrics.json`` — the
        expert-level detail lah_top renders that flat metrics can't carry
        (per-expert update counts, runtime/pool breakdown)."""
        return {
            "experts": {
                uid: b.update_count for uid, b in self.experts.items()
            },
            # replication view (ISSUE 8): which hosted uids are replicas
            # and which are currently hot — lah_top's REPLICAS column
            "replicas": sorted(self.replica_uids),
            "hot": self.hot_experts(),
            "runtime": self.runtime.stats(),
            "endpoint": list(self.endpoint),
            # lifecycle view (ISSUE 9): lah_top's STATE/UPTIME/RST columns
            "lifecycle": self.lifecycle_info(),
            # placement view (ISSUE 16): lah_top's migration column and
            # the rebalancer's snapshot of this server's outbound moves
            "placement": self.placement_info(),
        }

    def placement_info(self) -> dict:
        """Serializable placement-actuation snapshot (stats RPC +
        telemetry extra): outbound move counters and the uid mid-move
        (None when idle)."""
        return {
            "migrations_out": self.migrations_out,
            "migration_failures": self.migration_failures,
            "migration_in_flight": self._migration_uid,
        }

    def lifecycle_info(self) -> dict:
        """Serializable lifecycle snapshot (stats RPC + telemetry extra):
        state, uptime, restart-from-checkpoint count, drain progress and
        inbound-migration counters."""
        info = {
            "state": self.lifecycle_state,
            "uptime_s": round(time.monotonic() - self.started_at, 1),
            "restarts": self.restarts,
            "handoff": self.handoff.stats(),
            "migrated_in": sorted(self.migrated_in),
        }
        if self.draining_since is not None:
            info["draining_for_s"] = round(
                time.monotonic() - self.draining_since, 1
            )
        if self.drain_summary is not None:
            info["drain_summary"] = self.drain_summary
        return info

    def _native_worker(self, handler: ConnectionHandler) -> None:
        """THE single dispatcher thread: shovels whole frames from the
        native pump onto the event loop (task pools are asyncio) WITHOUT
        waiting for each dispatch — the reply is pushed back to the pump
        from a done-callback, so in-flight concurrency matches the asyncio
        transport's one-coroutine-per-request.

        Dispatches are CHAINED per connection: request N+1 on a connection
        starts only after request N's reply was queued, making in-order
        replies a server guarantee (the asyncio transport processes each
        connection serially too) — not merely a property of this repo's
        one-exchange-at-a-time client.  Being the only popper is what
        makes the chain sound: pop, link, and callback-attach happen in
        program order here, with no lock and no second thread to invert
        frames."""
        pump = self._pump
        chains = self._native_chains  # conn_id -> tail future (this thread only)

        async def process(prev, payload: bytes):
            from learning_at_home_tpu.utils.serialization import frame_payload

            if prev is not None:
                try:
                    await asyncio.wrap_future(prev)
                # lah-lint: ignore[R6] ordering barrier only: the prior
                # request's failure was already logged (and replied) where
                # it happened; this await exists to sequence replies
                except BaseException:
                    pass
            # the pump's C side frames replies itself: join the vectored
            # parts back into one payload (no writev through ctypes)
            reply = frame_payload(await handler._dispatch(payload))
            if self.chaos is not None and not await self.chaos.before_reply(
                len(payload) + len(reply)
            ):
                return None  # injected drop: client sees a timeout
            return reply

        def reply_cb(fut, conn_id):
            try:
                reply = fut.result()
            except BaseException as e:  # incl. CancelledError at shutdown
                if not isinstance(e, asyncio.CancelledError):
                    logger.exception("native dispatch failed")
                return
            if reply is None:
                return
            try:
                pump.send(conn_id, reply)  # cheap: C memcpy + eventfd
            except ValueError:
                logger.error("native reply exceeds frame cap — dropped")

        n_since_cleanup = 0
        while True:
            if self._native_stop.is_set():
                return
            try:
                item = pump.next(timeout=0.2)
            except EOFError:
                return
            loop = self._loop  # snapshot: shutdown() nulls the attribute
            if item is None or loop is None:
                if loop is None:
                    return
                continue
            conn_id, payload = item
            prev = chains.get(conn_id)
            if prev is not None and prev.done():
                prev = None
            try:
                fut = asyncio.run_coroutine_threadsafe(
                    process(prev, payload), loop.loop
                )
            except RuntimeError:  # loop closed mid-shutdown
                return
            chains[conn_id] = fut
            # callback attached HERE, still in the dispatcher: attaching
            # after releasing ordering control would let reply N land
            # after N+1 when the dispatcher is preempted between link and
            # attach (the chain only orders dispatch starts, and reply_cb
            # for an already-done future runs inline on whichever thread
            # attaches it)
            fut.add_done_callback(lambda f, cid=conn_id: reply_cb(f, cid))
            n_since_cleanup += 1
            if n_since_cleanup >= 256:  # lazily drop finished chains
                n_since_cleanup = 0
                for cid in [c for c, f in chains.items() if f.done()]:
                    del chains[cid]

    async def _monitor_load_forever(self) -> None:
        """Per-expert queue-depth EMA sampler (serving loop; qsize reads
        only — never tensor work).  The EMAs feed three consumers: the
        ``load.<prefix>`` heartbeat the client cost model reads, the
        ``replicas.wanted.<prefix>`` hot-expert advertisements the
        rebalancer acts on, and the server's own headline metrics."""
        period = min(1.0, max(0.1, self.update_period / 4))
        while True:
            try:
                for uid, pool in list(self.forward_pools.items()):
                    depth = pool._tasks.qsize() + (
                        1 if pool._carry is not None else 0
                    )
                    prev = self._queue_ema.get(uid, 0.0)
                    self._queue_ema[uid] = 0.7 * prev + 0.3 * depth
            except Exception:  # telemetry must never kill the loop task
                logger.exception("load monitor sample failed")
            await asyncio.sleep(period)

    def hot_experts(self) -> dict[str, float]:
        """uids whose queue-depth EMA crossed the hot threshold → EMA."""
        return {
            uid: round(ema, 3)
            for uid, ema in self._snap_queue_ema().items()
            if ema >= self.hot_depth_threshold
        }

    async def _declare_experts_forever(self) -> None:
        """Liveness heartbeat: re-declare experts so DHT records stay
        fresh, and advertise the metrics endpoint under the
        ``telemetry.<prefix>`` key (utils/telemetry.py) with the same
        TTL — one missed heartbeat cycle and the swarm view marks this
        peer dead.  The same cycle publishes the ``load.<prefix>`` record
        (runtime queue depth + per-expert hot map, keyed by this RPC
        endpoint so clients join it against expert records without an
        extra lookup) and one ``replicas.wanted.<prefix>`` entry per
        currently-hot expert."""
        from learning_at_home_tpu.utils.telemetry import (
            link_snapshot,
            links_key,
            load_key,
            replicas_wanted_key,
            telemetry_key,
        )

        peer_id = f"server-{self.endpoint[0]}:{self.port}"
        ep_key = f"{self.endpoint[0]}:{self.port}"
        while True:
            try:
                serving = self.lifecycle_state == lifecycle.SERVING
                ttl = self.update_period * 2
                # one record bundle per period (ISSUE 11): expert declares
                # + telemetry + load + wanted ads coalesce into a single
                # store_many — one multi-key store RPC per destination
                # peer instead of a per-key store storm
                extra: list[tuple] = []
                if self.metrics_port is not None:
                    # telemetry keeps heartbeating through the drain so
                    # observers (lah_top) see DRAINING, not a dead peer
                    extra.append((
                        telemetry_key(self.telemetry_prefix),
                        [self.endpoint[0], self.metrics_port, "server"],
                        ttl, peer_id,
                    ))
                if serving:
                    hot = self.hot_experts()
                    extra.append((
                        load_key(self.telemetry_prefix),
                        {
                            "q": float(self.runtime.queue_depth),
                            "n": len(self.experts),
                            "hot": hot,
                        },
                        ttl, ep_key,
                    ))
                    # measured link EMAs (ISSUE 16): this server's view
                    # of the peers it dialed (handoffs, replica syncs) —
                    # one more record in the same coalesced bundle
                    links = link_snapshot()
                    if links:
                        extra.append((
                            links_key(self.telemetry_prefix),
                            {"l": links}, ttl, ep_key,
                        ))
                    for uid, ema in hot.items():
                        extra.append((
                            replicas_wanted_key(self.telemetry_prefix),
                            [ema, self.endpoint[0], self.port],
                            ttl, uid,
                        ))
                    # a DRAINING server stops re-declaring its experts
                    # (and its load/wanted records): the records it
                    # already published expire within one TTL and new
                    # dispatch steers away — DHT expiry IS the drain
                    # announcement (hedges cover the stale window)
                    await self.dht.declare_experts(
                        list(self.experts), self.endpoint,
                        expiration=ttl, extra_records=extra,
                    )
                elif extra:
                    await self.dht.store_many(extra)
            except Exception:
                logger.exception("declare_experts heartbeat failed")
            await asyncio.sleep(self.update_period)

    # ---- checkpoint / resume (SURVEY.md §5.4) ----

    def save_checkpoint(self, root: str, step: Optional[int] = None) -> int:
        """Snapshot every expert's params+opt_state (safe during serving:
        each snapshot serializes against that expert's async updates).
        ``step=None`` picks the next unused step number; the completion
        marker is written only after every expert saved, so a crash
        mid-save can never masquerade as a usable checkpoint.  Returns
        the step saved."""
        from learning_at_home_tpu.utils.checkpoint import (
            mark_step_complete,
            next_step,
            save_pytree,
        )

        step = next_step(root) if step is None else step
        experts = dict(self.experts)
        if not experts:
            # never mark an EMPTY step complete: restore_latest would
            # prefer it over the last real snapshot (a drained or
            # replica-host-mode server simply has nothing to save)
            logger.warning(
                "checkpoint skipped: no experts to save (root %s)", root
            )
            return step
        for uid, backend in experts.items():
            save_pytree(root, step, uid.replace("/", "_"), backend.state_dict())
        mark_step_complete(root, step)
        logger.info("checkpointed %d experts to %s @ step %d",
                    len(experts), root, step)
        return step


    def load_checkpoint(self, root: str, step: Optional[int] = None) -> int:
        """Restore every hosted expert found in the checkpoint; returns the
        step restored.  Recovery contract: restart → load → re-declare."""
        from learning_at_home_tpu.utils.checkpoint import latest_step, restore_pytree

        step = step if step is not None else latest_step(root)
        if step is None:
            raise FileNotFoundError(f"no checkpoint steps under {root}")
        for uid, backend in self.experts.items():
            state = restore_pytree(
                root, step, uid.replace("/", "_"), backend.state_template()
            )
            backend.load_state_dict(state)
        logger.info("restored %d experts from %s @ step %d",
                    len(self.experts), root, step)
        return step

    # ---- elastic lifecycle: graceful drain + live migration (ISSUE 9) ----

    def pools_idle(self) -> bool:
        """True when no task pool holds queued/carried work and the
        Runtime queue is empty — the quiesce predicate the drain polls.
        Cross-thread reads of loop-owned state: qsize/attribute reads
        only, tolerate-never-crash like every other telemetry read."""
        try:
            if self.runtime.queue_depth > 0:
                return False
            for pool_map in (self.forward_pools, self.backward_pools):
                for pool in list(pool_map.values()):
                    if pool._tasks.qsize() > 0 or pool._carry is not None:
                        return False
        except RuntimeError:  # dict mutated under us: call it busy
            return False
        return True

    def _begin_drain(self) -> bool:
        """Atomically flip SERVING -> DRAINING; True if already past it."""
        with self._lifecycle_lock:
            if self.lifecycle_state != lifecycle.SERVING:
                return True
            self.lifecycle_state = lifecycle.DRAINING
            self.draining_since = time.monotonic()
        flight.record(
            "server", "drain_transition", state=lifecycle.DRAINING,
            port=self.port,
        )
        return False

    def _finish_drain(self) -> None:
        with self._lifecycle_lock:
            self.lifecycle_state = lifecycle.DRAINED
        flight.record(
            "server", "drain_transition", state=lifecycle.DRAINED,
            port=self.port,
        )
        self._drained.set()

    @sanitizer.runs_on("host", site="server.drain")
    def drain(
        self,
        successor: Optional[tuple] = None,
        *,
        grace: Optional[float] = None,
        quiesce_timeout: float = 30.0,
        handoff: bool = True,
        handoff_timeout: float = 60.0,
    ) -> dict:
        """Blocking graceful drain (host thread ONLY — the sequence
        sleeps through the record-expiry grace window and blocks on
        handoff RPCs; see lifecycle.run_drain for the steps).  Returns
        the drain summary; raises if a drain already ran/is running."""
        summary = lifecycle.run_drain(
            self, successor=successor, grace=grace,
            quiesce_timeout=quiesce_timeout, handoff=handoff,
            handoff_timeout=handoff_timeout,
        )
        self.drain_summary = summary
        return summary

    def start_drain(self, **kwargs) -> bool:
        """Fire-and-watch drain on the dedicated ``lah-drain`` daemon
        thread (the ``drain`` RPC's path — the serving loop must reply
        immediately, never block through the sequence).  Idempotent:
        False when a drain is already underway."""
        with self._lifecycle_lock:
            if (
                self.lifecycle_state != lifecycle.SERVING
                or self._drain_thread is not None
            ):
                return False

            def _run():
                try:
                    self.drain(**kwargs)
                except Exception:
                    logger.exception("background drain failed")
                    self._drained.set()  # waiters must not hang on a bug

            self._drain_thread = threading.Thread(
                target=_run, name="lah-drain", daemon=True
            )
        self._drain_thread.start()
        return True

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        return self._drained.wait(timeout)

    def start_migration(
        self, uid: str, target: Endpoint, timeout: float = 60.0
    ) -> bool:
        """Fire-and-watch single-expert move on a ``lah-migrate`` daemon
        thread (the ``migrate`` RPC's path — the serving loop replies
        immediately and keeps serving the uid through the transfer).
        One migration in flight per server; False when one already is,
        when a drain owns the lifecycle, or when not SERVING.  Callers
        watch the stats RPC's ``placement`` section
        (``migrations_out`` / ``migration_failures`` /
        ``migration_in_flight``) for the outcome.

        Raises ValueError for a uid not hosted here (the RPC turns that
        into an error reply) — refusals that depend on the lifecycle
        return False instead, mirroring ``start_drain``."""
        with self._lifecycle_lock:
            if (
                self.lifecycle_state != lifecycle.SERVING
                or self._drain_thread is not None
                or self._migration_uid is not None
            ):
                return False
            if uid not in self.experts:
                raise ValueError(f"migrate: uid {uid!r} is not hosted here")
            self._migration_uid = uid

            def _run():
                try:
                    lifecycle.run_migration(
                        self, uid, target, timeout=timeout
                    )
                except Exception:
                    logger.exception("background migration failed")
                finally:
                    self._migration_uid = None

            thread = threading.Thread(
                target=_run, name="lah-migrate", daemon=True
            )
        thread.start()
        return True

    async def _declare_now(self, uid: str) -> None:
        """Immediate single-uid declare (serving loop): new/updated
        hosters become discoverable within one alive-TTL instead of one
        heartbeat period.  Failures defer to the heartbeat."""
        if self.dht is None:
            return
        try:
            await self.dht.declare_experts(
                [uid], self.endpoint, expiration=self.update_period * 2
            )
        except Exception:
            logger.exception(
                "%s: immediate declare failed (the heartbeat will retry)",
                uid,
            )

    def _retire_expert(self, uid: str) -> None:
        """Drop a handed-off expert (drain thread): requests arriving
        after this get an unknown-expert error reply, which the client's
        retry/hedge machinery absorbs like any dead peer.  Pool shutdown
        runs on the serving loop, like Server.shutdown's."""
        self.experts.pop(uid, None)
        self.replica_uids.discard(uid)
        sync = self._replica_syncs.pop(uid, None)
        if sync is not None:
            sync.stop()
        for pool_map in (self.forward_pools, self.backward_pools):
            pool = pool_map.pop(uid, None)
            if pool is not None and self._loop is not None:
                with contextlib.suppress(Exception):
                    self._loop.loop.call_soon_threadsafe(pool.shutdown)

    # ---- dynamic expert replication (ISSUE 8) ----

    def _make_replica_backend(
        self, uid: str, allow_checkpoint: bool = True
    ) -> ExpertBackend:
        """Build a replica backend for ``uid``: the uid's deterministic
        crc32-seeded init (every process that ever hosts a uid starts
        from identical weights — Server.create's expert_uids contract),
        upgraded to the latest state in this server's OWN checkpoint root
        when one exists.  The root is local configuration, NEVER a
        peer-supplied path — the replica RPC carries only the uid.
        ``allow_checkpoint=False`` skips the restore-and-warn path: the
        handoff receiver overwrites the whole state from the wire."""
        import zlib

        from learning_at_home_tpu.models import make_expert

        recipe = self._replica_recipe
        if recipe is None:
            raise RuntimeError(
                "server has no replica recipe: construct it via "
                "Server.create (which records the expert zoo config), or "
                "pass an explicit backend to add_replica"
            )
        apply_fn, params = make_expert(
            recipe["expert_cls"], recipe["hidden_dim"],
            jax.random.PRNGKey(zlib.crc32(uid.encode()) & 0x7FFFFFFF),
        )
        backend = ExpertBackend(
            uid, apply_fn, params, recipe["optimizer"],
            max_batch_size=recipe["max_batch_size"],
            n_inputs=recipe["n_inputs"],
        )
        root = self.replica_checkpoint_root if allow_checkpoint else None
        restored = False
        if root is not None:
            from learning_at_home_tpu.utils.checkpoint import (
                latest_step,
                restore_pytree,
            )

            step = latest_step(root)
            if step is not None:
                try:
                    state = restore_pytree(
                        root, step, uid.replace("/", "_"),
                        backend.state_template(),
                    )
                    backend.load_state_dict(state)
                    restored = True
                    logger.info(
                        "replica %s restored from %s @ step %d",
                        uid, root, step,
                    )
                except Exception:
                    logger.exception(
                        "replica %s: checkpoint restore failed — serving "
                        "the crc32-seeded init (replica sync will pull it "
                        "toward the group)", uid,
                    )
        if allow_checkpoint and not restored and not recipe.get("uid_seeded"):
            # the crc32 init matches hosters created with explicit
            # expert_uids (crc32-uid seeding); a server whose OWN experts
            # came from the num_experts/seed path is a strong hint the
            # swarm seeds per-server — this replica's init then does NOT
            # match the hoster's params, and only a checkpoint restore or
            # ReplicaSync averaging aligns it.  Never silent.
            logger.warning(
                "replica %s: no checkpoint state to restore and this "
                "server's experts are seed-path initialized (not "
                "crc32-uid-seeded) — the replica starts from the uid's "
                "crc32 init, which matches expert_uids-created hosters "
                "only; enable replica sync (sync=true) or provide a "
                "checkpoint root so replies stay numerically aligned",
                uid,
            )
        return backend

    async def _install_replica(
        self, uid: str, backend: ExpertBackend, replica: bool = True
    ) -> None:
        """Register + start pools for a new expert ON the serving loop
        (the connection handler reads ``self.experts`` there), then
        declare it immediately so clients discover the new hoster within
        one alive-TTL instead of one heartbeat period.  ``replica=False``
        installs without the replica bookkeeping (the handoff path: a
        migrated expert is a full expert, not a copy of one)."""
        warm = lambda b=backend: getattr(b, "warm_buckets", ())
        fp = TaskPool(
            backend.forward, f"{uid}.forward",
            max_batch_size=backend.max_batch_size,
            batch_timeout=self.batch_timeout, serial_key=uid,
            warm_buckets=warm,
        )
        bp = TaskPool(
            lambda tensors, b=backend: b.backward(
                tensors[: b.n_inputs], tensors[b.n_inputs :]
            ),
            f"{uid}.backward", max_batch_size=backend.max_batch_size,
            batch_timeout=self.batch_timeout, serial_key=uid,
            warm_buckets=warm,
        )
        self.experts[uid] = backend
        self.forward_pools[uid] = fp
        self.backward_pools[uid] = bp
        if replica:
            self.replica_uids.add(uid)
        fp.start(self.runtime)
        bp.start(self.runtime)
        await self._declare_now(uid)
        logger.info("hosting %s expert %s",
                    "replica of" if replica else "migrated", uid)

    async def add_replica_async(self, uid: str, sync: bool = False) -> bool:
        """Loop-side replica install (the ``replica`` RPC's path).  The
        backend build (param init / checkpoint restore — seconds of jax
        work) runs in a worker thread so the serving loop never blocks.
        Returns True when installed, False when already hosted, when an
        install for the uid is in flight, or when this server is
        draining (a peer about to exit must not take on new experts)."""
        if (
            uid in self.experts
            or uid in self._replicas_installing
            or self.lifecycle_state != lifecycle.SERVING
        ):
            return False
        self._replicas_installing.add(uid)
        try:
            backend = await asyncio.to_thread(self._make_replica_backend, uid)
            await self._install_replica(uid, backend)
        finally:
            self._replicas_installing.discard(uid)
        if sync:
            # ReplicaSync construction blocks on the lah-avg loop binding
            # its peer endpoint (seconds) — never on the serving loop
            await asyncio.to_thread(self.enable_replica_sync, uid)
        return True

    def add_replica(
        self,
        uid: str,
        backend: Optional[ExpertBackend] = None,
        sync: bool = False,
        sync_period: float = 10.0,
    ) -> bool:
        """Host a replica of expert ``uid`` on this server (host-thread
        form; the rebalancer's ``replica`` RPC reaches
        :meth:`add_replica_async` instead).  ``sync=True`` also starts
        periodic replica averaging (:class:`ReplicaSync`)."""
        assert self._loop is not None, "server not started"
        if uid in self.experts:
            return False
        if backend is None:
            backend = self._make_replica_backend(uid)
        self._loop.run(self._install_replica(uid, backend), timeout=30)
        if sync:
            self.enable_replica_sync(uid, period=sync_period)
        return True

    def enable_replica_sync(
        self,
        uid: str,
        period: float = 10.0,
        min_group_size: int = 2,
    ) -> "ReplicaSync":
        """Start periodic parameter averaging with the other hosters of
        ``uid`` (idempotent per uid; requires a DHT for matchmaking)."""
        if self.dht is None:
            raise RuntimeError("replica sync needs a DHT for matchmaking")
        existing = self._replica_syncs.get(uid)
        if existing is not None:
            return existing
        sync = ReplicaSync(
            self, uid, period=period, min_group_size=min_group_size
        )
        self._replica_syncs[uid] = sync
        return sync

    @property
    def endpoint(self) -> tuple[str, int]:
        host = self.host
        if host in ("0.0.0.0", "::"):
            host = "127.0.0.1"  # localhost swarm default; WAN peers configure host
        return (host, self.port)

    def shutdown(self) -> None:
        from learning_at_home_tpu.utils.metrics import registry

        registry.unregister_collector(self._collector_key)
        if self.checkpoint_manager is not None:
            with contextlib.suppress(Exception):
                self.checkpoint_manager.stop()
            self.checkpoint_manager = None
        for sync in list(self._replica_syncs.values()):
            sync.stop()
        self._replica_syncs.clear()
        if self._loop is None:
            return
        for pool in (*self.forward_pools.values(), *self.backward_pools.values()):
            with contextlib.suppress(Exception):
                self._loop.loop.call_soon_threadsafe(pool.shutdown)
        if self._metrics_loop is not None:
            with contextlib.suppress(Exception):
                self._metrics_loop.loop.call_soon_threadsafe(
                    self.metrics_server.close
                )
            self._metrics_loop.shutdown()
            self._metrics_loop = None
        if self._tcp_server is not None:
            self._loop.loop.call_soon_threadsafe(self._tcp_server.close)
        # native teardown ORDER matters (the pump's shutdown frees its C
        # state): stop workers, drain the loop (all reply callbacks fire on
        # the loop thread before its join returns), join workers, and only
        # then destroy the pump — nothing can touch freed memory after.
        self._native_stop.set()
        self.runtime.shutdown()
        loop = self._loop
        self._loop = None  # signals native workers' timeout branch
        loop.shutdown()
        for t in self._native_threads:
            t.join(timeout=5)
        wedged = [t for t in self._native_threads if t.is_alive()]
        self._native_threads.clear()
        if self._pump is not None:
            if wedged:
                # A live worker may still be inside pump.next(); destroying
                # the C state under it is a use-after-free.  Leaking one
                # pump beats corrupting the process.
                logger.error(
                    "%d native worker(s) did not join; leaking the pump "
                    "instead of freeing C state under them", len(wedged)
                )
            else:
                with contextlib.suppress(Exception):
                    self._pump.shutdown()
            self._pump = None
        logger.info("server shut down")


class ReplicaSync:
    """Keeps the replicas of ONE expert numerically aligned by running
    periodic parameter-averaging rounds over the existing decentralized
    averaging machinery (averaging/ — chunked butterfly all-reduce on the
    same wire/codec stack): every server hosting ``uid`` with sync
    enabled rendezvouses under ``averaging.replica.<uid>`` and writes the
    group mean back via :meth:`ExpertBackend.replace_params`.  Optimizer
    state stays local — it is per-hoster momentum, not shared identity.

    Thread model (docs/CONCURRENCY.md): ONE daemon thread per synced
    expert owns the blocking ``step_round`` calls; nothing here ever
    runs on a server loop.  Matchmaking failures (a lone replica, a peer
    mid-death) just skip the round — sync is convergence pressure for
    independently-trained replicas, not a barrier."""

    def __init__(
        self,
        server: "Server",
        uid: str,
        period: float = 10.0,
        min_group_size: int = 2,
        max_group_size: int = 16,
    ):
        from learning_at_home_tpu.averaging import (
            AveragingConfig,
            DecentralizedAverager,
        )

        self.server = server
        self.uid = uid
        self.period = period
        self.rounds = 0
        self.failures = 0
        self._stop = threading.Event()
        cfg = AveragingConfig(
            prefix=f"averaging.replica.{uid}",
            min_group_size=min_group_size,
            max_group_size=max_group_size,
            matchmaking_timeout=max(2.0, period),
            gather_timeout=min(4.0, max(1.0, period)),
        )
        self._averager = DecentralizedAverager(
            server.dht, config=cfg,
            peer_id=f"replica-{server.endpoint[0]}:{server.port}",
        )
        self._thread = threading.Thread(
            target=self._run, name=f"lah-replica-sync-{uid}", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            backend = self.server.experts.get(self.uid)
            if backend is None:
                break
            try:
                params = backend.state_dict()["params"]
                averaged, _info = self._averager.step_round(
                    params, matchmaking_timeout=self.period
                )
                if averaged is not None:
                    backend.replace_params(averaged)
                    self.rounds += 1
            except Exception as e:
                # lone replica / peer churn: skip this round, keep trying
                self.failures += 1
                logger.debug("replica sync round for %s skipped: %s: %s",
                             self.uid, type(e).__name__, e)
            self._stop.wait(self.period)

    def stats(self) -> dict:
        return {"uid": self.uid, "rounds": self.rounds,
                "failures": self.failures}

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=30)
        if self._thread.is_alive():
            logger.warning("replica sync thread for %s did not join "
                           "(mid-round); averager shutdown will cancel it",
                           self.uid)
        self._averager.shutdown()


@contextlib.contextmanager
def background_server(
    num_experts: int = 2,
    expert_cls: str = "ffn",
    hidden_dim: int = 64,
    expert_prefix: str = "expert",
    optimizer: Optional[optax.GradientTransformation] = None,
    max_batch_size: int = 256,
    dht: Any = None,
    seed: int = 0,
    **server_kwargs,
):
    """Spin up a localhost Server with generated experts (test/benchmark rig).

    Mirrors the reference's ``background_server`` fixture contract: yields
    ``(endpoint, server)``; tears down on exit.  Expert UIDs are
    ``{prefix}.{i}`` — grid-style UIDs for MoE tests come from the caller.

    NB: this server shares the caller's XLA runtime.  For heavy training
    loops (especially with client-side jax.grad through io_callbacks) use
    a separate server process instead — see transformer_swarm.py's
    deployment note.
    """
    server = Server.create(
        num_experts=num_experts,
        expert_cls=expert_cls,
        hidden_dim=hidden_dim,
        expert_prefix=expert_prefix,
        optimizer=optimizer if optimizer is not None else optax.sgd(0.05),
        max_batch_size=max_batch_size,
        seed=seed,
        host="127.0.0.1",
        dht=dht,
        **server_kwargs,
    )
    try:
        yield server.endpoint, server
    finally:
        server.shutdown()
