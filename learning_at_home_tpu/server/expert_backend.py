"""ExpertBackend: one expert's parameters + optimizer as jitted XLA programs.

Behavioral contract from the reference's ``hivemind/server/expert_backend.py``
(SURVEY.md §2 [BJ]; file:line unverifiable, mount empty):

- ``forward(batch)`` runs the expert on a batch;
- ``backward(batch, grad_outputs)`` computes input-gradients to return to the
  caller AND **immediately applies the optimizer step** to the expert's own
  parameters — the asynchronous / local-SGD update at the heart of
  Learning@home.  No global barrier; staleness is tolerated by design.

TPU-native realization: parameters and optimizer state are **long-lived HBM
buffers**; ``backward`` is a single jitted computation with
``donate_argnums`` on (params, opt_state) so XLA updates them in place —
grads w.r.t. inputs come back to the host, the new parameter buffers never
leave the device.  Per-expert serialization (the reference Runtime's
single-consumer guarantee) is preserved: all state mutation happens on the
Runtime's one device-executor thread.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from learning_at_home_tpu.utils import sanitizer

logger = logging.getLogger(__name__)


class ExpertBackend:
    """An expert module + its optimizer, executed as jitted XLA computations.

    Args:
        name: globally-unique expert UID (e.g. ``"ffn.4.17"``).
        apply_fn: pure function.  Without ``input_structure``:
            ``(params, *inputs) -> output`` over flat arrays.  With
            ``input_structure``: ``(params, tree) -> output`` — the flat
            wire tensors are repacked into ONE nest argument shaped like
            ``input_structure`` before the call.
        params: initial parameter pytree (device or host).
        optimizer: an ``optax.GradientTransformation``.
        max_batch_size: upper bound on rows per executed batch; also the
            largest static-shape bucket.
    """

    def __init__(
        self,
        name: str,
        apply_fn: Callable,
        params: Any,
        optimizer: optax.GradientTransformation,
        max_batch_size: int = 1024,
        opt_state: Any = None,
        n_inputs: int = 1,
        input_structure: Any = None,
    ):
        self.name = name
        self.apply_fn = apply_fn
        self.optimizer = optimizer
        self.max_batch_size = max_batch_size
        # pytree inputs (SURVEY §2 "Nested structures"): wire tensors are
        # flat; an optional example structure repacks them into apply_fn's
        # argument nest, and its schema travels in the info RPC so clients
        # can flatten consistently.
        self.input_structure = input_structure
        if input_structure is not None:
            from learning_at_home_tpu.utils.nested import nested_flatten

            self._input_treedef = jax.tree_util.tree_structure(input_structure)
            structure_arity = len(nested_flatten(input_structure))
            if n_inputs != 1 and n_inputs != structure_arity:
                raise ValueError(
                    f"n_inputs={n_inputs} contradicts input_structure with "
                    f"{structure_arity} leaves — pass only one of them"
                )
            n_inputs = structure_arity
        else:
            self._input_treedef = None
        self.n_inputs = n_inputs  # wire arity: tensors before grad_outputs
        # output wire arity: fixed by apply_fn's tree structure but only
        # discoverable by tracing — set at warmup / first forward, then used
        # to reject over-arity backward requests exactly
        self.n_outputs: Optional[int] = None
        # per-leaf output schema (row dim stripped): published in the info
        # RPC so clients can build io_callback result specs without a
        # hand-written ``output_spec_fn``
        self.output_schema: Optional[list] = None
        # batch buckets AOT-compiled by warmup(): the TaskPool's
        # compile/hit telemetry counts a first-seen bucket outside this
        # set as a cold in-request compile
        self.warm_buckets: frozenset[int] = frozenset()
        self.params = jax.device_put(params)
        self.opt_state = (
            jax.device_put(opt_state)
            if opt_state is not None
            else jax.jit(optimizer.init)(self.params)
        )
        self.update_count = 0
        # guards params/opt_state against torn reads: backward DONATES the
        # old buffers, so a checkpoint snapshot racing an update would read
        # invalidated arrays.  backward runs on the Runtime thread;
        # state_dict may be called from any thread.
        self._state_lock = sanitizer.lock("server.expert_state")

        self._jit_forward = jax.jit(self._forward_impl)
        # params/opt_state donated: XLA reuses their HBM for the new state.
        self._jit_backward = jax.jit(self._backward_impl, donate_argnums=(0, 1))

    # ---- pure computations (jitted once per input-shape bucket) ----

    def _apply(self, params, inputs: tuple):
        if self._input_treedef is not None:
            tree = jax.tree_util.tree_unflatten(self._input_treedef, inputs)
            return self.apply_fn(params, tree)
        return self.apply_fn(params, *inputs)

    def _forward_impl(self, params, inputs: tuple):
        return self._apply(params, inputs)

    def _backward_impl(self, params, opt_state, inputs: tuple, grad_outputs):
        outputs, vjp_fn = jax.vjp(
            lambda p, xs: self._apply(p, xs), params, inputs
        )
        param_grads, input_grads = vjp_fn(grad_outputs)
        # integer wire inputs (e.g. det_dropout's per-row seed) get float0
        # cotangents, which cannot travel the wire — ship f32 zeros; the
        # client discards grads for its integer primals anyway
        input_grads = jax.tree_util.tree_map(
            lambda x, g: (
                jnp.zeros(jnp.shape(x), jnp.float32)
                if getattr(g, "dtype", None) == jax.dtypes.float0
                else g
            ),
            inputs,
            input_grads,
        )
        updates, new_opt_state = self.optimizer.update(
            param_grads, opt_state, params
        )
        new_params = optax.apply_updates(params, updates)
        return input_grads, new_params, new_opt_state

    # ---- runtime-thread entry points (NOT thread-safe by themselves;
    #      the Runtime serializes all calls per process) ----

    def forward(self, inputs: Sequence[np.ndarray]):
        """Run the expert on one padded batch; returns flat output arrays."""
        outputs = self._jit_forward(self.params, tuple(inputs))
        leaves = jax.tree_util.tree_leaves(outputs)
        self._record_output_schema(leaves)
        return leaves

    def _record_output_schema(self, leaves) -> None:
        """Outputs are row-aligned with inputs (the TaskPool scatters rows
        back per task), so shape[0] is the batch dim and shape[1:] is the
        wire-stable per-row schema."""
        self.n_outputs = len(leaves)
        self.output_schema = [
            {"shape": [int(d) for d in np.shape(l)[1:]],
             "dtype": str(np.dtype(l.dtype))}
            for l in leaves
        ]

    def backward(
        self, inputs: Sequence[np.ndarray], grad_outputs: Sequence[np.ndarray]
    ):
        """Return input-grads AND apply the async optimizer step in one XLA call."""
        grad_out = grad_outputs[0] if len(grad_outputs) == 1 else tuple(grad_outputs)
        with self._state_lock:
            input_grads, self.params, self.opt_state = self._jit_backward(
                self.params, self.opt_state, tuple(inputs), grad_out
            )
            self.update_count += 1
        return jax.tree_util.tree_leaves(input_grads)

    # ---- metadata / checkpoint ----

    def warmup(self, sample_inputs: Sequence[np.ndarray], buckets=None) -> int:
        """Pre-compile forward and backward for the padded batch buckets.

        XLA compiles one program per shape; without warmup the first
        request of each bucket size compiles INSIDE its RPC window, which
        on slow hosts reads as a dead expert to clients (and concurrent
        client-side tracing in the same process can stall compiles for
        minutes).  Call before declaring liveness; returns the number of
        programs compiled.  ``sample_inputs``: one example row-batch per
        input tensor (row count is replaced by each bucket size).
        """
        from learning_at_home_tpu.server.task_pool import bucket_rows

        if buckets is None:
            b = 1
            buckets = []
            while b < self.max_batch_size:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_batch_size)
        # compile the buckets the RUNTIME will actually execute: requested
        # sizes map through the same rounding the TaskPool applies
        buckets = sorted({bucket_rows(b, self.max_batch_size) for b in buckets})
        compiled = 0
        for rows in buckets:
            padded = tuple(
                jax.ShapeDtypeStruct(
                    (rows, *np.shape(t)[1:]), np.asarray(t).dtype
                )
                for t in sample_inputs
            )
            # AOT: lower + compile WITHOUT executing — no donation, no
            # state mutation, programs land in the executable cache
            self._jit_forward.lower(self.params, padded).compile()
            out_aval = jax.eval_shape(self._forward_impl, self.params, padded)
            leaves = jax.tree_util.tree_leaves(out_aval)
            self._record_output_schema(leaves)
            grad_out = (
                leaves[0] if len(leaves) == 1 else tuple(leaves)
            )
            self._jit_backward.lower(
                self.params, self.opt_state, padded, grad_out
            ).compile()
            compiled += 2
        self.warm_buckets = self.warm_buckets | frozenset(buckets)
        return compiled

    def get_info(self) -> dict:
        """Serializable expert metadata (for the ``info`` RPC)."""
        info = {
            "name": self.name,
            "max_batch_size": self.max_batch_size,
            "n_inputs": self.n_inputs,
            "num_params": int(
                sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(self.params))
            ),
            "update_count": self.update_count,
        }
        if self.input_structure is not None:
            from learning_at_home_tpu.utils.nested import schema_from_tree

            info["input_schema"] = schema_from_tree(self.input_structure)
        if self.output_schema is not None:
            info["output_schema"] = self.output_schema
        return info

    def state_dict(self) -> dict:
        """Host-side snapshot of params + opt state (for checkpointing)."""
        with self._state_lock:
            return {
                "params": jax.tree_util.tree_map(np.asarray, self.params),
                "opt_state": jax.tree_util.tree_map(np.asarray, self.opt_state),
                "update_count": self.update_count,
            }

    def state_template(self) -> dict:
        """Shapes/dtypes of state_dict WITHOUT copying anything off-device
        (restore template for checkpoint loading)."""

        def to_sds(x):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)

        with self._state_lock:
            return {
                "params": jax.tree_util.tree_map(to_sds, self.params),
                "opt_state": jax.tree_util.tree_map(to_sds, self.opt_state),
                "update_count": 0,
            }

    def load_state_dict(self, state: dict) -> None:
        with self._state_lock:
            self.params = jax.device_put(state["params"])
            self.opt_state = jax.device_put(state["opt_state"])
            self.update_count = int(state.get("update_count", 0))

    def replace_params(self, params) -> None:
        """Swap the parameter tree in place, keeping the optimizer state
        (replica sync: an averaging round over the replicas of one
        expert writes the group mean back here — server/server.py).  The
        state lock serializes against a concurrent backward's donated
        update, so the swap is never a torn read and the Runtime's next
        job sees either tree, never a mix."""
        with self._state_lock:
            self.params = jax.device_put(
                jax.tree_util.tree_map(np.asarray, params)
            )
