"""Runtime: the double-buffered device-consumer loop executing formed batches.

Contract from the reference's ``hivemind/server/runtime.py`` (SURVEY.md §2
[BJ]; unverifiable refs, mount empty): repeatedly pick the
**highest-priority (oldest-waiting) non-empty pool** across all experts, run
its batch on the device, push outputs back to the pool's futures.  A single
serialized consumer per device → no intra-device contention and per-expert
update serialization for free.

TPU-native realization: a dedicated Python thread per process draining a
thread-safe priority queue of :class:`BatchJob`s.  The jitted XLA call
releases the GIL, so the asyncio networking loop keeps serving while the
device computes.  Results are handed back to the event loop via
``call_soon_threadsafe``.

The loop is **double-buffered** to exploit XLA's async dispatch: while job
N's outputs materialize (``np.asarray`` blocks until the device finishes),
job N+1 has already been stacked — into reusable staging buffers from
:mod:`.staging` — and its jitted call dispatched, so host work (stacking,
output copies, future delivery) overlaps device execution instead of
serializing with it.  The one hard exception: two jobs sharing a pool
``serial_key`` (forward/backward of the SAME expert — backward donates the
param buffers forward reads) are never in flight together.
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from learning_at_home_tpu.server.staging import StagingBuffers
from learning_at_home_tpu.server.task_pool import BatchJob
from learning_at_home_tpu.utils.profiling import timeline

logger = logging.getLogger(__name__)

# Sentinel must be a tuple so it compares cleanly inside the PriorityQueue;
# -inf priority drains it ahead of any real job.
_SENTINEL = (float("-inf"), -1, None)


def _job_trace(job: BatchJob) -> Optional[str]:
    """The batch's trace id for span stamping: the single distinct
    non-None task trace, or None when the batch merged several traced
    requests (no single owner) or carried none."""
    distinct = {t for t in getattr(job, "traces", ()) if t}
    return distinct.pop() if len(distinct) == 1 else None


@dataclass
class _Inflight:
    """A dispatched-but-not-materialized job (the second pipeline stage)."""

    job: BatchJob
    raw_outputs: list
    staging: list = field(default_factory=list)
    started: float = 0.0
    dispatch_s: float = 0.0  # duration of the process_fn call itself
    trace: Optional[str] = None  # distributed-tracing id (see _job_trace)


class Runtime:
    """Double-buffered device executor fed by all TaskPools of a Server."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._queue: queue.PriorityQueue = queue.PriorityQueue()
        self._loop = loop
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.staging = StagingBuffers()
        # telemetry (written by the runtime thread; read anywhere)
        self.jobs_processed = 0
        self.jobs_overlapped = 0  # dispatched while another job was in flight
        self.device_time = 0.0  # process_fn + materialization (busy time)
        self.queue_time = 0.0
        self.stack_time = 0.0
        self.materialize_time = 0.0
        self.queue_depth_max = 0

    def attach_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def start(self) -> None:
        assert self._loop is not None, "attach_loop() before start()"
        self._thread = threading.Thread(
            target=self._run, name="lah-runtime", daemon=True
        )
        self._thread.start()

    def submit(self, job: BatchJob) -> None:
        """Called from the event loop when a pool has formed a batch."""
        self._queue.put((job.priority, job.seq, job))
        depth = self._queue.qsize()
        if depth > self.queue_depth_max:
            self.queue_depth_max = depth

    @property
    def queue_depth(self) -> int:
        return self._queue.qsize()

    def _run(self) -> None:
        pending: Optional[_Inflight] = None
        while True:
            if pending is None:
                item = self._queue.get()
            else:
                try:
                    # don't wait: if no new job is ready, spend the idle
                    # time materializing the in-flight one instead
                    item = self._queue.get_nowait()
                except queue.Empty:
                    self._finish(pending)
                    pending = None
                    continue
            _, _, job = item
            if job is None or self._stop.is_set():
                if pending is not None:
                    self._finish(pending)
                    pending = None
                if job is not None:
                    self._deliver(job, None, RuntimeError("runtime shut down"))
                break
            if (
                pending is not None
                and pending.job.pool.serial_key == job.pool.serial_key
            ):
                # per-expert serialization: never overlap two jobs of the
                # same expert/pool — drain the pipeline first
                self._finish(pending)
                pending = None
            overlapped = pending is not None
            inflight = self._dispatch_job(job)
            if pending is not None:
                self._finish(pending)
                pending = None
            if inflight is not None and overlapped:
                self.jobs_overlapped += 1
                timeline.count("runtime.jobs_overlapped")
            pending = inflight
        if pending is not None:
            self._finish(pending)
        self._drain_remaining()

    def _dispatch_job(self, job: BatchJob) -> Optional[_Inflight]:
        """Stage one: stack the batch into staging buffers and dispatch the
        jitted call.  Returns the in-flight record, or None if the job
        failed (error already delivered)."""
        started = time.monotonic()
        self.queue_time += started - job.formed_at
        buffers: list = []
        trace = _job_trace(job)
        try:
            with timeline.span(f"runtime.stack.{job.pool.name}", trace=trace):
                inputs, buffers = job.stack(self.staging)
            stacked = time.monotonic()
            self.stack_time += stacked - started
            job.pool.stack_time += stacked - started
            with timeline.span(
                f"runtime.dispatch.{job.pool.name}", trace=trace
            ):
                raw = list(job.pool.process_fn(inputs))
            dispatched = time.monotonic()
        except BaseException as e:  # deliver, don't kill the device loop
            logger.exception("runtime job failed in pool %s", job.pool.name)
            self.staging.release(buffers)
            self.jobs_processed += 1
            self._deliver(job, None, e)
            return None
        return _Inflight(
            job, raw, buffers, started, dispatched - stacked, trace
        )

    def _finish(self, inflight: _Inflight) -> None:
        """Stage two: materialize the outputs (blocks until the device
        finishes — this is the wait the NEXT job's dispatch overlaps),
        recycle the staging buffers, deliver to the pool's futures."""
        job = inflight.job
        outputs, error = None, None
        t0 = time.monotonic()
        try:
            with timeline.span(
                f"runtime.materialize.{job.pool.name}", trace=inflight.trace
            ):
                outputs = []
                for o in inflight.raw_outputs:
                    arr = np.asarray(o)
                    # a pure-host process_fn can return views INTO the
                    # staging buffers; those must be copied out before the
                    # buffer is recycled under the delivered results
                    if inflight.staging and any(
                        np.may_share_memory(arr, buf)
                        for buf in inflight.staging
                    ):
                        arr = np.array(arr)
                    outputs.append(arr)
        except BaseException as e:
            logger.exception(
                "runtime job failed to materialize in pool %s", job.pool.name
            )
            error = e
        now = time.monotonic()
        self.materialize_time += now - t0
        # device_time keeps its pre-pipeline meaning — process_fn call +
        # output materialization, the job's own busy time.  Under overlap,
        # wall time from dispatch to materialized also contains the NEXT
        # job's stack/dispatch; folding that in would double-count and
        # make the pipelined runtime read as a device-time regression.
        busy = inflight.dispatch_s + (now - t0)
        self.device_time += busy
        self.jobs_processed += 1
        timeline.record(
            f"runtime.{job.pool.name}", inflight.started, busy,
            trace=inflight.trace,
        )
        self.staging.release(inflight.staging)
        self._deliver(job, outputs, error)

    def stats(self) -> dict:
        """Hot-path telemetry snapshot for the server ``stats`` surface."""
        jobs = self.jobs_processed
        return {
            "jobs_processed": jobs,
            "jobs_overlapped": self.jobs_overlapped,
            "overlap_fraction": round(self.jobs_overlapped / jobs, 4) if jobs else 0.0,
            "device_time_ms": round(self.device_time * 1e3, 2),
            "queue_time_ms": round(self.queue_time * 1e3, 2),
            "stack_time_ms": round(self.stack_time * 1e3, 2),
            "materialize_time_ms": round(self.materialize_time * 1e3, 2),
            "queue_depth": self.queue_depth,
            "queue_depth_max": self.queue_depth_max,
            "staging": self.staging.stats(),
        }

    def _deliver(self, job: BatchJob, outputs, error) -> None:
        try:
            self._loop.call_soon_threadsafe(job.pool.deliver, job, outputs, error)
        except RuntimeError:
            pass  # event loop already closed; the futures died with it

    def _drain_remaining(self) -> None:
        """Fail queued-but-never-run jobs fast instead of leaving their
        clients to hit the full RPC timeout."""
        while True:
            try:
                _, _, job = self._queue.get_nowait()
            except queue.Empty:
                return
            if job is not None:
                self._deliver(job, None, RuntimeError("runtime shut down"))

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._queue.put(_SENTINEL)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
