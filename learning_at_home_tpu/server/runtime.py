"""Runtime: the single device-consumer loop executing formed batches.

Contract from the reference's ``hivemind/server/runtime.py`` (SURVEY.md §2
[BJ]; unverifiable refs, mount empty): repeatedly pick the
**highest-priority (oldest-waiting) non-empty pool** across all experts, run
its batch on the device, push outputs back to the pool's futures.  A single
serialized consumer per device → no intra-device contention and per-expert
update serialization for free.

TPU-native realization: a dedicated Python thread per process draining a
thread-safe priority queue of :class:`BatchJob`s.  The jitted XLA call
releases the GIL, so the asyncio networking loop keeps serving while the
device computes.  Results are handed back to the event loop via
``call_soon_threadsafe``.
"""

from __future__ import annotations

import asyncio
import logging
import queue
import threading
import time
from typing import Optional

import numpy as np

from learning_at_home_tpu.server.task_pool import BatchJob
from learning_at_home_tpu.utils.profiling import timeline

logger = logging.getLogger(__name__)

# Sentinel must be a tuple so it compares cleanly inside the PriorityQueue;
# -inf priority drains it ahead of any real job.
_SENTINEL = (float("-inf"), -1, None)


class Runtime:
    """Single-threaded device executor fed by all TaskPools of a Server."""

    def __init__(self, loop: Optional[asyncio.AbstractEventLoop] = None):
        self._queue: queue.PriorityQueue = queue.PriorityQueue()
        self._loop = loop
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # telemetry
        self.jobs_processed = 0
        self.device_time = 0.0
        self.queue_time = 0.0

    def attach_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def start(self) -> None:
        assert self._loop is not None, "attach_loop() before start()"
        self._thread = threading.Thread(
            target=self._run, name="lah-runtime", daemon=True
        )
        self._thread.start()

    def submit(self, job: BatchJob) -> None:
        """Called from the event loop when a pool has formed a batch."""
        self._queue.put((job.priority, job.seq, job))

    def _run(self) -> None:
        while not self._stop.is_set():
            item = self._queue.get()
            _, _, job = item
            if job is None or self._stop.is_set():
                if job is not None:
                    self._deliver(job, None, RuntimeError("runtime shut down"))
                break
            started = time.monotonic()
            self.queue_time += started - job.formed_at
            outputs, error = None, None
            try:
                with timeline.span(f"runtime.{job.pool.name}"):
                    outputs = job.pool.process_fn(job.inputs)
                # Materialize HERE, on the device thread: jit dispatch returns
                # async arrays, and slicing them later on the event loop would
                # block all networking until the device finishes.  This also
                # makes device_time measure actual execution, not dispatch.
                outputs = [np.asarray(o) for o in outputs]
            except BaseException as e:  # deliver, don't kill the device loop
                logger.exception("runtime job failed in pool %s", job.pool.name)
                error = e
            self.device_time += time.monotonic() - started
            self.jobs_processed += 1
            self._deliver(job, outputs, error)
        self._drain_remaining()

    def _deliver(self, job: BatchJob, outputs, error) -> None:
        try:
            self._loop.call_soon_threadsafe(job.pool.deliver, job, outputs, error)
        except RuntimeError:
            pass  # event loop already closed; the futures died with it

    def _drain_remaining(self) -> None:
        """Fail queued-but-never-run jobs fast instead of leaving their
        clients to hit the full RPC timeout."""
        while True:
            try:
                _, _, job = self._queue.get_nowait()
            except queue.Empty:
                return
            if job is not None:
                self._deliver(job, None, RuntimeError("runtime shut down"))

    def shutdown(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._queue.put(_SENTINEL)
        if self._thread is not None:
            self._thread.join(timeout=timeout)
