"""Elastic swarm lifecycle: graceful drain + live expert migration.

The source paper's swarm promises that peers come and go while training
continues, but a departing server used to just vanish — its experts died
with it.  This module is the control flow that turns "kill -9" into
"drain, hand off, rejoin" (ISSUE 9 / ROADMAP item 5):

- **drain** — the server flips to DRAINING: it stops heartbeating its
  experts (DHT record TTL expiry steers new dispatch away; hedged
  replica dispatch covers the stale window), keeps SERVING until the
  records it already published have expired, waits for in-flight batches
  to finish, then migrates every expert to a successor and exits.
- **handoff** — live migration of one expert's params AND optimizer
  state to a successor over the framed tensor wire (always the RAW wire
  — never a quantized codec: migration is bitwise or it failed).  The
  state pytree is flattened to leaves, split into bounded parts, and
  streamed as sequential ``handoff`` RPCs with a per-leaf crc32
  manifest; the successor installs the expert and declares the uid ONLY
  after re-reading the installed state and verifying every leaf's crc —
  a bitwise-verified install.  An interrupted handoff leaves the
  successor clean (sessions expire) and the drain falls back to a
  checkpoint save, from which a restarted server rejoins.

Thread model (docs/CONCURRENCY.md invariant 10): the whole drain
sequence — grace sleep, quiesce polling, state snapshots, handoff RPCs —
runs on ONE dedicated ``lah-drain`` host thread.  The serving loop's
only involvement is plain attribute reads (the lifecycle flag in the
heartbeat task) and the single-threaded handoff-session dict mutated
inside the ``handoff`` RPC handler; no new locks touch the serving loop.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
import uuid
import zlib
from typing import TYPE_CHECKING, Any, Optional, Sequence

import numpy as np

if TYPE_CHECKING:
    from learning_at_home_tpu.server.expert_backend import ExpertBackend
    from learning_at_home_tpu.server.server import Server

logger = logging.getLogger(__name__)

Endpoint = tuple[str, int]


def _monotonic() -> float:
    """Clock seam — the lah-verify explorer replays drain/handoff
    sequences on a virtual clock (deterministic session-TTL expiry and
    quiesce deadlines across interleavings)."""
    return time.monotonic()


def _sleep(seconds: float) -> None:
    """Sleep seam — under the explorer, a drain 'sleep' is a scheduling
    point (advance the virtual clock, maybe switch actors), not a wall
    wait."""
    time.sleep(seconds)


# Machine-checked invariants (lah-verify shape: (name, what is
# asserted)); enforced by the explorer's lifecycle world against a real
# run_drain / HandoffReceiver driven through the seams above.
VERIFIED_INVARIANTS = (
    ("lifecycle.drain_no_abort",
     "a drain that quiesced in budget never retires an expert while the "
     "server still reports in-flight batches — draining waits, it never "
     "aborts work"),
    ("lifecycle.finish_drain_always",
     "_finish_drain runs on every drain path, success or failure — the "
     "server can never be wedged in DRAINING"),
    ("lifecycle.no_state_dropped",
     "every expert is handed off, checkpointed, or explicitly reported "
     "failed — no training state silently vanishes in a drain"),
    ("lifecycle.handoff_sessions_bounded",
     "the receiver never holds more than MAX_SESSIONS half-open "
     "sessions, and abandoned sessions are TTL-garbage-collected"),
    ("lifecycle.migrate_handoff_before_retire",
     "a live placement migration retires the source copy only after the "
     "successor acked a bitwise-verified install — the uid's hoster "
     "count never dips below its pre-move value"),
    ("lifecycle.migrate_failure_keeps_source",
     "a migration whose handoff failed leaves the source copy hosted "
     "and serving — a failed move degrades to no move, never to a lost "
     "expert or a dropped in-flight dispatch"),
)

# Lifecycle states a server advertises (stats RPC + telemetry extras;
# lah_top renders them).  DEAD is never self-reported — it is the
# observer-side verdict when a peer's telemetry record expired.
SERVING = "SERVING"
DRAINING = "DRAINING"
DRAINED = "DRAINED"

# One handoff part carries at most this many payload bytes (whole leaves
# are never split — a leaf larger than the cap travels alone in its own
# part; MAX_FRAME_BYTES is 1 GiB, so the cap is flow control, not a
# correctness bound).  Parts are sent SEQUENTIALLY — each awaited before
# the next — so receiver-side assembly needs no reordering and the
# transfer never floods the successor's serving loop.
HANDOFF_PART_BYTES = int(
    os.environ.get("LAH_HANDOFF_PART_BYTES", str(4 << 20))
)

# A half-assembled handoff session whose sender died is garbage-collected
# after this long (lazily, on the next handoff RPC — an idle server holds
# no timer for it).
HANDOFF_SESSION_TTL_S = float(
    os.environ.get("LAH_HANDOFF_SESSION_TTL_S", "60")
)


class HandoffError(RuntimeError):
    """A live migration failed (peer refused, transfer interrupted, or
    verification mismatched).  The drain falls back to checkpointing the
    expert so a restart can still recover it."""


# --------------------------------------------------------------------------
# state <-> wire: flatten, manifest, verify
# --------------------------------------------------------------------------


def _leaf_crc(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def flatten_state(state: dict) -> tuple[list, list]:
    """``ExpertBackend.state_dict()`` → (leaves, manifest).

    Only ``params`` and ``opt_state`` travel as tensors (``update_count``
    rides in the RPC meta).  The manifest carries one
    ``{"shape", "dtype", "crc"}`` entry per leaf — the bitwise contract
    the successor verifies AFTER install, by re-reading its own installed
    state.  Leaf order is the deterministic ``jax.tree_util`` flatten of
    ``{"params", "opt_state"}``; both sides host the same expert zoo
    (the replica-recipe contract), so their tree structures agree — and
    any mismatch is caught leaf-by-leaf against the receiver's template.
    """
    import jax

    leaves = [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(
            {"params": state["params"], "opt_state": state["opt_state"]}
        )
    ]
    manifest = [
        {
            "shape": [int(d) for d in leaf.shape],
            "dtype": str(leaf.dtype),
            "crc": _leaf_crc(leaf),
        }
        for leaf in leaves
    ]
    return leaves, manifest


def split_parts(leaves: Sequence[np.ndarray], part_bytes: int) -> list[list[int]]:
    """Greedy leaf-index grouping: each part stays under ``part_bytes``
    unless a single leaf alone exceeds it.  Always at least one part —
    an expert with zero-size state still completes the RPC sequence."""
    parts: list[list[int]] = []
    current: list[int] = []
    current_bytes = 0
    for i, leaf in enumerate(leaves):
        n = int(leaf.nbytes)
        if current and current_bytes + n > part_bytes:
            parts.append(current)
            current, current_bytes = [], 0
        current.append(i)
        current_bytes += n
    parts.append(current)
    return parts


def verify_manifest(leaves: Sequence[np.ndarray], manifest: Sequence[dict]) -> bool:
    """True iff every leaf matches its manifest entry bitwise."""
    if len(leaves) != len(manifest):
        return False
    for leaf, entry in zip(leaves, manifest):
        if list(leaf.shape) != list(entry["shape"]):
            return False
        if str(leaf.dtype) != entry["dtype"]:
            return False
        if _leaf_crc(leaf) != entry["crc"]:
            return False
    return True


# --------------------------------------------------------------------------
# sender side (runs on the lah-drain host thread)
# --------------------------------------------------------------------------


def send_expert_handoff(
    successor: Endpoint,
    uid: str,
    state: dict,
    *,
    timeout: float = 60.0,
    part_bytes: Optional[int] = None,
) -> dict:
    """Stream one expert's state to ``successor`` and return the final
    reply meta.  Raises :class:`HandoffError` unless the successor
    reports a bitwise-verified install.

    Runs on a HOST thread (the drain thread): payloads are serialized
    here via ``WireTensors.prepare`` and only the ready buffers cross
    the ``lah-client`` loop (``rpc_prepared`` — the pack-once contract).
    The wire is the RAW v1/v2 tensor framing with no ``wire`` meta: a
    quantized codec would break the bitwise contract by construction.
    """
    from learning_at_home_tpu.client.rpc import client_loop, pool_registry
    from learning_at_home_tpu.utils.connection import RemoteCallError
    from learning_at_home_tpu.utils.serialization import WireTensors

    part_bytes = HANDOFF_PART_BYTES if part_bytes is None else part_bytes
    leaves, manifest = flatten_state(state)
    parts = split_parts(leaves, part_bytes)
    session = uuid.uuid4().hex[:16]
    pool = pool_registry().get(tuple(successor))
    final_meta: dict = {}
    for part_idx, leaf_idxs in enumerate(parts):
        meta = {
            "uid": uid,
            "session": session,
            "part": part_idx,
            "n_parts": len(parts),
        }
        if part_idx == 0:
            # the manifest travels once, up front: the receiver can
            # reject a structurally impossible transfer before buffering
            # a single payload part
            meta["manifest"] = manifest
            meta["update_count"] = int(state.get("update_count", 0))
        wire = WireTensors.prepare([leaves[i] for i in leaf_idxs])
        try:
            _tensors, reply = client_loop().run(
                pool.rpc_prepared("handoff", wire, meta, timeout=timeout)
            )
        # asyncio.TimeoutError is NOT builtins.TimeoutError on 3.10 —
        # missing it here would skip the checkpoint fallback
        except (
            RemoteCallError, OSError, TimeoutError, asyncio.TimeoutError,
        ) as e:
            raise HandoffError(
                f"handoff of {uid} to {successor} failed at part "
                f"{part_idx + 1}/{len(parts)}: {type(e).__name__}: {e}"
            ) from e
        final_meta = reply if isinstance(reply, dict) else {}
    if not (final_meta.get("installed") and final_meta.get("verified")):
        raise HandoffError(
            f"handoff of {uid} to {successor}: successor did not report a "
            f"verified install (reply meta: {final_meta})"
        )
    return final_meta


def run_migration(
    server: "Server", uid: str, successor: Endpoint, *,
    timeout: float = 60.0,
) -> dict:
    """Move ONE serving expert to ``successor`` — the placement
    rebalancer's actuation primitive (ISSUE 16; the ``migrate`` RPC's
    background thread runs this).

    Ordering is run_drain's per-uid success path, without the drain:
    hand off first, retire the source copy only after the successor's
    bitwise-verified install acked.  The source keeps SERVING the uid
    through the whole transfer, so its hoster count never dips below
    the pre-move value and dispatches in flight complete on whichever
    copy holds them (VERIFIED_INVARIANTS: migrate_handoff_before_retire,
    migrate_failure_keeps_source — the lah-verify migration world
    explores exactly these interleavings).  A failed handoff raises
    :class:`HandoffError` with the source untouched: a failed move
    degrades to no move.

    The handed-off state is the source's live snapshot at send time;
    updates landing during the transfer stay on the source copy until
    retire — the same bounded-staleness window a drain's quiesce timeout
    accepts, and replica averaging reconverges it.
    """
    backend = server.experts.get(uid)
    if backend is None:
        raise ValueError(f"migrate: uid {uid!r} is not hosted here")
    try:
        send_expert_handoff(
            tuple(successor), uid, backend.state_dict(), timeout=timeout
        )
    except Exception:
        server.migration_failures += 1
        raise
    server._retire_expert(uid)
    server.migrations_out += 1
    logger.info("migrated %s -> %s:%s", uid, successor[0], successor[1])
    return {"uid": uid, "target": list(successor), "handed_off": True,
            "retired": True}


# --------------------------------------------------------------------------
# receiver side (serving loop; heavy work hops to worker threads)
# --------------------------------------------------------------------------


class _HandoffSession:
    __slots__ = (
        "uid", "n_parts", "manifest", "update_count", "leaves",
        "next_part", "created_at",
    )

    def __init__(self, uid: str, n_parts: int, manifest: list,
                 update_count: int):
        self.uid = uid
        self.n_parts = n_parts
        self.manifest = manifest
        self.update_count = update_count
        self.leaves: list = []
        self.next_part = 0
        self.created_at = _monotonic()


class HandoffReceiver:
    """Per-server assembly of inbound expert migrations.

    All session-dict mutation happens ON the serving loop (the
    ``handoff`` RPC handler), which is single-threaded — no lock, same
    contract as ``Server._replicas_installing``.  The expensive finalize
    (backend build, state load, crc re-verification) hops to a worker
    thread; only the pool start + DHT declare return to the loop.
    """

    MAX_SESSIONS = 16  # concurrent half-open migrations; more is abuse

    def __init__(self, server: "Server"):
        self.server = server
        self._sessions: dict[str, _HandoffSession] = {}
        self.received = 0       # verified installs
        self.rejected = 0       # refused / failed / mismatched transfers

    def _gc(self) -> None:
        now = _monotonic()
        for key in [
            k for k, s in self._sessions.items()
            if now - s.created_at > HANDOFF_SESSION_TTL_S
        ]:
            stale = self._sessions.pop(key)
            logger.warning(
                "handoff session for %s abandoned after %.0fs — sender "
                "died mid-transfer; dropping %d buffered leaves",
                stale.uid, now - stale.created_at, len(stale.leaves),
            )

    async def handle_part(self, meta: dict, tensors: Sequence) -> dict:
        """One ``handoff`` RPC.  Peer-supplied meta — validate
        structurally; any failure raises ``ValueError`` which the
        connection handler turns into an error reply (the sender's
        :class:`HandoffError` path)."""
        self._gc()
        srv = self.server
        if srv.lifecycle_state != SERVING:
            self.rejected += 1
            raise ValueError(
                f"server is {srv.lifecycle_state}: a draining server "
                "cannot accept expert migrations"
            )
        uid = meta.get("uid")
        session_id = meta.get("session")
        part = meta.get("part")
        n_parts = meta.get("n_parts")
        if not (isinstance(uid, str) and uid):
            raise ValueError("handoff needs a uid")
        if not (isinstance(session_id, str) and 0 < len(session_id) <= 64):
            raise ValueError("handoff needs a session id")
        if not (
            isinstance(part, int) and isinstance(n_parts, int)
            and 0 <= part < n_parts
        ):
            raise ValueError("handoff part indices are inconsistent")
        key = f"{uid}/{session_id}"
        if part == 0:
            manifest = meta.get("manifest")
            if not isinstance(manifest, list) or not all(
                isinstance(m, dict) for m in manifest
            ):
                raise ValueError("handoff part 0 must carry the manifest")
            if len(self._sessions) >= self.MAX_SESSIONS:
                self.rejected += 1
                raise ValueError("too many concurrent handoff sessions")
            if uid in srv._replicas_installing:
                self.rejected += 1
                raise ValueError(
                    f"an install for {uid} is already in flight"
                )
            self._sessions[key] = _HandoffSession(
                uid, n_parts, manifest,
                int(meta.get("update_count") or 0),
            )
        session = self._sessions.get(key)
        if session is None:
            raise ValueError(
                f"unknown handoff session for {uid} (expired or never "
                "opened with part 0)"
            )
        if part != session.next_part or n_parts != session.n_parts:
            del self._sessions[key]
            raise ValueError(
                f"handoff part {part} arrived out of order "
                f"(expected {session.next_part})"
            )
        session.leaves.extend(np.asarray(t) for t in tensors)
        session.next_part += 1
        if len(session.leaves) > len(session.manifest):
            del self._sessions[key]
            raise ValueError("handoff carries more leaves than its manifest")
        if session.next_part < session.n_parts:
            return {"uid": uid, "session": session_id, "part": part,
                    "ok": True}
        # final part: install + verify, then declare
        del self._sessions[key]
        return await self._finalize(session)

    async def _finalize(self, session: _HandoffSession) -> dict:
        srv = self.server
        uid = session.uid
        if len(session.leaves) != len(session.manifest):
            self.rejected += 1
            raise ValueError(
                f"handoff for {uid} delivered {len(session.leaves)} leaves, "
                f"manifest promises {len(session.manifest)}"
            )
        if uid in srv._replicas_installing:
            # a second session for the uid raced this finalize (its own
            # part-0 check predates our install window): refuse — two
            # concurrent installs would leak one session's started pools
            self.rejected += 1
            raise ValueError(f"an install for {uid} is already in flight")
        existing = srv.experts.get(uid)
        srv._replicas_installing.add(uid)
        try:
            backend, verified = await asyncio.to_thread(
                self._install_state, existing, session
            )
            if not verified:
                self.rejected += 1
                raise ValueError(
                    f"handoff for {uid}: installed state failed bitwise "
                    "verification against the sender's manifest"
                )
            if existing is None:
                # new expert: pools + immediate declare (the successor
                # declares the uid ONLY here, after verification)
                await srv._install_replica(uid, backend, replica=False)
            else:
                # the uid was already hosted (e.g. as a replica): the
                # migrated state — the most-trained copy — replaced it
                # in place; re-declare so the record is fresh
                await srv._declare_now(uid)
            srv.migrated_in.add(uid)
            self.received += 1
        finally:
            srv._replicas_installing.discard(uid)
        logger.info("handoff: installed migrated expert %s (verified)", uid)
        return {
            "uid": uid, "ok": True, "installed": True, "verified": True,
            "hosted": True,
        }

    def _install_state(
        self, existing: Optional["ExpertBackend"], session: _HandoffSession
    ) -> tuple["ExpertBackend", bool]:
        """Worker-thread half of finalize: build-or-reuse the backend,
        load the migrated leaves, and re-read the installed state to
        verify the manifest bitwise.  Shape/dtype validation runs
        against the receiver's OWN template (never trusting the wire)."""
        import jax

        srv = self.server
        backend = existing
        if backend is None:
            backend = srv._make_replica_backend(
                session.uid, allow_checkpoint=False
            )
        template = backend.state_template()
        t_leaves, treedef = jax.tree_util.tree_flatten(
            {"params": template["params"],
             "opt_state": template["opt_state"]}
        )
        if len(t_leaves) != len(session.leaves):
            raise ValueError(
                f"migrated state for {session.uid} has "
                f"{len(session.leaves)} leaves; this server's zoo "
                f"template has {len(t_leaves)} — expert zoo mismatch"
            )
        for got, want in zip(session.leaves, t_leaves):
            if tuple(got.shape) != tuple(want.shape) or np.dtype(
                got.dtype
            ) != np.dtype(want.dtype):
                raise ValueError(
                    f"migrated leaf {got.shape}/{got.dtype} does not "
                    f"match template {want.shape}/{want.dtype} for "
                    f"{session.uid}"
                )
        tree = jax.tree_util.tree_unflatten(treedef, session.leaves)
        # an EXISTING backend is live state: snapshot it first so a
        # failed verification can roll back — the bitwise-or-it-failed
        # contract must hold in the failure case too, not replace a
        # good replica with unverified bytes
        previous = existing.state_dict() if existing is not None else None
        backend.load_state_dict(
            {
                "params": tree["params"],
                "opt_state": tree["opt_state"],
                "update_count": session.update_count,
            }
        )
        # bitwise verification of the INSTALLED state: re-read what the
        # backend will actually serve and check it against the sender's
        # manifest — a device_put round-trip that mangled a single byte
        # fails the transfer instead of silently serving corrupt weights
        installed = backend.state_dict()
        leaves = [
            np.asarray(leaf)
            for leaf in jax.tree_util.tree_leaves(
                {"params": installed["params"],
                 "opt_state": installed["opt_state"]}
            )
        ]
        verified = verify_manifest(leaves, session.manifest)
        if not verified and previous is not None:
            backend.load_state_dict(previous)
            logger.warning(
                "handoff for %s failed verification — existing state "
                "rolled back", session.uid,
            )
        return backend, verified

    def stats(self) -> dict:
        return {
            "sessions_open": len(self._sessions),
            "received": self.received,
            "rejected": self.rejected,
        }


# --------------------------------------------------------------------------
# drain coordinator (runs on the lah-drain host thread)
# --------------------------------------------------------------------------


def pick_successor(server: "Server") -> Optional[Endpoint]:
    """Least-loaded peer from the ``load.<prefix>`` DHT heartbeats
    (queue depth, then hosted-expert count, then endpoint for
    determinism), excluding this server.  None when the swarm has no
    other advertised server — the drain then falls back to checkpoint."""
    if server.dht is None:
        return None
    from learning_at_home_tpu.utils.telemetry import load_key, parse_load_value

    own = f"{server.endpoint[0]}:{server.endpoint[1]}"
    candidates = []
    try:
        records = server.dht.get_sync(load_key(server.telemetry_prefix))
    except Exception as e:
        logger.warning("successor discovery failed: %s: %s",
                       type(e).__name__, e)
        return None
    for subkey, entry in records.items():
        if not isinstance(subkey, str) or subkey == own:
            continue
        value = entry[0] if isinstance(entry, (tuple, list)) else entry
        load = parse_load_value(value)
        host, _, port = subkey.rpartition(":")
        if load is None or not port.isdigit() or not host:
            continue
        candidates.append(
            (load.get("q", 0.0), load.get("n", 0), (host, int(port)))
        )
    if not candidates:
        return None
    return min(candidates)[2]


def run_drain(
    server: "Server",
    *,
    successor: Optional[Endpoint] = None,
    grace: Optional[float] = None,
    quiesce_timeout: float = 30.0,
    handoff: bool = True,
    handoff_timeout: float = 60.0,
) -> dict:
    """The full graceful-drain sequence; returns a summary dict.

    1. flip to DRAINING — the heartbeat task stops re-declaring experts
       (telemetry keeps heartbeating so observers see the state);
    2. keep serving for ``grace`` seconds (default: the declared record
       TTL, ``2 x update_period``) so every record published before the
       flip expires and clients steer away;
    3. quiesce — poll until every task pool and the runtime queue are
       empty (bounded by ``quiesce_timeout``; a busy server drains its
       in-flight batches, it never aborts them);
    4. migrate every expert to the successor (explicit endpoint, or the
       least-loaded peer from the load heartbeats); failures fall back
       to a checkpoint save under ``server.replica_checkpoint_root``;
    5. flip to DRAINED and report.

    Runs on a host thread (asserted via the sanitizer in
    ``Server.drain``); never call on a server loop.
    """
    t0 = _monotonic()
    summary: dict[str, Any] = {
        "handed_off": [], "checkpointed": [], "failed": [],
        "successor": None,
    }
    already = server._begin_drain()
    if already:
        raise RuntimeError("server is already draining")
    # the periodic checkpointer must NOT run through the drain: a save
    # taken while _retire_expert shrinks self.experts would write a
    # partial (or empty) step as the newest COMPLETE checkpoint, which
    # a --resume relaunch would then restore over the real state.  The
    # drain's own fallback saves through save_checkpoint directly.
    if server.checkpoint_manager is not None:
        try:
            server.checkpoint_manager.stop()
        except Exception:
            logger.exception("drain: stopping the checkpointer failed")
    try:
        if grace is None:
            grace = 2.0 * server.update_period if server.dht is not None else 0.0
        if grace > 0:
            logger.info(
                "drain: serving through the %.1fs record-expiry grace "
                "window", grace,
            )
            _sleep(grace)
        quiesce_deadline = _monotonic() + max(0.0, quiesce_timeout)
        settled = 0
        while _monotonic() < quiesce_deadline:
            if server.pools_idle():
                settled += 1
                if settled >= 3:  # idle across consecutive polls, not a gap
                    break
            else:
                settled = 0
            _sleep(max(server.batch_timeout, 0.02))
        else:
            logger.warning(
                "drain: pools still busy after %.1fs quiesce budget — "
                "handing off anyway (late updates stay on this copy)",
                quiesce_timeout,
            )
        if handoff and server.experts:
            target = tuple(successor) if successor else pick_successor(server)
            summary["successor"] = list(target) if target else None
            if target is None:
                logger.warning(
                    "drain: no successor available — falling back to "
                    "checkpoint for all %d experts", len(server.experts),
                )
            else:
                for uid in sorted(server.experts):
                    backend = server.experts.get(uid)
                    if backend is None:
                        continue
                    # catch EVERYTHING per expert: one snapshot/retire
                    # failure must not abort the other migrations, and
                    # the checkpoint fallback below must still run for
                    # whatever did not make it across
                    try:
                        send_expert_handoff(
                            target, uid, backend.state_dict(),
                            timeout=handoff_timeout,
                        )
                        summary["handed_off"].append(uid)
                        server._retire_expert(uid)
                    except HandoffError as e:
                        logger.warning("drain: %s", e)
                        summary["failed"].append(uid)
                    except Exception:
                        logger.exception(
                            "drain: handoff of %s failed unexpectedly", uid
                        )
                        summary["failed"].append(uid)
        remaining = [
            uid for uid in sorted(server.experts)
            if uid not in summary["handed_off"]
        ]
        if remaining:
            root = server.replica_checkpoint_root
            if root:
                try:
                    step = server.save_checkpoint(root)
                    summary["checkpointed"] = remaining
                    summary["checkpoint_step"] = step
                except Exception:
                    logger.exception(
                        "drain: fallback checkpoint failed — %d experts "
                        "will restart from an older step (or the seed)",
                        len(remaining),
                    )
            else:
                logger.warning(
                    "drain: %d experts have no successor and no checkpoint "
                    "root — their training state dies with this process",
                    len(remaining),
                )
    finally:
        server._finish_drain()
    summary["duration_s"] = round(_monotonic() - t0, 3)
    logger.info(
        "drain complete in %.1fs: %d handed off, %d checkpointed, %d failed",
        summary["duration_s"], len(summary["handed_off"]),
        len(summary["checkpointed"]), len(summary["failed"]),
    )
    return summary
