from learning_at_home_tpu.server.expert_backend import ExpertBackend
from learning_at_home_tpu.server.task_pool import TaskPool, BatchJob, bucket_rows
from learning_at_home_tpu.server.runtime import Runtime
from learning_at_home_tpu.server.staging import StagingBuffers
from learning_at_home_tpu.server.chaos import ChaosConfig, ChaosInjector
from learning_at_home_tpu.server.server import Server, background_server

__all__ = [
    "ExpertBackend",
    "TaskPool",
    "BatchJob",
    "bucket_rows",
    "Runtime",
    "StagingBuffers",
    "ChaosConfig",
    "ChaosInjector",
    "Server",
    "background_server",
]
