"""Standalone expert server CLI — the reference's ``Server.create`` entry
point (SURVEY.md §3.3): start a peer hosting N experts, join the DHT swarm,
declare + heartbeat, serve until interrupted.

    python -m learning_at_home_tpu.server \
        --num-experts 4 --expert-cls ffn --hidden-dim 1024 \
        --expert-prefix ffn --port 31337 \
        --initial-peers 10.0.0.1:31338 \
        --checkpoint-dir ./ckpt --checkpoint-every 300
"""

from __future__ import annotations

import argparse
import signal
import threading


def parse_endpoint(s: str) -> tuple[str, int]:
    host, sep, port = s.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(
            f"--initial-peers entry {s!r} must be host:port (e.g. 10.0.0.1:31337)"
        )
    return (host, int(port))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-experts", type=int, default=4)
    p.add_argument("--expert-cls", default="ffn",
                   choices=["ffn", "transformer", "swiglu", "nop"])
    p.add_argument("--hidden-dim", type=int, default=1024)
    p.add_argument("--expert-prefix", default="expert")
    p.add_argument("--expert-offset", type=int, default=0,
                   help="first expert index (partition a grid across servers)")
    p.add_argument("--expert-uids", default=None,
                   help="comma-separated explicit uid list (e.g. "
                        "'ffn0.1,ffn1.3'); overrides prefix/offset/num; "
                        "params seeded stably per uid")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--dht-port", type=int, default=0)
    p.add_argument("--initial-peers", nargs="*", default=[],
                   help="host:port of existing DHT peers")
    p.add_argument("--no-dht", action="store_true")
    p.add_argument("--update-period", type=float, default=15.0)
    p.add_argument("--max-batch-size", type=int, default=1024)
    p.add_argument("--optimizer", default="adam", choices=["adam", "sgd", "adamw"])
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=float, default=0.0,
                   help="seconds between checkpoints (0 = only on shutdown)")
    p.add_argument("--checkpoint-keep-last", type=int, default=3,
                   help="complete checkpoint steps to retain (older ones "
                        "and crashed half-saves are pruned)")
    p.add_argument("--resume", action="store_true",
                   help="load the latest checkpoint before serving")
    p.add_argument("--drain-on-term", action="store_true",
                   help="graceful lifecycle (ISSUE 9): the first SIGTERM "
                        "DRAINS instead of exiting — stop heartbeating "
                        "(DHT expiry steers dispatch away), finish "
                        "in-flight batches, migrate every expert's params"
                        "+optimizer state to a successor (checkpoint "
                        "fallback), then exit.  A second SIGTERM forces "
                        "immediate shutdown")
    p.add_argument("--drain-grace", type=float, default=None,
                   help="seconds to keep serving after the drain starts "
                        "(default: the declared record TTL, 2 x "
                        "--update-period, so published records expire)")
    p.add_argument("--drain-successor", default=None,
                   help="host:port to migrate experts to on drain "
                        "(default: least-loaded peer from the load.* "
                        "DHT heartbeats)")
    p.add_argument("--warmup", type=int, nargs="*", default=None,
                   help="pre-compile fwd/bwd for these batch-bucket sizes "
                        "before serving (e.g. --warmup 64 256 1024); "
                        "no value = all power-of-2 buckets")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--telemetry-prefix", default="swarm",
                   help="DHT scope the metrics endpoint is advertised "
                        "under (telemetry.<prefix>); lah_top discovers "
                        "all peers sharing a prefix")
    p.add_argument("--transport", default="asyncio",
                   choices=["asyncio", "native"],
                   help="data plane: asyncio loop, or the C++ epoll "
                        "framepump (GIL-free socket work; multi-core hosts)")
    p.add_argument("--chaos-latency", type=float, default=0.0,
                   help="inject WAN-like base latency (seconds) per request")
    p.add_argument("--chaos-jitter", type=float, default=0.0)
    p.add_argument("--chaos-straggler-prob", type=float, default=0.0)
    p.add_argument("--chaos-straggler-delay", type=float, default=1.5)
    p.add_argument("--chaos-bandwidth", type=float, default=0.0,
                   help="emulated link bandwidth in bytes/sec (0 = "
                        "unlimited); each reply delayed by payload/bw")
    args = p.parse_args()

    import logging

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )

    import optax

    from learning_at_home_tpu.dht import DHT
    from learning_at_home_tpu.server import ChaosConfig, Server

    optimizer = {
        "adam": optax.adam,
        "adamw": optax.adamw,
        "sgd": optax.sgd,
    }[args.optimizer](args.lr)

    dht = None
    if not args.no_dht:
        dht = DHT(
            initial_peers=[parse_endpoint(s) for s in args.initial_peers],
            port=args.dht_port,
        )
        print(f"DHT node at {dht.endpoint}", flush=True)

    if args.warmup is not None:
        # True = all power-of-two buckets; a list = exactly those sizes
        warmup = args.warmup if args.warmup else True
    else:
        warmup = False
    expert_uids = None
    if args.expert_uids is not None:
        expert_uids = [u.strip() for u in args.expert_uids.split(",") if u.strip()]
        if not expert_uids:
            raise SystemExit("--expert-uids given but empty")
    server = Server.create(
        num_experts=args.num_experts,
        expert_cls=args.expert_cls,
        hidden_dim=args.hidden_dim,
        expert_prefix=args.expert_prefix,
        expert_offset=args.expert_offset,
        expert_uids=expert_uids,
        optimizer=optimizer,
        max_batch_size=args.max_batch_size,
        warmup=warmup,
        seed=args.seed,
        start=False,
        host=args.host,
        port=args.port,
        dht=dht,
        update_period=args.update_period,
        transport=args.transport,
        telemetry_prefix=args.telemetry_prefix,
        chaos=(
            ChaosConfig(
                base_latency=args.chaos_latency,
                jitter=args.chaos_jitter,
                straggler_prob=args.chaos_straggler_prob,
                straggler_delay=args.chaos_straggler_delay,
                bandwidth_bps=args.chaos_bandwidth,
                seed=args.seed,
            )
            if args.chaos_latency or args.chaos_jitter
            or args.chaos_straggler_prob or args.chaos_bandwidth
            else None
        ),
    )
    experts = server.experts
    # replicas installed via the ``replica`` RPC and the drain fallback
    # restore from THIS server's checkpoint root (never peer-supplied)
    server.replica_checkpoint_root = args.checkpoint_dir
    server.run_in_background()
    ckpt_mgr = None
    if args.checkpoint_dir:
        from learning_at_home_tpu.utils.checkpoint import CheckpointManager

        ckpt_mgr = CheckpointManager(
            args.checkpoint_dir, keep_last=args.checkpoint_keep_last
        )
    if args.resume and ckpt_mgr is not None:
        try:
            step = server.load_checkpoint(args.checkpoint_dir)
            server.restarts = ckpt_mgr.record_restart()
            print(f"resumed from checkpoint step {step} "
                  f"(restart #{server.restarts})", flush=True)
        except FileNotFoundError:
            print("no checkpoint found; starting fresh", flush=True)
    if ckpt_mgr is not None and args.checkpoint_every > 0:
        ckpt_mgr.start_periodic(
            lambda step: server.save_checkpoint(args.checkpoint_dir, step),
            args.checkpoint_every,
        )
        server.checkpoint_manager = ckpt_mgr
    span = (
        f"({sorted(experts)[0]}..{sorted(experts)[-1]}) " if experts
        # a server may boot EMPTY and gain experts via replica RPCs
        else "(none yet — replica-host mode) "
    )
    print(
        f"serving {len(experts)} {args.expert_cls!r} experts "
        f"{span}on "
        f"{server.endpoint[0]}:{server.endpoint[1]} "
        f"(metrics http://{server.endpoint[0]}:{server.metrics_port}/metrics)",
        flush=True,
    )

    stop = threading.Event()
    drain_req = threading.Event()

    def on_term(*_):
        # first SIGTERM with --drain-on-term: graceful drain (handled by
        # the main loop — a signal handler must not block through the
        # whole sequence); second SIGTERM, or no drain flag: exit now
        if args.drain_on_term and not drain_req.is_set():
            drain_req.set()
        else:
            stop.set()

    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, on_term)
    successor = (
        parse_endpoint(args.drain_successor) if args.drain_successor else None
    )
    drained = False
    while not stop.wait(timeout=0.5):
        if drain_req.is_set() and not drained:
            drained = True
            print("SIGTERM: draining (migrate experts, then exit) ...",
                  flush=True)
            server.start_drain(successor=successor, grace=args.drain_grace)
        if drained and server.wait_drained(timeout=0.0):
            print(f"drain complete: {server.drain_summary}", flush=True)
            break
    if ckpt_mgr is not None and not drained:
        # a drain already checkpointed whatever it could not hand off;
        # the plain-shutdown path snapshots everything here instead.
        # Stop the periodic thread FIRST: racing it on next_step() could
        # mark a torn two-writer snapshot complete
        ckpt_mgr.stop()
        step = ckpt_mgr.save_now(
            lambda s: server.save_checkpoint(args.checkpoint_dir, s)
        )
        if step is None:
            print("final checkpoint FAILED (see log)", flush=True)
        else:
            print(f"final checkpoint saved @ step {step}", flush=True)
    server.shutdown()
    if dht is not None:
        dht.shutdown()
    print("server shut down", flush=True)


if __name__ == "__main__":
    main()
