"""Standalone expert server CLI — the reference's ``Server.create`` entry
point (SURVEY.md §3.3): start a peer hosting N experts, join the DHT swarm,
declare + heartbeat, serve until interrupted.

    python -m learning_at_home_tpu.server \
        --num-experts 4 --expert-cls ffn --hidden-dim 1024 \
        --expert-prefix ffn --port 31337 \
        --initial-peers 10.0.0.1:31338 \
        --checkpoint-dir ./ckpt --checkpoint-every 300
"""

from __future__ import annotations

import argparse
import signal
import threading
import time


def parse_endpoint(s: str) -> tuple[str, int]:
    host, sep, port = s.rpartition(":")
    if not sep or not port.isdigit():
        raise SystemExit(
            f"--initial-peers entry {s!r} must be host:port (e.g. 10.0.0.1:31337)"
        )
    return (host, int(port))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num-experts", type=int, default=4)
    p.add_argument("--expert-cls", default="ffn",
                   choices=["ffn", "transformer", "swiglu", "nop"])
    p.add_argument("--hidden-dim", type=int, default=1024)
    p.add_argument("--expert-prefix", default="expert")
    p.add_argument("--expert-offset", type=int, default=0,
                   help="first expert index (partition a grid across servers)")
    p.add_argument("--expert-uids", default=None,
                   help="comma-separated explicit uid list (e.g. "
                        "'ffn0.1,ffn1.3'); overrides prefix/offset/num; "
                        "params seeded stably per uid")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--dht-port", type=int, default=0)
    p.add_argument("--initial-peers", nargs="*", default=[],
                   help="host:port of existing DHT peers")
    p.add_argument("--no-dht", action="store_true")
    p.add_argument("--update-period", type=float, default=15.0)
    p.add_argument("--max-batch-size", type=int, default=1024)
    p.add_argument("--optimizer", default="adam", choices=["adam", "sgd", "adamw"])
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=float, default=0.0,
                   help="seconds between checkpoints (0 = only on shutdown)")
    p.add_argument("--resume", action="store_true",
                   help="load the latest checkpoint before serving")
    p.add_argument("--warmup", type=int, nargs="*", default=None,
                   help="pre-compile fwd/bwd for these batch-bucket sizes "
                        "before serving (e.g. --warmup 64 256 1024); "
                        "no value = all power-of-2 buckets")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--telemetry-prefix", default="swarm",
                   help="DHT scope the metrics endpoint is advertised "
                        "under (telemetry.<prefix>); lah_top discovers "
                        "all peers sharing a prefix")
    p.add_argument("--transport", default="asyncio",
                   choices=["asyncio", "native"],
                   help="data plane: asyncio loop, or the C++ epoll "
                        "framepump (GIL-free socket work; multi-core hosts)")
    p.add_argument("--chaos-latency", type=float, default=0.0,
                   help="inject WAN-like base latency (seconds) per request")
    p.add_argument("--chaos-jitter", type=float, default=0.0)
    p.add_argument("--chaos-straggler-prob", type=float, default=0.0)
    p.add_argument("--chaos-straggler-delay", type=float, default=1.5)
    p.add_argument("--chaos-bandwidth", type=float, default=0.0,
                   help="emulated link bandwidth in bytes/sec (0 = "
                        "unlimited); each reply delayed by payload/bw")
    args = p.parse_args()

    import logging

    logging.basicConfig(
        level=logging.INFO, format="%(asctime)s %(name)s: %(message)s"
    )

    import optax

    from learning_at_home_tpu.dht import DHT
    from learning_at_home_tpu.server import ChaosConfig, Server

    optimizer = {
        "adam": optax.adam,
        "adamw": optax.adamw,
        "sgd": optax.sgd,
    }[args.optimizer](args.lr)

    dht = None
    if not args.no_dht:
        dht = DHT(
            initial_peers=[parse_endpoint(s) for s in args.initial_peers],
            port=args.dht_port,
        )
        print(f"DHT node at {dht.endpoint}", flush=True)

    if args.warmup is not None:
        # True = all power-of-two buckets; a list = exactly those sizes
        warmup = args.warmup if args.warmup else True
    else:
        warmup = False
    expert_uids = None
    if args.expert_uids is not None:
        expert_uids = [u.strip() for u in args.expert_uids.split(",") if u.strip()]
        if not expert_uids:
            raise SystemExit("--expert-uids given but empty")
    server = Server.create(
        num_experts=args.num_experts,
        expert_cls=args.expert_cls,
        hidden_dim=args.hidden_dim,
        expert_prefix=args.expert_prefix,
        expert_offset=args.expert_offset,
        expert_uids=expert_uids,
        optimizer=optimizer,
        max_batch_size=args.max_batch_size,
        warmup=warmup,
        seed=args.seed,
        start=False,
        host=args.host,
        port=args.port,
        dht=dht,
        update_period=args.update_period,
        transport=args.transport,
        telemetry_prefix=args.telemetry_prefix,
        chaos=(
            ChaosConfig(
                base_latency=args.chaos_latency,
                jitter=args.chaos_jitter,
                straggler_prob=args.chaos_straggler_prob,
                straggler_delay=args.chaos_straggler_delay,
                bandwidth_bps=args.chaos_bandwidth,
                seed=args.seed,
            )
            if args.chaos_latency or args.chaos_jitter
            or args.chaos_straggler_prob or args.chaos_bandwidth
            else None
        ),
    )
    experts = server.experts
    # replicas installed via the ``replica`` RPC restore from THIS
    # server's checkpoint root (never a peer-supplied path)
    server.replica_checkpoint_root = args.checkpoint_dir
    server.run_in_background()
    ckpt_step = 0
    if args.resume and args.checkpoint_dir:
        try:
            ckpt_step = server.load_checkpoint(args.checkpoint_dir)
            print(f"resumed from checkpoint step {ckpt_step}", flush=True)
        except FileNotFoundError:
            print("no checkpoint found; starting fresh", flush=True)
    span = (
        f"({sorted(experts)[0]}..{sorted(experts)[-1]}) " if experts
        # a server may boot EMPTY and gain experts via replica RPCs
        else "(none yet — replica-host mode) "
    )
    print(
        f"serving {len(experts)} {args.expert_cls!r} experts "
        f"{span}on "
        f"{server.endpoint[0]}:{server.endpoint[1]} "
        f"(metrics http://{server.endpoint[0]}:{server.metrics_port}/metrics)",
        flush=True,
    )

    stop = threading.Event()
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    last_ckpt = time.monotonic()
    while not stop.wait(timeout=1.0):
        if (
            args.checkpoint_dir
            and args.checkpoint_every > 0
            and time.monotonic() - last_ckpt >= args.checkpoint_every
        ):
            ckpt_step += 1
            server.save_checkpoint(args.checkpoint_dir, ckpt_step)
            last_ckpt = time.monotonic()
    if args.checkpoint_dir:
        server.save_checkpoint(args.checkpoint_dir, ckpt_step + 1)
        print("final checkpoint saved", flush=True)
    server.shutdown()
    if dht is not None:
        dht.shutdown()
    print("server shut down", flush=True)


if __name__ == "__main__":
    main()
