"""Server-side RPC dispatch: forward / backward / info over framed TCP.

Contract from the reference's ``hivemind/server/connection_handler.py``
(SURVEY.md §2; unverifiable refs, mount empty): accept connections, parse
message type, deserialize tensors, submit to the right expert's pool, await
the future, reply.  Reference runs one-or-more *processes*; here it is pure
asyncio on the server's event loop — each connection is a coroutine, and
the expensive work (XLA execution) happens on the Runtime thread anyway.

Wire protocol (see utils/serialization.py for framing):

- ``forward``:  meta {uid}, tensors [*inputs]            → ``result`` [*outputs]
- ``backward``: meta {uid, n_inputs}, tensors [*inputs, *grad_outputs]
                                                          → ``result`` [*input_grads]
- ``info``:     meta {uid}                                → ``result`` meta=info
- ``multi``:    meta {op: forward|backward,
                      parts: [{uid, n_tensors}...]},
                tensors = concatenation in parts order     → ``result``
                meta {parts: [{uid, ok, n_tensors, message?}...]},
                tensors = concatenation of successful parts' outputs.
                ONE request serves every expert a client picked on this
                server — the swarm fan-out pays per-request overhead per
                PEER, not per expert (failure granularity is per-peer
                anyway: co-hosted experts die together).
- errors                                                  → ``error`` meta {message}

Wire compression: a request whose meta carries ``{"wire": "bfloat16"}``
(or ``"float16"``) declares that its floating tensors were downcast to
that dtype for transport.  The handler upcasts them to float32 BEFORE the
task pool (so batches stay one-dtype and each bucket compiles once) and
downcasts the reply's floating tensors back to the wire dtype.  Halves
activation/grad bytes on the DCN tier — the 2048-row swarm dispatches are
payload-bound (BASELINE.md round-2: 300 ms p50).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

import numpy as np

from learning_at_home_tpu.utils.serialization import (
    WIRE_DTYPES,
    is_float_dtype,
    pack_message,
    recv_frame,
    send_frame,
    unpack_message,
    wire_cast,
)

if TYPE_CHECKING:
    from learning_at_home_tpu.server.server import Server

logger = logging.getLogger(__name__)


def upcast_from_wire(tensors, wire: str | None) -> list:
    """Wire-compressed floating tensors → float32 compute dtype.

    A declared wire dtype is a CONTRACT: every floating payload must
    actually carry it.  Keying the upcast on each tensor's observed dtype
    would silently launder a client-side encoding bug (e.g. wire=bfloat16
    declared, float64 sent) into a normal-looking float32 batch; reject
    the mismatch so the client gets an error reply instead (round-4
    advisor)."""
    if not wire:
        return list(tensors)
    expected = np.dtype(wire)
    out = []
    for t in tensors:
        arr = np.asarray(t)
        if is_float_dtype(arr.dtype):
            if arr.dtype != expected:
                raise ValueError(
                    f"request declares wire={wire} but carries a "
                    f"{arr.dtype} floating tensor — client-side encoding "
                    "bug; refusing to upcast"
                )
            out.append(arr.astype(np.float32))
        else:
            out.append(t)
    return out


def downcast_to_wire(tensors, wire: str | None) -> list:
    """Reply's floating tensors → the requester's wire dtype."""
    return wire_cast(tensors, wire or None)


class ConnectionHandler:
    """Dispatches one TCP connection's requests to expert task pools."""

    def __init__(self, server: "Server"):
        self.server = server

    async def handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                reply = await self._dispatch(payload)
                if self.server.chaos is not None:
                    if not await self.server.chaos.before_reply(
                        len(payload) + len(reply)
                    ):
                        continue  # injected drop: client sees a timeout
                await send_frame(writer, reply)
        except Exception:
            logger.exception("connection handler failed for peer %s", peer)
        finally:
            writer.close()

    # ---- per-op execution (validation + pool submit), shared by the
    #      single-expert and multi-expert paths; raises on any failure ----

    async def _run_forward(self, uid: str, tensors, wire: str | None = None) -> list:
        backend = self.server.experts.get(uid)
        if backend is None:
            raise ValueError(f"unknown expert uid: {uid!r}")
        if len(tensors) != backend.n_inputs:
            # reject HERE: a wrong-arity task reaching the pool would
            # poison the whole formed batch (innocent co-batched
            # requests fail with it)
            raise ValueError(
                f"expert {uid} takes {backend.n_inputs} inputs, "
                f"got {len(tensors)}"
            )
        tensors = upcast_from_wire(tensors, wire)
        result = await self.server.forward_pools[uid].submit_task(*tensors)
        return downcast_to_wire(result, wire)

    async def _run_backward(
        self, uid: str, tensors, declared_n_inputs, wire: str | None = None
    ) -> list:
        backend = self.server.experts.get(uid)
        if backend is None:
            raise ValueError(f"unknown expert uid: {uid!r}")
        n_inputs = (
            int(declared_n_inputs)
            if declared_n_inputs is not None
            else backend.n_inputs
        )
        if n_inputs != backend.n_inputs:
            raise ValueError(
                f"expert {uid} takes {backend.n_inputs} inputs, "
                f"request declared {n_inputs}"
            )
        # mirror the forward guard: a backward request carries the
        # inputs PLUS the grad_outputs; wrong arity in EITHER
        # direction must be rejected before it can poison a formed
        # batch (exact check once n_outputs is known, i.e. after
        # warmup or the first forward)
        expected = (
            backend.n_inputs + backend.n_outputs
            if backend.n_outputs is not None
            else None
        )
        if (expected is not None and len(tensors) != expected) or (
            len(tensors) <= backend.n_inputs
        ):
            raise ValueError(
                f"backward for {uid} needs "
                f"{expected or f'>{backend.n_inputs}'} tensors "
                f"(inputs + grad_outputs), got {len(tensors)}"
            )
        tensors = upcast_from_wire(tensors, wire)
        result = await self.server.backward_pools[uid].submit_task(*tensors)
        return downcast_to_wire(result, wire)

    async def _run_multi(self, tensors, meta) -> bytes:
        """Fan a merged request out to the local expert pools concurrently;
        per-part failures are reported per part, not as a whole-request
        error.  All meta is peer-supplied — validate structurally."""
        op = meta.get("op")
        parts = meta.get("parts")
        wire = meta.get("wire")
        if op not in ("forward", "backward") or not isinstance(parts, list):
            raise ValueError("multi needs op forward|backward and parts list")
        slices = []
        off = 0
        for part in parts:
            if not isinstance(part, dict):
                raise ValueError("multi part must be a dict")
            n = part.get("n_tensors")
            if not isinstance(n, int) or n < 0 or off + n > len(tensors):
                raise ValueError("multi part tensor counts are inconsistent")
            slices.append((part, tensors[off : off + n]))
            off += n
        if off != len(tensors):
            raise ValueError(
                f"multi parts cover {off} tensors, request has {len(tensors)}"
            )

        async def run_part(part, part_tensors):
            uid = part.get("uid")
            if op == "forward":
                return await self._run_forward(uid, part_tensors, wire)
            return await self._run_backward(
                uid, part_tensors, part.get("n_inputs"), wire
            )

        settled = await asyncio.gather(
            *(run_part(p, t) for p, t in slices), return_exceptions=True
        )
        reply_parts, reply_tensors = [], []
        for (part, _), result in zip(slices, settled):
            uid = part.get("uid")
            if isinstance(result, BaseException):
                logger.warning(
                    "multi %s part failed for expert %s: %s", op, uid, result
                )
                reply_parts.append(
                    {"uid": uid, "ok": False,
                     "message": f"{type(result).__name__}: {result}"}
                )
            else:
                reply_parts.append(
                    {"uid": uid, "ok": True, "n_tensors": len(result)}
                )
                reply_tensors.extend(result)
        return pack_message("result", reply_tensors, {"parts": reply_parts})

    def _server_stats(self) -> dict:
        """Server-WIDE counters in one round trip (the ``info`` op is
        per-expert): ops dashboards and swarm telemetry poll this instead
        of fanning out one RPC per hosted expert."""
        srv = self.server
        experts = {}
        total_updates = 0
        for uid, backend in srv.experts.items():
            experts[uid] = backend.update_count
            total_updates += backend.update_count
        pools = {}
        for kind, pool_map in (
            ("forward", srv.forward_pools), ("backward", srv.backward_pools)
        ):
            rows = padded = batches = cold = hits = 0
            stack_ms = 0.0
            buckets: dict[int, int] = {}
            for p in pool_map.values():
                rows += p.total_rows
                padded += p.padded_rows
                batches += p.batches_formed
                stack_ms += p.stack_time * 1e3
                bs = p.bucket_stats()
                cold += bs["cold_compiles"]
                hits += bs["cache_hits"]
                for bucket, n in bs["batches_per_bucket"].items():
                    buckets[bucket] = buckets.get(bucket, 0) + n
            pools[kind] = {
                "rows": rows, "padded_rows": padded,
                "batches_formed": batches,
                "padding_waste": padded / (rows + padded) if rows + padded else 0.0,
                "stack_time_ms": round(stack_ms, 2),
                # string keys: the msgpack wire rejects int map keys
                "batches_per_bucket": {
                    str(b): n for b, n in sorted(buckets.items())
                },
                "bucket_cold_compiles": cold,
                "bucket_cache_hits": hits,
            }
        stats = {
            "n_experts": len(srv.experts),
            "update_count_total": total_updates,
            "update_count": experts,
            "pools": pools,
            # hot-path pipeline counters: queue depth, stacking/materialize
            # time, overlap fraction, staging-buffer reuse (ISSUE 1)
            "runtime": srv.runtime.stats(),
        }
        if srv.chaos is not None:
            stats["chaos"] = {
                "delays": srv.chaos.injected_delays,
                "stragglers": srv.chaos.injected_stragglers,
                "drops": srv.chaos.injected_drops,
            }
        return stats

    async def _dispatch(self, payload: bytes) -> bytes:
        try:
            msg_type, tensors, meta = unpack_message(payload)
        except Exception as e:
            return pack_message("error", meta={"message": f"malformed request: {e}"})
        uid = meta.get("uid")
        wire = meta.get("wire")
        if wire is not None and wire not in WIRE_DTYPES:
            return pack_message(
                "error",
                meta={"message": f"unsupported wire dtype {wire!r}; "
                      f"supported: {WIRE_DTYPES}"},
            )
        try:
            if msg_type == "forward":
                return pack_message(
                    "result", await self._run_forward(uid, tensors, wire)
                )
            elif msg_type == "backward":
                return pack_message(
                    "result",
                    await self._run_backward(
                        uid, tensors, meta.get("n_inputs"), wire
                    ),
                )
            elif msg_type == "multi":
                return await self._run_multi(tensors, meta)
            elif msg_type == "info":
                backend = self.server.experts.get(uid)
                if backend is None:
                    raise ValueError(f"unknown expert uid: {uid!r}")
                return pack_message("result", meta=backend.get_info())
            elif msg_type == "stats":
                return pack_message("result", meta=self._server_stats())
            else:
                return pack_message(
                    "error", meta={"message": f"unknown message type {msg_type!r}"}
                )
        except Exception as e:
            logger.exception("request %s failed (expert %s)", msg_type, uid)
            return pack_message("error", meta={"message": f"{type(e).__name__}: {e}"})
