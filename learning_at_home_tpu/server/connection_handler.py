"""Server-side RPC dispatch: forward / backward / info over framed TCP.

Contract from the reference's ``hivemind/server/connection_handler.py``
(SURVEY.md §2; unverifiable refs, mount empty): accept connections, parse
message type, deserialize tensors, submit to the right expert's pool, await
the future, reply.  Reference runs one-or-more *processes*; here it is pure
asyncio on the server's event loop — each connection is a coroutine, and
the expensive work (XLA execution) happens on the Runtime thread anyway.

Wire protocol (see utils/serialization.py for framing):

- ``forward``:  meta {uid}, tensors [*inputs]            → ``result`` [*outputs]
- ``backward``: meta {uid, n_inputs}, tensors [*inputs, *grad_outputs]
                                                          → ``result`` [*input_grads]
- ``info``:     meta {uid}                                → ``result`` meta=info
- ``multi``:    meta {op: forward|backward,
                      parts: [{uid, n_tensors}...]},
                tensors = concatenation in parts order     → ``result``
                meta {parts: [{uid, ok, n_tensors, message?}...]},
                tensors = concatenation of successful parts' outputs.
                ONE request serves every expert a client picked on this
                server — the swarm fan-out pays per-request overhead per
                PEER, not per expert (failure granularity is per-peer
                anyway: co-hosted experts die together).
- ``hello``:    meta {features: [...]}                    → ``hello_ok``
                meta {features: intersection} and the connection becomes
                protocol v2: requests carry a header ``rid`` which the
                reply echoes, many requests may be in flight, replies
                arrive in COMPLETION order (docs/PROTOCOL.md).
- errors                                                  → ``error`` meta {message}

Wire compression: a request whose meta carries ``{"wire": "bfloat16"}``
(or ``"float16"``) declares that its floating tensors were downcast to
that dtype for transport.  The handler upcasts them to float32 BEFORE the
task pool (so batches stay one-dtype and each bucket compiles once) and
downcasts the reply's floating tensors back to the wire dtype.  Halves
activation/grad bytes on the DCN tier — the 2048-row swarm dispatches are
payload-bound (BASELINE.md round-2: 300 ms p50).
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

import numpy as np

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.profiling import timeline
from learning_at_home_tpu.utils.serialization import (
    WIRE_CODECS,
    WIRE_DTYPES,
    WireTensors,
    decode_wire_tensors,
    encode_wire_tensors,
    frame_nbytes,
    is_float_dtype,
    pack_frames,
    peek_header,
    recv_frame,
    send_frame_parts,
    unpack_message,
    wire_cast,
    wire_codec_name,
)

if TYPE_CHECKING:
    from learning_at_home_tpu.server.server import Server

logger = logging.getLogger(__name__)

# Features the asyncio transport speaks; a client ``hello`` gets back the
# intersection with what it offered.  The native C++ pump does NOT
# negotiate (its dispatcher replies through handler._dispatch, where
# ``hello`` lands in the unknown-message error path), so clients fall
# back to protocol v1 against it — by design, not by accident.
# ``codec``: the request may carry the DICT wire form (quantized 8-bit
# codecs with per-tensor headers — serialization.py, docs/PROTOCOL.md);
# clients never offer quantized payloads to peers that did not echo it.
SERVER_FEATURES = ("mux", "codec")

# Reply payloads at least this large (decoded bytes) quantize in the
# default executor, not on the serving loop — the server-side mirror of
# the client's encode-on-the-host-thread contract.  Small replies encode
# inline: a thread hop costs more than the quantize itself.
ENCODE_OFFLOOP_BYTES = 1 << 18


def upcast_from_wire(tensors, wire: str | None) -> list:
    """Wire-compressed floating tensors → float32 compute dtype.

    A declared wire dtype is a CONTRACT: every floating payload must
    actually carry it.  Keying the upcast on each tensor's observed dtype
    would silently launder a client-side encoding bug (e.g. wire=bfloat16
    declared, float64 sent) into a normal-looking float32 batch; reject
    the mismatch so the client gets an error reply instead (round-4
    advisor)."""
    if not wire:
        return list(tensors)
    expected = np.dtype(wire)
    out = []
    for t in tensors:
        arr = np.asarray(t)
        if is_float_dtype(arr.dtype):
            if arr.dtype != expected:
                raise ValueError(
                    f"request declares wire={wire} but carries a "
                    f"{arr.dtype} floating tensor — client-side encoding "
                    "bug; refusing to upcast"
                )
            out.append(arr.astype(np.float32))
        else:
            out.append(t)
    return out


def downcast_to_wire(tensors, wire: str | None) -> list:
    """Reply's floating tensors → the requester's wire dtype."""
    return wire_cast(tensors, wire or None)


def decode_request_wire(tensors, wire) -> list:
    """Request payload → compute tensors, both wire meta forms.

    Legacy string form: the strict eager upcast above.  Dict (codec)
    form: per-tensor validation with QUANTIZED tensors wrapped as
    :class:`~learning_at_home_tpu.utils.serialization.LazyDecode` — the
    dequantize runs on the Runtime thread, directly into the batch's
    staging buffer, never on this serving loop."""
    if isinstance(wire, dict):
        return decode_wire_tensors(tensors, wire, lazy=True)
    return upcast_from_wire(tensors, wire)


async def encode_reply_wire(tensors, wire) -> tuple[list, dict | None]:
    """Reply tensors → the requester's wire encoding.  Returns
    ``(wire_tensors, reply_wire_meta)``; the meta is None for the legacy
    forms (the downcast dtype is visible in the tensor specs).  Quantized
    encodes of large replies run in the default executor so the serving
    loop never spends milliseconds quantizing a 4 MB batch reply."""
    if not isinstance(wire, dict):
        return downcast_to_wire(tensors, wire), None
    codec = wire.get("c")
    nbytes = sum(np.asarray(t).nbytes for t in tensors)
    if nbytes >= ENCODE_OFFLOOP_BYTES:
        return await asyncio.to_thread(encode_wire_tensors, tensors, codec)
    # deliberate on-loop encode: below ENCODE_OFFLOOP_BYTES the thread
    # hop costs more than the quantize itself — scoped sanitizer pass,
    # so any OTHER on-loop encode still trips the check
    with sanitizer.allowed("EncodedBatch.encode"):
        # lah-lint: ignore[R1] size-gated: this branch only runs below
        # ENCODE_OFFLOOP_BYTES, where a thread hop costs more than the
        # quantize; large replies took the to_thread branch above
        return encode_wire_tensors(tensors, codec)


class ConnectionHandler:
    """Dispatches one TCP connection's requests to expert task pools."""

    def __init__(self, server: "Server"):
        self.server = server

    async def handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        muxed = False  # becomes True after a ``hello`` negotiates v2
        wlock = asyncio.Lock()  # one frame at a time on the socket
        inflight: set[asyncio.Task] = set()
        try:
            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                try:
                    msg_type, rid = peek_header(payload)
                except Exception:
                    msg_type, rid = None, None  # _dispatch makes the error reply
                if msg_type == "hello":
                    # protocol v2 feature negotiation: echo the feature
                    # subset we speak; the connection is multiplexed from
                    # here on (request-id-tagged frames, replies in
                    # completion order)
                    # hello meta is peer-supplied: a non-map meta or a
                    # non-list offer negotiates the empty feature set
                    # instead of tearing down the connection
                    try:
                        _, _, hmeta = unpack_message(payload)
                        offered = hmeta.get("features")
                    except Exception:
                        offered = None
                    if not isinstance(offered, list):
                        offered = []
                    common = [f for f in SERVER_FEATURES if f in offered]
                    muxed = "mux" in common
                    await self._send(
                        writer, wlock,
                        pack_frames(
                            "hello_ok", WireTensors.prepare(),
                            {"features": common}, rid=rid,
                        ),
                    )
                    continue
                if muxed and rid is not None:
                    # serve concurrently; each reply carries its request id
                    # so the client can match out-of-order completions
                    task = asyncio.get_running_loop().create_task(
                        self._serve_muxed(payload, rid, writer, wlock)
                    )
                    inflight.add(task)
                    task.add_done_callback(inflight.discard)
                    continue
                reply = await self._dispatch(payload, rid)
                if self.server.chaos is not None:
                    if not await self.server.chaos.before_reply(
                        len(payload) + frame_nbytes(reply) - 4
                    ):
                        continue  # injected drop: client sees a timeout
                await self._send(writer, wlock, reply)
        except Exception:
            logger.exception("connection handler failed for peer %s", peer)
        finally:
            for task in inflight:
                task.cancel()
            writer.close()

    @staticmethod
    async def _send(writer, wlock: asyncio.Lock, parts: list) -> None:
        async with wlock:
            await send_frame_parts(writer, parts)

    @staticmethod
    def _count_wire_bytes(wire, nbytes: int, direction: str) -> None:
        """``lah_server_wire_bytes_total{codec=,direction=}``: data-plane
        bytes by negotiated wire codec — the observable the byte-reduction
        acceptance gates on.  One labeled counter inc per request/reply
        (never per row); label cardinality is bounded by construction
        (|WIRE_CODECS| x 2)."""
        from learning_at_home_tpu.utils.metrics import registry

        registry.counter(
            "lah_server_wire_bytes_total",
            "request/reply payload bytes by wire codec",
        ).inc(nbytes, codec=wire_codec_name(wire), direction=direction)

    async def _serve_muxed(
        self, payload: bytes, rid: int, writer, wlock: asyncio.Lock
    ) -> None:
        try:
            reply = await self._dispatch(payload, rid)
            if self.server.chaos is not None:
                if not await self.server.chaos.before_reply(
                    len(payload) + frame_nbytes(reply) - 4
                ):
                    return  # injected drop: client sees a timeout
            await self._send(writer, wlock, reply)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("muxed request %d failed", rid)

    # ---- per-op execution (validation + pool submit), shared by the
    #      single-expert and multi-expert paths; raises on any failure ----

    async def _run_forward(
        self, uid: str, tensors, wire=None,
        trace: str | None = None,
    ) -> tuple[list, dict | None]:
        backend = self.server.experts.get(uid)
        if backend is None:
            raise ValueError(f"unknown expert uid: {uid!r}")
        if len(tensors) != backend.n_inputs:
            # reject HERE: a wrong-arity task reaching the pool would
            # poison the whole formed batch (innocent co-batched
            # requests fail with it)
            raise ValueError(
                f"expert {uid} takes {backend.n_inputs} inputs, "
                f"got {len(tensors)}"
            )
        tensors = decode_request_wire(tensors, wire)
        result = await self.server.forward_pools[uid].submit_task(
            *tensors, trace=trace
        )
        return await encode_reply_wire(result, wire)

    async def _run_backward(
        self, uid: str, tensors, declared_n_inputs, wire=None,
        trace: str | None = None,
    ) -> tuple[list, dict | None]:
        backend = self.server.experts.get(uid)
        if backend is None:
            raise ValueError(f"unknown expert uid: {uid!r}")
        n_inputs = (
            int(declared_n_inputs)
            if declared_n_inputs is not None
            else backend.n_inputs
        )
        if n_inputs != backend.n_inputs:
            raise ValueError(
                f"expert {uid} takes {backend.n_inputs} inputs, "
                f"request declared {n_inputs}"
            )
        # mirror the forward guard: a backward request carries the
        # inputs PLUS the grad_outputs; wrong arity in EITHER
        # direction must be rejected before it can poison a formed
        # batch (exact check once n_outputs is known, i.e. after
        # warmup or the first forward)
        expected = (
            backend.n_inputs + backend.n_outputs
            if backend.n_outputs is not None
            else None
        )
        if (expected is not None and len(tensors) != expected) or (
            len(tensors) <= backend.n_inputs
        ):
            raise ValueError(
                f"backward for {uid} needs "
                f"{expected or f'>{backend.n_inputs}'} tensors "
                f"(inputs + grad_outputs), got {len(tensors)}"
            )
        tensors = decode_request_wire(tensors, wire)
        result = await self.server.backward_pools[uid].submit_task(
            *tensors, trace=trace
        )
        return await encode_reply_wire(result, wire)

    async def _run_multi(self, tensors, meta, rid=None, trace=None) -> list:
        """Fan a merged request out to the local expert pools concurrently;
        per-part failures are reported per part, not as a whole-request
        error.  All meta is peer-supplied — validate structurally."""
        op = meta.get("op")
        parts = meta.get("parts")
        wire = meta.get("wire")
        if op not in ("forward", "backward") or not isinstance(parts, list):
            raise ValueError("multi needs op forward|backward and parts list")
        # dict (codec) wire form: headers align 1:1 with the request's
        # tensor concat — slice them per part exactly like the tensors
        wire_headers = None
        if isinstance(wire, dict):
            wire_headers = wire.get("h")
            if not isinstance(wire_headers, list) or len(wire_headers) != len(
                tensors
            ):
                raise ValueError(
                    "multi wire codec headers do not align with the "
                    "request's tensors"
                )
        slices = []
        off = 0
        for part in parts:
            if not isinstance(part, dict):
                raise ValueError("multi part must be a dict")
            n = part.get("n_tensors")
            if not isinstance(n, int) or n < 0 or off + n > len(tensors):
                raise ValueError("multi part tensor counts are inconsistent")
            part_wire = wire
            if wire_headers is not None:
                part_wire = {"c": wire.get("c"),
                             "h": wire_headers[off : off + n]}
            slices.append((part, tensors[off : off + n], part_wire))
            off += n
        if off != len(tensors):
            raise ValueError(
                f"multi parts cover {off} tensors, request has {len(tensors)}"
            )

        async def run_part(part, part_tensors, part_wire):
            uid = part.get("uid")
            if op == "forward":
                return await self._run_forward(
                    uid, part_tensors, part_wire, trace
                )
            return await self._run_backward(
                uid, part_tensors, part.get("n_inputs"), part_wire, trace
            )

        settled = await asyncio.gather(
            *(run_part(p, t, w) for p, t, w in slices), return_exceptions=True
        )
        reply_parts, reply_tensors, reply_headers = [], [], []
        for (part, _t, _w), result in zip(slices, settled):
            uid = part.get("uid")
            if isinstance(result, BaseException):
                logger.warning(
                    "multi %s part failed for expert %s: %s", op, uid, result
                )
                reply_parts.append(
                    {"uid": uid, "ok": False,
                     "message": f"{type(result).__name__}: {result}"}
                )
            else:
                part_tensors, part_wire = result
                reply_parts.append(
                    {"uid": uid, "ok": True, "n_tensors": len(part_tensors)}
                )
                reply_tensors.extend(part_tensors)
                if isinstance(part_wire, dict):
                    reply_headers.extend(part_wire["h"])
        reply_meta = {"parts": reply_parts}
        if isinstance(wire, dict) and len(reply_headers) == len(reply_tensors) \
                and reply_tensors:
            # per-part encodes concatenate like the tensors themselves:
            # one header entry per reply tensor, in parts order.  (A dict
            # request whose codec is a plain downcast produces no headers
            # — the reply then travels like the legacy form.)
            reply_meta["wire"] = {"c": wire.get("c"), "h": reply_headers}
        if trace is not None:
            reply_meta["trace"] = trace  # echo: the reply joins the trace
        # reply prepare is an O(#tensors) spec walk over zero-copy
        # memoryviews — the O(bytes) work (encode/downcast) already ran
        # off-loop or in the executor above
        return pack_frames(
            "result",
            WireTensors.prepare(reply_tensors),  # lah-lint: ignore[R1]
            reply_meta, rid=rid,
        )

    def _server_stats(self, include_spans: bool = False) -> dict:
        """Server-WIDE counters in one round trip (the ``info`` op is
        per-expert): ops dashboards and swarm telemetry poll this instead
        of fanning out one RPC per hosted expert.

        ``include_spans`` (request meta ``{"spans": true}``) adds the
        Timeline span summaries.  Opt-in on purpose: summarizing a full
        span deque on a PROFILED server is O(100k) work that would
        otherwise run on this serving loop every time a monitor polls —
        the dedicated-loop ``/metrics.json`` endpoint is the stall-free
        default surface for span data."""
        srv = self.server
        experts = {}
        total_updates = 0
        for uid, backend in srv.experts.items():
            experts[uid] = backend.update_count
            total_updates += backend.update_count
        pools = {}
        for kind, pool_map in (
            ("forward", srv.forward_pools), ("backward", srv.backward_pools)
        ):
            rows = padded = batches = cold = hits = 0
            stack_ms = 0.0
            buckets: dict[int, int] = {}
            for p in pool_map.values():
                rows += p.total_rows
                padded += p.padded_rows
                batches += p.batches_formed
                stack_ms += p.stack_time * 1e3
                bs = p.bucket_stats()
                cold += bs["cold_compiles"]
                hits += bs["cache_hits"]
                for bucket, n in bs["batches_per_bucket"].items():
                    buckets[bucket] = buckets.get(bucket, 0) + n
            pools[kind] = {
                "rows": rows, "padded_rows": padded,
                "batches_formed": batches,
                "padding_waste": padded / (rows + padded) if rows + padded else 0.0,
                "stack_time_ms": round(stack_ms, 2),
                # string keys: the msgpack wire rejects int map keys
                "batches_per_bucket": {
                    str(b): n for b, n in sorted(buckets.items())
                },
                "bucket_cold_compiles": cold,
                "bucket_cache_hits": hits,
            }
        from learning_at_home_tpu.utils.metrics import registry
        from learning_at_home_tpu.utils.telemetry import (
            link_snapshot as _link_snapshot,
        )

        stats = {
            "n_experts": len(srv.experts),
            "update_count_total": total_updates,
            "update_count": experts,
            # replication observability (ISSUE 8): which hosted uids are
            # replicas, and which experts are currently hot (queue-depth
            # EMA over the threshold — the replicas.wanted signal)
            "replicas": sorted(srv.replica_uids),
            "hot_experts": srv.hot_experts(),
            # elastic lifecycle (ISSUE 9): drain state, uptime, restarts
            # and migration counters — one poll tells an operator whether
            # this peer is SERVING, mid-drain, or freshly rejoined
            "lifecycle": srv.lifecycle_info(),
            "pools": pools,
            # hot-path pipeline counters: queue depth, stacking/materialize
            # time, overlap fraction, staging-buffer reuse (ISSUE 1)
            "runtime": srv.runtime.stats(),
            # ALWAYS-ON headline registry (ISSUE 4): the ~10 production
            # counters are never empty just because LAH_PROFILE is off —
            # this is the same snapshot the /metrics.json endpoint serves
            "metrics": registry.snapshot(),
            # placement measurement + actuation (ISSUE 16): this
            # server's measured per-destination link EMAs and its
            # outbound-migration state — the rebalancer's stats-RPC view
            "links": _link_snapshot(),
            "placement": srv.placement_info(),
        }
        if include_spans:
            stats["spans"] = timeline.summary()
        if srv.chaos is not None:
            stats["chaos"] = {
                "delays": srv.chaos.injected_delays,
                "stragglers": srv.chaos.injected_stragglers,
                "drops": srv.chaos.injected_drops,
            }
        return stats

    async def _dispatch(self, payload: bytes, rid=None) -> list:
        """Serve one request; returns the reply as vectored frame parts
        (``pack_frames`` output — header buffer + raw tensor blobs), so
        the reply payload is never joined into one bytestring on this
        loop.  ``rid`` (protocol v2) is echoed into the reply header.

        A ``{"trace": id}`` meta entry (distributed tracing) is
        peer-supplied: it is structurally validated, stamped onto this
        request's server-side spans and the downstream pool/runtime
        spans, and ECHOED into the reply meta so the client can join the
        round trip.  Absent trace → exactly the old behavior."""
        trace = None

        def reply(msg_type: str, tensors=(), meta=None) -> list:
            if trace is not None:
                meta = {**(meta or {}), "trace": trace}
            return pack_frames(
                msg_type, WireTensors.prepare(tensors), meta, rid=rid
            )

        def wire_reply(result: tuple) -> list:
            """``result`` is an ``encode_reply_wire`` pair: tensors plus
            the reply's wire meta (dict codec form only — the legacy
            downcast needs no meta, its dtype is in the tensor specs)."""
            tensors, rwire = result
            meta = {"wire": rwire} if isinstance(rwire, dict) else None
            return reply("result", tensors, meta)

        try:
            msg_type, tensors, meta = unpack_message(payload)
            if not isinstance(meta, dict):
                raise ValueError(
                    f"meta must be a map, got {type(meta).__name__}"
                )
        except Exception as e:
            return reply("error", meta={"message": f"malformed request: {e}"})
        uid = meta.get("uid")
        wire = meta.get("wire")
        trace = meta.get("trace")
        if not (isinstance(trace, str) and 0 < len(trace) <= 64):
            trace = None  # malformed/absent: never trust peer-supplied meta
        if isinstance(wire, str) and wire not in WIRE_DTYPES:
            return reply(
                "error",
                meta={"message": f"unsupported wire dtype {wire!r}; "
                      f"supported: {WIRE_DTYPES}"},
            )
        if isinstance(wire, dict) and wire.get("c") not in WIRE_CODECS:
            return reply(
                "error",
                meta={"message": f"unsupported wire codec {wire.get('c')!r}; "
                      f"supported: {WIRE_CODECS}"},
            )
        if wire is not None and not isinstance(wire, (str, dict)):
            return reply(
                "error",
                meta={"message": "malformed wire meta: expected a dtype "
                      "string or a codec map"},
            )
        data_plane = msg_type in ("forward", "backward", "multi")
        if data_plane:
            self._count_wire_bytes(wire, len(payload), "rx")
        try:
            with timeline.span(f"server.request.{msg_type}", trace=trace):
                if msg_type == "forward":
                    out = wire_reply(
                        await self._run_forward(uid, tensors, wire, trace)
                    )
                    self._count_wire_bytes(wire, frame_nbytes(out), "tx")
                    return out
                elif msg_type == "backward":
                    out = wire_reply(
                        await self._run_backward(
                            uid, tensors, meta.get("n_inputs"), wire, trace
                        )
                    )
                    self._count_wire_bytes(wire, frame_nbytes(out), "tx")
                    return out
                elif msg_type == "multi":
                    out = await self._run_multi(tensors, meta, rid, trace)
                    self._count_wire_bytes(wire, frame_nbytes(out), "tx")
                    return out
                elif msg_type == "info":
                    backend = self.server.experts.get(uid)
                    if backend is None:
                        raise ValueError(f"unknown expert uid: {uid!r}")
                    return reply("result", meta=backend.get_info())
                elif msg_type == "replica":
                    # rebalancer control plane (ISSUE 8): host a replica
                    # of ``uid`` here.  The request carries ONLY the uid
                    # (+ the sync flag) — checkpoint location is this
                    # server's own configuration, never peer-supplied.
                    if not isinstance(uid, str) or not uid:
                        raise ValueError("replica request needs a uid")
                    installed = await self.server.add_replica_async(
                        uid, sync=bool(meta.get("sync"))
                    )
                    return reply(
                        "result",
                        meta={
                            "uid": uid,
                            "installed": bool(installed),
                            "hosted": uid in self.server.experts,
                        },
                    )
                elif msg_type == "handoff":
                    # live expert migration (ISSUE 9): a draining peer
                    # streams one expert's params+opt state here in
                    # sequential parts; the receiver installs and
                    # declares the uid only after a bitwise-verified
                    # install.  Always the RAW wire — a quantized
                    # payload cannot be bitwise by construction.
                    if wire is not None:
                        raise ValueError(
                            "handoff must travel the raw wire (no wire "
                            "meta): migration is bitwise or it failed"
                        )
                    return reply(
                        "result",
                        meta=await self.server.handoff.handle_part(
                            meta, tensors
                        ),
                    )
                elif msg_type == "migrate":
                    # placement actuation (ISSUE 16): move ONE hosted
                    # expert to an explicit target over the handoff
                    # wire, on the lah-migrate thread — handoff first,
                    # retire only after the bitwise-verified install
                    # (run_drain's per-uid order), so the uid's hoster
                    # count never dips mid-move.  Reply is immediate;
                    # callers watch the stats RPC's placement section.
                    if not isinstance(uid, str) or not uid:
                        raise ValueError("migrate request needs a uid")
                    target = meta["target"]
                    if not (
                        isinstance(target, (list, tuple))
                        and len(target) == 2
                        and isinstance(target[0], str)
                        and isinstance(target[1], int)
                    ):
                        raise ValueError(
                            "migrate target must be [host, port]"
                        )
                    kwargs = {}
                    timeout_s = meta.get("timeout")
                    if timeout_s is not None:
                        kwargs["timeout"] = min(
                            600.0, max(1.0, float(timeout_s))
                        )
                    started = self.server.start_migration(
                        uid, (target[0], target[1]), **kwargs
                    )
                    return reply(
                        "result",
                        meta={
                            "uid": uid,
                            "started": bool(started),
                            "state": self.server.lifecycle_state,
                        },
                    )
                elif msg_type == "drain":
                    # graceful-drain trigger (ISSUE 9): flip the server
                    # into the drain sequence on its lah-drain thread
                    # and reply immediately — callers watch the stats
                    # RPC's lifecycle section (or process exit)
                    kwargs = {}
                    successor = meta.get("successor")
                    if successor is not None:
                        if not (
                            isinstance(successor, (list, tuple))
                            and len(successor) == 2
                            and isinstance(successor[0], str)
                            and isinstance(successor[1], int)
                        ):
                            raise ValueError(
                                "drain successor must be [host, port]"
                            )
                        kwargs["successor"] = (successor[0], successor[1])
                    grace = meta.get("grace")
                    if grace is not None:
                        kwargs["grace"] = float(grace)
                    if meta.get("handoff") is not None:
                        kwargs["handoff"] = bool(meta.get("handoff"))
                    started = self.server.start_drain(**kwargs)
                    return reply(
                        "result",
                        meta={
                            "draining": True,
                            "started": bool(started),
                            "state": self.server.lifecycle_state,
                        },
                    )
                elif msg_type == "stats":
                    return reply(
                        "result",
                        meta=self._server_stats(
                            include_spans=bool(meta.get("spans"))
                        ),
                    )
                else:
                    return reply(
                        "error",
                        meta={"message": f"unknown message type {msg_type!r}"},
                    )
        except Exception as e:
            logger.exception("request %s failed (expert %s)", msg_type, uid)
            return reply("error", meta={"message": f"{type(e).__name__}: {e}"})
