"""Server-side RPC dispatch: forward / backward / info over framed TCP.

Contract from the reference's ``hivemind/server/connection_handler.py``
(SURVEY.md §2; unverifiable refs, mount empty): accept connections, parse
message type, deserialize tensors, submit to the right expert's pool, await
the future, reply.  Reference runs one-or-more *processes*; here it is pure
asyncio on the server's event loop — each connection is a coroutine, and
the expensive work (XLA execution) happens on the Runtime thread anyway.

Wire protocol (see utils/serialization.py for framing):

- ``forward``:  meta {uid}, tensors [*inputs]            → ``result`` [*outputs]
- ``backward``: meta {uid, n_inputs}, tensors [*inputs, *grad_outputs]
                                                          → ``result`` [*input_grads]
- ``info``:     meta {uid}                                → ``result`` meta=info
- errors                                                  → ``error`` meta {message}
"""

from __future__ import annotations

import asyncio
import logging
from typing import TYPE_CHECKING

from learning_at_home_tpu.utils.serialization import (
    pack_message,
    recv_frame,
    send_frame,
    unpack_message,
)

if TYPE_CHECKING:
    from learning_at_home_tpu.server.server import Server

logger = logging.getLogger(__name__)


class ConnectionHandler:
    """Dispatches one TCP connection's requests to expert task pools."""

    def __init__(self, server: "Server"):
        self.server = server

    async def handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        try:
            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                reply = await self._dispatch(payload)
                if self.server.chaos is not None:
                    if not await self.server.chaos.before_reply():
                        continue  # injected drop: client sees a timeout
                await send_frame(writer, reply)
        except Exception:
            logger.exception("connection handler failed for peer %s", peer)
        finally:
            writer.close()

    async def _dispatch(self, payload: bytes) -> bytes:
        try:
            msg_type, tensors, meta = unpack_message(payload)
        except Exception as e:
            return pack_message("error", meta={"message": f"malformed request: {e}"})
        uid = meta.get("uid")
        backend = self.server.experts.get(uid)
        if backend is None:
            return pack_message(
                "error", meta={"message": f"unknown expert uid: {uid!r}"}
            )
        try:
            if msg_type == "forward":
                if len(tensors) != backend.n_inputs:
                    # reject HERE: a wrong-arity task reaching the pool would
                    # poison the whole formed batch (innocent co-batched
                    # requests fail with it)
                    raise ValueError(
                        f"expert {uid} takes {backend.n_inputs} inputs, "
                        f"got {len(tensors)}"
                    )
                outputs = await self.server.forward_pools[uid].submit_task(*tensors)
                return pack_message("result", outputs)
            elif msg_type == "backward":
                n_inputs = int(meta.get("n_inputs", backend.n_inputs))
                if n_inputs != backend.n_inputs:
                    raise ValueError(
                        f"expert {uid} takes {backend.n_inputs} inputs, "
                        f"request declared {n_inputs}"
                    )
                # mirror the forward guard: a backward request carries the
                # inputs PLUS the grad_outputs; wrong arity in EITHER
                # direction must be rejected before it can poison a formed
                # batch (exact check once n_outputs is known, i.e. after
                # warmup or the first forward)
                expected = (
                    backend.n_inputs + backend.n_outputs
                    if backend.n_outputs is not None
                    else None
                )
                if (expected is not None and len(tensors) != expected) or (
                    len(tensors) <= backend.n_inputs
                ):
                    raise ValueError(
                        f"backward for {uid} needs "
                        f"{expected or f'>{backend.n_inputs}'} tensors "
                        f"(inputs + grad_outputs), got {len(tensors)}"
                    )
                outputs = await self.server.backward_pools[uid].submit_task(*tensors)
                return pack_message("result", outputs)
            elif msg_type == "info":
                return pack_message("result", meta=backend.get_info())
            else:
                return pack_message(
                    "error", meta={"message": f"unknown message type {msg_type!r}"}
                )
        except Exception as e:
            logger.exception("request %s failed for expert %s", msg_type, uid)
            return pack_message("error", meta={"message": f"{type(e).__name__}: {e}"})
