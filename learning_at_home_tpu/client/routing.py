"""Expert discovery sources + top-k selection for the DMoE client.

The reference client finds alive experts via DHT prefix beam search
(``first_k_active``-style, ``hivemind/client/moe.py`` — SURVEY.md §2;
unverifiable refs, mount empty).  This module defines the *source*
interface both the DHT (M2) and a static in-process table implement, plus
the batched per-sample top-k scoring used by RemoteMixtureOfExperts.

Expert UIDs are grid-structured: ``{prefix}.{i1}.{i2}...{in}`` for an
n-dimensional grid (e.g. ``ffn.4.17``), matching the reference's
multi-dimensional gating.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Callable, Iterable, Optional, Protocol, Sequence

import numpy as np

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.connection import Endpoint

logger = logging.getLogger(__name__)

# Clock seam: alive-set and load/link feed TTL stamps read time through
# here so sim/clock.py can virtualize them (docs/SIMULATION.md).
_monotonic = time.monotonic

UID_DELIMITER = "."

# A replica set: every endpoint currently hosting one expert uid, in a
# deterministic order.  Alive-map values are EITHER a bare (host, port)
# endpoint (single-hoster uid — the historical form every existing
# consumer understands) OR a tuple of endpoints once an expert gained
# DHT-advertised replicas; ``as_replica_set`` normalizes both.
ReplicaSet = tuple[Endpoint, ...]


def as_replica_set(value) -> ReplicaSet:
    """Normalize an alive-map value to a tuple of endpoints.

    ``("10.0.0.1", 9000)`` → a 1-tuple; an iterable of endpoints passes
    through deduplicated with order preserved (the resolver's order is
    deterministic, so two clients see the same replica list).  Malformed
    entries inside a set are dropped rather than raised — alive maps are
    peer-supplied."""
    if (
        isinstance(value, (tuple, list))
        and len(value) == 2
        and isinstance(value[0], str)
        and not isinstance(value[1], (tuple, list))
    ):
        return ((value[0], int(value[1])),)
    out: list[Endpoint] = []
    seen = set()
    for ep in value:
        try:
            ep = (ep[0], int(ep[1]))
        except (TypeError, ValueError, IndexError):
            continue
        if not isinstance(ep[0], str) or ep in seen:
            continue
        seen.add(ep)
        out.append(ep)
    return tuple(out)


def endpoint_key(endpoint: Endpoint) -> str:
    """The ``host:port`` string form used as DHT subkey for per-endpoint
    records (replica advertisement, load heartbeats)."""
    return f"{endpoint[0]}:{endpoint[1]}"


def make_uid(prefix: str, coords: Sequence[int]) -> str:
    return UID_DELIMITER.join([prefix, *map(str, coords)])


def split_uid(uid: str, n_dims: Optional[int] = None) -> tuple[str, tuple[int, ...]]:
    """Split a grid uid into (prefix, coords).

    With ``n_dims`` given, exactly the last n_dims components are coords —
    required when the prefix itself may contain numeric segments (e.g.
    ``block.3.1.2`` with prefix ``block.3``).  Without it, all trailing
    numeric components are treated as coords (greedy; fine for display).
    """
    parts = uid.split(UID_DELIMITER)
    if n_dims is not None:
        if len(parts) <= n_dims or not all(p.isdigit() for p in parts[-n_dims:]):
            raise ValueError(f"uid {uid!r} does not end in {n_dims} grid coords")
        coords = tuple(int(p) for p in parts[-n_dims:])
        return UID_DELIMITER.join(parts[:-n_dims]), coords
    coords_rev = []
    while parts and parts[-1].isdigit():
        coords_rev.append(int(parts.pop()))
    return UID_DELIMITER.join(parts), tuple(reversed(coords_rev))


def filter_valid_uids(
    uids: Iterable[str], prefix: str, grid_size: Sequence[int]
) -> list[str]:
    """Keep only uids of the exact form prefix.c1...cn with coords in-grid.

    DHT alive-sets are peer-supplied; a malformed or out-of-range uid must
    not crash routing (IndexError in score_experts) or skew selection."""
    out = []
    n_dims = len(grid_size)
    for uid in uids:
        try:
            p, coords = split_uid(uid, n_dims)
        except ValueError:
            continue
        if p == prefix and all(0 <= c < g for c, g in zip(coords, grid_size)):
            out.append(uid)
    return out


class ExpertSource(Protocol):
    """Anything that can enumerate alive experts and resolve endpoints."""

    async def get_alive_experts(
        self, prefix: str
    ) -> dict[str, Endpoint]:  # uid -> endpoint
        ...

    async def first_k_active(
        self, prefixes: Sequence[str], k: int
    ) -> dict[str, bool]:
        """Which of the given uid prefixes have ≥1 alive expert (beam search)."""
        ...


class StaticExpertSource:
    """Fixed uid→endpoint table (single-host tests, no DHT; [BJ] config 2)."""

    def __init__(self, experts: dict[str, Endpoint]):
        self.experts = dict(experts)

    @staticmethod
    def _matches(uid: str, prefix: str) -> bool:
        # full-component match: prefix "ffn" owns "ffn.3" but not "ffn2.3"
        return uid == prefix or uid.startswith(prefix + UID_DELIMITER)

    async def get_alive_experts(self, prefix: str) -> dict[str, Endpoint]:
        return {
            uid: ep for uid, ep in self.experts.items() if self._matches(uid, prefix)
        }

    async def first_k_active(self, prefixes, k) -> dict[str, bool]:
        out = {}
        for p in prefixes:
            out[p] = any(self._matches(uid, p) for uid in self.experts)
        return out


def _top_union(scores: np.ndarray, width: int) -> np.ndarray:
    """Union over the batch of each sample's top-``width`` column indices."""
    width = min(width, scores.shape[1])
    return np.unique(np.argpartition(-scores, width - 1, axis=1)[:, :width])


async def beam_search_alive(
    source: "ExpertSource",
    uid_prefix: str,
    logits_per_dim: Sequence[np.ndarray],
    grid_size: Sequence[int],
    beam_size: int,
) -> dict[str, Endpoint]:
    """Find alive experts for a batch WITHOUT fetching the whole grid.

    True per-dimension prefix walk (the reference's ``first_k_active``
    contract, SURVEY.md §3.1): starting from each sample's top
    ``beam_size`` first-dimension indices, at every intermediate level ask
    the DHT which candidate prefixes are active (one batched
    ``first_k_active``), keep only active ones, extend them with the next
    dimension's per-sample top indices, and prune the union to
    ``4·beam_size`` by best-over-batch score.  Only the deepest prefix
    level (leaf rows, which hold at most ``grid_size[-1]`` subkey records
    each) fetches endpoint records.  Total DHT reads are therefore
    O(beam · dims) — independent of grid volume, unlike enumerating a
    4096-expert top-level record.

    If an entire level's candidates turn out dead, that level is retried
    ONCE with all extensions of the surviving parent beam (capped at the
    same ``4·beam_size`` budget) — a dead row diverts the walk instead of
    ending it, while the fetch bound stays O(beam · dims).  Beyond that
    cap the search is best-effort, exactly like the reference's bounded
    ``first_k_active`` scan.

    Returns uid → endpoint for the candidate set (callers re-score exactly).
    """
    n_dims = len(grid_size)
    width = beam_size
    union_cap = max(4 * beam_size, 8)

    def prefixes_of(coords_list: list[tuple[int, ...]]) -> list[str]:
        return [make_uid(uid_prefix, c) for c in coords_list]

    def prune(coords_list: list[tuple[int, ...]]) -> list[tuple[int, ...]]:
        if len(coords_list) <= union_cap:
            return coords_list
        best = score_experts(
            logits_per_dim, np.asarray(coords_list, dtype=np.int64)
        ).max(axis=0)
        keep = np.argsort(-best)[:union_cap]
        return [coords_list[i] for i in keep]

    def all_extensions(
        parent_beam: list[tuple[int, ...]], dim: int
    ) -> list[tuple[int, ...]]:
        """Every child of the parent beam along ``dim`` (root → whole dim 0)."""
        if not parent_beam:
            return [(i,) for i in range(grid_size[0])]
        return [p + (i,) for p in parent_beam for i in range(grid_size[dim])]

    def extend_top(
        beam: list[tuple[int, ...]], dim: int
    ) -> list[tuple[int, ...]]:
        """Union over the batch of per-sample top (prefix, next-index) pairs."""
        prev = np.asarray(beam, dtype=np.int64)  # [A, dim]
        base = score_experts(logits_per_dim, prev)  # [B, A]
        ext = base[:, :, None] + logits_per_dim[dim][:, None, :]  # [B, A, g]
        g = ext.shape[2]
        flat_idx = _top_union(ext.reshape(ext.shape[0], -1), width)
        return [tuple(prev[i // g]) + (int(i % g),) for i in flat_idx]

    async def active_subset(cands):
        prefixes = prefixes_of(cands)
        active = await source.first_k_active(prefixes, beam_size)
        return [c for c, p in zip(cands, prefixes) if active[p]]

    # depth-1 candidates: union over batch of per-sample top dim-0 indices
    cand = [(int(i),) for i in _top_union(logits_per_dim[0], width)]
    parent_beam: list[tuple[int, ...]] = []  # beam one level above cand

    # walk until cand are leaf-row prefixes (depth n_dims-1); every
    # intermediate level is pruned by an activity check first
    for depth in range(1, n_dims - 1):
        cand = prune(cand)
        alive_coords = await active_subset(cand)
        if not alive_coords:
            # the whole level looked dead: one capped retry over every
            # extension of the parent beam not already checked
            seen = set(cand)
            retry = prune(
                [c for c in all_extensions(parent_beam, depth - 1)
                 if c not in seen]
            )
            if retry:
                alive_coords = await active_subset(retry)
        if not alive_coords:
            return {}
        parent_beam = alive_coords
        cand = extend_top(alive_coords, depth)

    # cand are now leaf-row prefixes (each record holds ≤ grid_size[-1]
    # subkeys; for 1-D grids they are the full uids themselves —
    # DHT.get_alive_experts handles both)
    async def fetch(cands) -> dict[str, Endpoint]:
        records = await asyncio.gather(
            *(source.get_alive_experts(p) for p in prefixes_of(cands))
        )
        merged: dict[str, Endpoint] = {}
        for rec in records:
            merged.update(rec)
        return merged

    cand = prune(cand)
    alive = await fetch(cand)
    if not alive:
        # same one-shot capped reroute at the leaf level
        seen = set(cand)
        retry = prune(
            [c for c in all_extensions(parent_beam, n_dims - 2 if n_dims > 1 else 0)
             if c not in seen]
        )
        if retry:
            alive = await fetch(retry)
    valid = set(filter_valid_uids(alive, uid_prefix, grid_size))
    return {uid: ep for uid, ep in alive.items() if uid in valid}


class CachedAliveSet:
    """TTL cache over get_alive_experts — one discovery per window, not per
    batch (keeps routing off the dispatch hot path).

    ``swr`` (stale-while-revalidate, ISSUE 9): when the window expires,
    :meth:`get` serves the STALE set immediately and refreshes in a
    background loop task instead of blocking the dispatch on the
    discovery lookup.  Under churn a DHT lookup can stall behind
    dead-but-not-yet-evicted peers — with swr that cost never lands on
    the dispatch path, and the one-window staleness it trades for is
    exactly what the hedge/retry machinery already covers.  ON by
    default since ISSUE 11 (refreshes are cheap now: record cache +
    adaptive sub-second RPC timeouts); ``LAH_ALIVE_SWR=0`` or
    ``swr=False`` restores the blocking refresh — tests and chaos
    scenarios that reason about WHEN a kill becomes visible pin it.

    A ``force_refresh`` get always blocks on a fresh lookup, and asks a
    DHT-backed source to bypass its record cache too
    (``get_alive_experts_fresh``) — the authoritative read the dispatch
    retry path uses when a sole endpoint hard-fails."""

    def __init__(
        self,
        source: ExpertSource,
        prefix: str,
        ttl: float = 3.0,
        swr: Optional[bool] = None,
    ):
        self.source = source
        self.prefix = prefix
        self.ttl = ttl
        if swr is None:
            swr = os.environ.get("LAH_ALIVE_SWR", "1") != "0"
        self.swr = bool(swr)
        self._cached: Optional[dict[str, Endpoint]] = None
        self._stamp = 0.0
        self._refreshing: Optional[Any] = None  # in-flight background task
        self.stale_serves = 0
        self.refresh_failures = 0

    async def _fetch(self, fresh: bool = False) -> dict[str, Endpoint]:
        if fresh:
            fetch_fresh = getattr(self.source, "get_alive_experts_fresh", None)
            if fetch_fresh is not None:
                return await fetch_fresh(self.prefix)
        return await self.source.get_alive_experts(self.prefix)

    async def get(self, force_refresh: bool = False) -> dict[str, Endpoint]:
        now = _monotonic()
        stale = self._cached is None or now - self._stamp > self.ttl
        if not (force_refresh or stale):
            return self._cached
        if not self.swr or self._cached is None or force_refresh:
            # blocking refresh: first discovery (nothing to serve stale),
            # an explicit force, or swr disabled (the historical path).
            # Cancel any in-flight background refresh first: it started
            # EARLIER, so letting it complete after this authoritative
            # read could overwrite a fresher set with a staler one
            # (e.g. resurrecting a just-killed endpoint for a full TTL)
            if self._refreshing is not None and not self._refreshing.done():
                self._refreshing.cancel()
            self._refreshing = None
            self._cached = await self._fetch(fresh=force_refresh)
            self._stamp = _monotonic()
            return self._cached
        # stale-while-revalidate: hand back the stale set NOW; at most
        # one background refresh in flight (loop-confined state — this
        # coroutine and the task both run on the owning loop)
        if self._refreshing is None or self._refreshing.done():
            self._refreshing = asyncio.get_running_loop().create_task(
                self._refresh_bg(), name=f"alive-refresh-{self.prefix}"
            )
        self.stale_serves += 1
        return self._cached

    async def _refresh_bg(self) -> None:
        try:
            alive = await self._fetch()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # a failed background refresh keeps the stale set: routing
            # degrades gracefully, exactly like the load-feed reads
            self.refresh_failures += 1
            logger.debug("alive-set refresh for %s failed: %s: %s",
                         self.prefix, type(e).__name__, e)
            return
        self._cached = alive
        self._stamp = _monotonic()

    def peek_fresh(self) -> Optional[dict[str, Endpoint]]:
        """The cached alive set if still within TTL, else None — a pure
        sync read with no loop round-trip, so the fire half of a
        future-based dispatch only touches the client loop at all on the
        one-per-TTL-window refresh (a bounded control-plane lookup)."""
        if (
            self._cached is not None
            and _monotonic() - self._stamp <= self.ttl
        ):
            return self._cached
        return None


def score_experts(
    logits_per_dim: Sequence[np.ndarray], coords: np.ndarray
) -> np.ndarray:
    """Batched grid scores: sum of per-dimension gate logits.

    logits_per_dim: list over dims d of [batch, grid_d] arrays.
    coords: [n_experts, n_dims] integer grid coordinates.
    Returns [batch, n_experts].
    """
    scores = logits_per_dim[0][:, coords[:, 0]]
    for d in range(1, coords.shape[1]):
        scores = scores + logits_per_dim[d][:, coords[:, d]]
    return scores


def select_top_k(
    logits_per_dim: Sequence[np.ndarray],
    alive_uids: Sequence[str],
    k: int,
    bias: Optional[np.ndarray] = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-sample top-k over all alive experts (full enumeration).

    Exact and vectorized; fine up to ~10^4 alive experts per batch.  The
    DHT-backed beam search (M2/M4) replaces enumeration when the grid is
    large but only a fraction is alive or local.
    Returns (sel [batch, k] indices into alive_uids, coords [n, n_dims]).

    ``bias`` [len(alive_uids)] (optional): per-expert additive score
    adjustment applied to SELECTION only — the caller's combine weights
    still come from the clean gate scores (same selection-vs-weights
    split as router jitter).  Used for latency-aware routing.
    """
    n_dims = len(logits_per_dim)
    coords = np.asarray(
        [split_uid(uid, n_dims)[1] for uid in alive_uids], dtype=np.int64
    )
    scores = score_experts(logits_per_dim, coords)  # [B, E]
    if bias is not None:
        scores = scores + np.asarray(bias, scores.dtype)[None, :]
    n = scores.shape[1]
    k_eff = min(k, n)
    # argpartition then sort the head: O(E + k log k) per sample
    part = np.argpartition(-scores, k_eff - 1, axis=1)[:, :k_eff]
    order = np.take_along_axis(scores, part, axis=1).argsort(axis=1)[:, ::-1]
    sel = np.take_along_axis(part, order, axis=1)
    return sel, coords


# --------------------------------------------------------------------------
# latency-aware routing (ISSUE 8): predicted-completion-time cost model
# --------------------------------------------------------------------------

# Default selection-bias strength when latency-aware routing is enabled
# without an explicit weight (gate logits are O(1), so 5.0 makes a 100 ms
# predicted cost worth 0.5 logits — enough to flip near-ties, never enough
# to override a decisive gate preference).
DEFAULT_COST_WEIGHT = 5.0


class RoutingCostModel:
    """Scores alive experts by PREDICTED COMPLETION TIME and turns the
    prediction into a ``select_top_k(bias=...)`` penalty (cf. TA-MoE's
    topology-aware dispatch and MoETuner's placement-aware routing).

    Per endpoint, predicted cost (seconds) =

    - the pool's whole-exchange **RTT EMA** (network + peer queueing +
      compute — ``ConnectionPool.rtt_ema``), plus
    - **queue-depth cost**: the peer's DHT-advertised runtime queue depth
      (``load.<prefix>`` heartbeats, utils/telemetry.py) ×
      ``queue_cost_s`` per queued batch, plus
    - **estimated transfer time** of this dispatch's payload at the
      negotiated codec: encoded bytes / the pool's measured bytes-per-sec
      EMA (``bw_ema``; pools without a large-exchange measurement pay no
      transfer term rather than a guessed one).

    A uid's cost is the MINIMUM over its replica set (the dispatch will
    pick that cheapest replica), and endpoints with no signal at all cost
    0.0 — unmeasured peers stay attractive (exploration), exactly the old
    ``latency_weight`` semantics, so ``weight == latency_weight`` with no
    load feed and no bw measurement reproduces the historical bias
    bitwise.  ``weight == 0`` returns ``bias=None``: selection is then
    bitwise identical to the blind gate (the A/B contract).

    Placement/routing co-optimization (ISSUE 16): an optional
    ``link_getter`` feeds the swarm's published ``links.<prefix>`` RTT/
    bandwidth EMAs (utils/telemetry.py) in as a PRIOR for endpoints this
    process has never dialed — the same link-cost data the placement
    solver scores assignments on, so token routing and expert placement
    move on one view instead of fighting.  A local pool measurement
    always wins over the prior; with no getter the model is bitwise the
    pre-ISSUE-16 one.

    All lookups are plain dict/attribute reads on the calling host
    thread; the only I/O is the TTL-gated ``load_getter``/``link_getter``
    refresh (a bounded control-plane DHT read, mirroring the alive-set
    cache).
    """

    def __init__(
        self,
        weight: float = 0.0,
        *,
        registry=None,
        load_getter: Optional[Callable[[], dict]] = None,
        load_ttl: float = 3.0,
        queue_cost_s: Optional[float] = None,
        codec_ratio: float = 1.0,
        link_getter: Optional[Callable[[], dict]] = None,
        link_ttl: float = 10.0,
    ):
        self.weight = float(weight)
        self._registry = registry
        self._load_getter = load_getter
        self.load_ttl = load_ttl
        if queue_cost_s is None:
            try:
                queue_cost_s = float(
                    os.environ.get("LAH_ROUTING_QUEUE_COST_S", "0.005")
                )
            except ValueError:
                queue_cost_s = 0.005
        self.queue_cost_s = queue_cost_s
        # wire-bytes multiplier of the codec the dispatch will negotiate
        # (0.25 for the 8-bit codecs, 0.5 for bf16, 1.0 raw)
        self.codec_ratio = codec_ratio
        self._loads: dict = {}
        self._loads_stamp = 0.0
        self._link_getter = link_getter
        self.link_ttl = link_ttl
        self._links: dict = {}
        self._links_stamp = 0.0
        # observability: how many bias computations actually had signal
        self.bias_applied = 0
        self.load_refresh_failures = 0
        # co-optimization observability: predictions that fell back to a
        # swarm-published link prior (no local pool measurement yet)
        self.link_fallbacks = 0
        self.link_refresh_failures = 0

    def _pools(self):
        if self._registry is not None:
            return self._registry
        from learning_at_home_tpu.client.rpc import pool_registry

        return pool_registry()

    def loads(self) -> dict:
        """endpoint-key ("host:port") → load record, TTL-refreshed via
        the getter (best-effort: a failed refresh keeps the stale map for
        one window and counts the failure)."""
        if self._load_getter is None:
            return self._loads
        now = _monotonic()
        if now - self._loads_stamp > self.load_ttl:
            self._loads_stamp = now  # stamp first: one refresh per window
            try:
                loads = self._load_getter()
                self._loads = loads if isinstance(loads, dict) else {}
            except Exception as e:
                self.load_refresh_failures += 1
                logger.debug("routing load refresh failed: %s: %s",
                             type(e).__name__, e)
        return self._loads

    def queue_depth(self, endpoint: Endpoint) -> Optional[float]:
        rec = self.loads().get(endpoint_key(endpoint))
        if isinstance(rec, dict):
            try:
                return float(rec.get("q"))
            except (TypeError, ValueError):
                return None
        return None

    def links(self) -> dict:
        """endpoint-key ("host:port") → ``{"rtt_s", "bw_bps"}`` from the
        swarm's published link records, TTL-refreshed like ``loads()``
        (stamp-first; a failed refresh keeps the stale map one window)."""
        if self._link_getter is None:
            return self._links
        now = _monotonic()
        if now - self._links_stamp > self.link_ttl:
            self._links_stamp = now
            try:
                links = self._link_getter()
                self._links = links if isinstance(links, dict) else {}
            except Exception as e:
                self.link_refresh_failures += 1
                logger.debug("routing link refresh failed: %s: %s",
                             type(e).__name__, e)
        return self._links

    def predicted_cost_s(
        self, endpoint: Endpoint, nbytes: int = 0
    ) -> Optional[float]:
        """Predicted completion time for one dispatch to ``endpoint``;
        None when there is NO signal (never contacted, no load record,
        no published link) — the caller treats that as cost 0
        (optimistic exploration)."""
        pool = self._pools().peek(endpoint)
        rtt = pool.rtt_ema if pool is not None else None
        bw = pool.bw_ema if pool is not None else None
        if rtt is None:
            # swarm link prior (ISSUE 16): other peers' measurements of
            # this endpoint, until the first local exchange lands
            link = self.links().get(endpoint_key(endpoint))
            if isinstance(link, dict):
                try:
                    rtt = float(link.get("rtt_s"))
                except (TypeError, ValueError):
                    rtt = None
                if rtt is not None:
                    self.link_fallbacks += 1
                    if bw is None:
                        lbw = link.get("bw_bps")
                        bw = float(lbw) if isinstance(
                            lbw, (int, float)
                        ) and lbw > 0 else None
        q = self.queue_depth(endpoint)
        transfer = None
        if nbytes > 0 and bw is not None and bw > 0:
            transfer = (nbytes * self.codec_ratio) / bw
        if rtt is None and q is None and transfer is None:
            return None
        return (
            (rtt or 0.0)
            + (q or 0.0) * self.queue_cost_s
            + (transfer or 0.0)
        )

    def order_replicas(
        self, replicas: ReplicaSet, nbytes: int = 0
    ) -> ReplicaSet:
        """Replica set sorted cheapest-first (the least-loaded pick; the
        second entry is the hedge backup).  Unmeasured replicas cost 0 —
        an unknown peer outranks a known-slow one — and exact ties break
        on the endpoint itself, so the order is deterministic."""
        if len(replicas) <= 1:
            return replicas
        return tuple(
            sorted(
                replicas,
                key=lambda ep: (self.predicted_cost_s(ep, nbytes) or 0.0, ep),
            )
        )

    @sanitizer.runs_on("host", site="routing.cost_bias")
    def bias(
        self,
        alive_uids: Sequence[str],
        replica_sets: dict,
        nbytes: int = 0,
    ) -> Optional[np.ndarray]:
        """The ``select_top_k`` bias vector: ``-weight × min-over-replica
        predicted cost`` per uid.  None when the weight is 0 (bias=None →
        selection bitwise identical to today's blind gate) or when no
        endpoint has any signal yet."""
        if not self.weight:
            return None
        bias = np.zeros(len(alive_uids), np.float32)
        any_signal = False
        for j, uid in enumerate(alive_uids):
            best = None
            for ep in replica_sets[uid]:
                cost = self.predicted_cost_s(ep, nbytes)
                if cost is not None and (best is None or cost < best):
                    best = cost
            if best is not None:
                bias[j] = -self.weight * best
                any_signal = True
        if not any_signal:
            return None
        self.bias_applied += 1
        return bias
