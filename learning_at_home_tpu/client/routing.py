"""Expert discovery sources + top-k selection for the DMoE client.

The reference client finds alive experts via DHT prefix beam search
(``first_k_active``-style, ``hivemind/client/moe.py`` — SURVEY.md §2;
unverifiable refs, mount empty).  This module defines the *source*
interface both the DHT (M2) and a static in-process table implement, plus
the batched per-sample top-k scoring used by RemoteMixtureOfExperts.

Expert UIDs are grid-structured: ``{prefix}.{i1}.{i2}...{in}`` for an
n-dimensional grid (e.g. ``ffn.4.17``), matching the reference's
multi-dimensional gating.
"""

from __future__ import annotations

import asyncio
import time
from typing import Iterable, Optional, Protocol, Sequence

import numpy as np

from learning_at_home_tpu.utils.connection import Endpoint

UID_DELIMITER = "."


def make_uid(prefix: str, coords: Sequence[int]) -> str:
    return UID_DELIMITER.join([prefix, *map(str, coords)])


def split_uid(uid: str, n_dims: Optional[int] = None) -> tuple[str, tuple[int, ...]]:
    """Split a grid uid into (prefix, coords).

    With ``n_dims`` given, exactly the last n_dims components are coords —
    required when the prefix itself may contain numeric segments (e.g.
    ``block.3.1.2`` with prefix ``block.3``).  Without it, all trailing
    numeric components are treated as coords (greedy; fine for display).
    """
    parts = uid.split(UID_DELIMITER)
    if n_dims is not None:
        if len(parts) <= n_dims or not all(p.isdigit() for p in parts[-n_dims:]):
            raise ValueError(f"uid {uid!r} does not end in {n_dims} grid coords")
        coords = tuple(int(p) for p in parts[-n_dims:])
        return UID_DELIMITER.join(parts[:-n_dims]), coords
    coords_rev = []
    while parts and parts[-1].isdigit():
        coords_rev.append(int(parts.pop()))
    return UID_DELIMITER.join(parts), tuple(reversed(coords_rev))


def filter_valid_uids(
    uids: Iterable[str], prefix: str, grid_size: Sequence[int]
) -> list[str]:
    """Keep only uids of the exact form prefix.c1...cn with coords in-grid.

    DHT alive-sets are peer-supplied; a malformed or out-of-range uid must
    not crash routing (IndexError in score_experts) or skew selection."""
    out = []
    n_dims = len(grid_size)
    for uid in uids:
        try:
            p, coords = split_uid(uid, n_dims)
        except ValueError:
            continue
        if p == prefix and all(0 <= c < g for c, g in zip(coords, grid_size)):
            out.append(uid)
    return out


class ExpertSource(Protocol):
    """Anything that can enumerate alive experts and resolve endpoints."""

    async def get_alive_experts(
        self, prefix: str
    ) -> dict[str, Endpoint]:  # uid -> endpoint
        ...

    async def first_k_active(
        self, prefixes: Sequence[str], k: int
    ) -> dict[str, bool]:
        """Which of the given uid prefixes have ≥1 alive expert (beam search)."""
        ...


class StaticExpertSource:
    """Fixed uid→endpoint table (single-host tests, no DHT; [BJ] config 2)."""

    def __init__(self, experts: dict[str, Endpoint]):
        self.experts = dict(experts)

    @staticmethod
    def _matches(uid: str, prefix: str) -> bool:
        # full-component match: prefix "ffn" owns "ffn.3" but not "ffn2.3"
        return uid == prefix or uid.startswith(prefix + UID_DELIMITER)

    async def get_alive_experts(self, prefix: str) -> dict[str, Endpoint]:
        return {
            uid: ep for uid, ep in self.experts.items() if self._matches(uid, prefix)
        }

    async def first_k_active(self, prefixes, k) -> dict[str, bool]:
        out = {}
        for p in prefixes:
            out[p] = any(self._matches(uid, p) for uid in self.experts)
        return out


async def beam_search_alive(
    source: "ExpertSource",
    uid_prefix: str,
    logits_per_dim: Sequence[np.ndarray],
    grid_size: Sequence[int],
    beam_size: int,
) -> dict[str, Endpoint]:
    """Find alive experts for a batch WITHOUT fetching the whole grid.

    The reference walks DHT prefixes dimension-by-dimension per sample
    (``first_k_active`` beam search).  Our record layout stores every alive
    full uid under each prefix level, so one pruning step suffices: take
    each sample's top ``beam_size`` first-dimension indices (union over the
    batch), fetch those ``prefix.i`` records in parallel, and return the
    union of alive experts found — a handful of small record fetches
    instead of one giant top-level record for a 4096-expert grid.

    Returns uid → endpoint for the candidate set (callers re-score exactly).
    """
    dim0 = logits_per_dim[0]  # [batch, grid_0]
    width = min(beam_size, dim0.shape[1])
    per_sample = np.argpartition(-dim0, width - 1, axis=1)[:, :width]
    needed = np.unique(per_sample)
    prefixes = [f"{uid_prefix}{UID_DELIMITER}{int(i)}" for i in needed]
    records = await asyncio.gather(
        *(source.get_alive_experts(p) for p in prefixes)
    )
    alive: dict[str, Endpoint] = {}
    for rec in records:
        alive.update(rec)
    valid = set(filter_valid_uids(alive, uid_prefix, grid_size))
    return {uid: ep for uid, ep in alive.items() if uid in valid}


class CachedAliveSet:
    """TTL cache over get_alive_experts — one discovery per window, not per
    batch (keeps routing off the dispatch hot path)."""

    def __init__(self, source: ExpertSource, prefix: str, ttl: float = 3.0):
        self.source = source
        self.prefix = prefix
        self.ttl = ttl
        self._cached: Optional[dict[str, Endpoint]] = None
        self._stamp = 0.0

    async def get(self, force_refresh: bool = False) -> dict[str, Endpoint]:
        now = time.monotonic()
        if force_refresh or self._cached is None or now - self._stamp > self.ttl:
            self._cached = await self.source.get_alive_experts(self.prefix)
            self._stamp = now
        return self._cached


def score_experts(
    logits_per_dim: Sequence[np.ndarray], coords: np.ndarray
) -> np.ndarray:
    """Batched grid scores: sum of per-dimension gate logits.

    logits_per_dim: list over dims d of [batch, grid_d] arrays.
    coords: [n_experts, n_dims] integer grid coordinates.
    Returns [batch, n_experts].
    """
    scores = logits_per_dim[0][:, coords[:, 0]]
    for d in range(1, coords.shape[1]):
        scores = scores + logits_per_dim[d][:, coords[:, d]]
    return scores


def select_top_k(
    logits_per_dim: Sequence[np.ndarray],
    alive_uids: Sequence[str],
    k: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Per-sample top-k over all alive experts (full enumeration).

    Exact and vectorized; fine up to ~10^4 alive experts per batch.  The
    DHT-backed beam search (M2/M4) replaces enumeration when the grid is
    large but only a fraction is alive or local.
    Returns (sel [batch, k] indices into alive_uids, coords [n, n_dims]).
    """
    n_dims = len(logits_per_dim)
    coords = np.asarray(
        [split_uid(uid, n_dims)[1] for uid in alive_uids], dtype=np.int64
    )
    scores = score_experts(logits_per_dim, coords)  # [B, E]
    n = scores.shape[1]
    k_eff = min(k, n)
    # argpartition then sort the head: O(E + k log k) per sample
    part = np.argpartition(-scores, k_eff - 1, axis=1)[:, :k_eff]
    order = np.take_along_axis(scores, part, axis=1).argsort(axis=1)[:, ::-1]
    sel = np.take_along_axis(part, order, axis=1)
    return sel, coords
