"""Shared client-side RPC machinery: one background loop + pool registry.

All client stubs (RemoteExpert, RemoteMixtureOfExperts) in a process share a
single asyncio loop thread and a per-endpoint connection-pool registry —
the TPU-build replacement for the reference's thread-per-call dispatch.
"""

from __future__ import annotations

import concurrent.futures
import contextlib
import logging
import os
import threading
import time
from typing import Any, Callable, Coroutine, Optional

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop
from learning_at_home_tpu.utils.connection import PoolRegistry, force_protocol_v1

logger = logging.getLogger(__name__)

_lock = sanitizer.lock("client.rpc.state")
_loop: Optional[BackgroundLoop] = None
_registry: Optional[PoolRegistry] = None
_sync_dispatch_set = False

# Dispatch data-path regime.  "pipelined" (default): serialization happens
# on the caller's host thread (pack-once fan-out, WireTensors), frames go
# out via vectored writes, and connections negotiate protocol v2
# multiplexing.  "legacy": the pre-PR-2 path — per-call wire_cast +
# pack_message ON the client event loop, one RPC per socket (protocol v1
# forced).  Kept alive as the same-session A/B baseline (bench.py) and as
# an escape hatch (LAH_CLIENT_PIPELINE=0).
_dispatch_mode = (
    "legacy"
    if os.environ.get("LAH_CLIENT_PIPELINE", "1") in ("0", "legacy")
    else "pipelined"
)
if _dispatch_mode == "legacy":
    force_protocol_v1(True)


def dispatch_mode() -> str:
    return _dispatch_mode


def set_dispatch_mode(mode: str) -> None:
    """Switch the client dispatch regime at runtime (bench A/B)."""
    global _dispatch_mode
    if mode not in ("pipelined", "legacy"):
        raise ValueError(f"dispatch mode must be pipelined|legacy, got {mode!r}")
    _dispatch_mode = mode
    force_protocol_v1(mode == "legacy")


def ensure_sync_cpu_dispatch() -> None:
    """Disable XLA:CPU async dispatch — REQUIRED before any host-callback
    dispatch path (RemoteExpert / RemoteMixtureOfExperts).

    With async dispatch on, the CPU runtime can invoke an ``io_callback``
    whose input buffers are still being produced by thunks queued on the
    same (small) execution pool; the callback's ``np.asarray(arg)`` then
    waits on a computation that needs the thread the callback occupies —
    a deadlock.  Reproduced minimally on 1-core hosts at batch 2048
    (2026-07-29); anything that blocks inside a callback (our RPC quorum
    waits) is exposed.  Sync dispatch trades a little eager-mode pipelining
    for correctness; the pod-mode jitted path is unaffected.
    """
    global _sync_dispatch_set
    if _sync_dispatch_set:
        return
    import jax

    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        _sync_dispatch_set = True
        import logging

        # loud on purpose: this is a PROCESS-WIDE side effect — merely
        # constructing a swarm client object slows unrelated eager
        # XLA:CPU work in the same process (round-4 verdict weak #5)
        logging.getLogger(__name__).warning(
            "XLA:CPU async dispatch disabled process-wide (required for "
            "host-callback RPC paths; see ensure_sync_cpu_dispatch). "
            "Unrelated eager CPU work in this process loses pipelining."
        )
    except Exception as e:  # unknown option on this jax version
        import logging

        logging.getLogger(__name__).warning(
            "could not disable XLA:CPU async dispatch (%s: %s) — blocking "
            "host callbacks may deadlock under load; see ensure_sync_cpu_"
            "dispatch docstring", type(e).__name__, e,
        )
        _sync_dispatch_set = True


# --------------------------------------------------------------------------
# dispatch-wait watchdog (ISSUE 5 satellite): the jitted-client
# io_callback deadlock class (ROUND5_NOTES "hazards") presents as a
# SILENT hang — the host thread blocks in client_loop().run() forever
# while the loop waits on buffers the blocked thread will never release.
# A watchdog timer armed around the dispatch wait turns that into a
# diagnosable event: one WARNING per process, with every thread's stack.
# --------------------------------------------------------------------------

_watchdog_lock = sanitizer.lock("client.rpc.watchdog")
_watchdog_fired = False


def reset_dispatch_watchdog() -> None:
    """Re-arm the once-per-process watchdog warning (test hook)."""
    global _watchdog_fired
    with _watchdog_lock:
        _watchdog_fired = False


def _all_thread_stacks() -> str:
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def _watchdog_fire(budget: float, what: str) -> None:
    global _watchdog_fired
    with _watchdog_lock:
        if _watchdog_fired:
            return
        _watchdog_fired = True
    # a fired watchdog is exactly the moment the recent-event ring matters:
    # persist it before anyone restarts the process (ISSUE 19 layer 4)
    from learning_at_home_tpu.utils import flight

    flight.record(
        "client", "dispatch_watchdog", what=what, budget_s=round(budget, 3)
    )
    flight.dump("dispatch_watchdog")
    logger.warning(
        "dispatch-wait watchdog: %s has waited > %.2fs (watchdog budget = "
        "LAH_DISPATCH_WATCHDOG_MULT x pool RTT-EMA).  If this never "
        "completes, suspect the jitted-client io_callback deadlock "
        "(ROUND5_NOTES hazards).  Thread stacks:\n%s",
        what, budget, _all_thread_stacks(),
    )


@contextlib.contextmanager
def dispatch_wait_watchdog(rtt_ema: Optional[float], what: str = "dispatch"):
    """Arm a timer for the enclosed blocking dispatch wait.

    Budget = ``LAH_DISPATCH_WATCHDOG_MULT`` (default 20) x the slowest
    involved pool's RTT EMA, floored at ``LAH_DISPATCH_WATCHDOG_MIN_S``
    (default 5 s — cold pools' first exchanges legitimately include
    connects and server-side warmup compiles).  Disabled when the
    multiple is <= 0 or no RTT has ever been measured (nothing to scale
    from).  Firing logs ONE warning per process with all thread stacks
    and never interrupts the wait — diagnosis, not intervention."""
    if _watchdog_fired or rtt_ema is None:
        # once the single warning is out there is nothing left to arm —
        # don't pay a Timer-thread create/cancel per dispatch forever
        yield
        return
    try:
        mult = float(os.environ.get("LAH_DISPATCH_WATCHDOG_MULT", "20"))
        floor = float(os.environ.get("LAH_DISPATCH_WATCHDOG_MIN_S", "5"))
    except ValueError:
        mult, floor = 20.0, 5.0
    if mult <= 0:
        yield
        return
    budget = max(mult * rtt_ema, floor)
    timer = threading.Timer(budget, _watchdog_fire, args=(budget, what))
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()


# --------------------------------------------------------------------------
# future-based dispatch core (ISSUE 7): the fire half of a dispatch
# submits its quorum fan-out coroutine to the lah-client loop and
# immediately returns a joinable DispatchFuture — the caller's host
# thread is free to keep computing anything not data-dependent on the
# replies, and joins as late as the dependency allows.  The ROUND5
# io_callback-hang hazard class is retired BY CONSTRUCTION here: the
# fire path never waits on the loop at all, and the join is one bounded
# wait on a concurrent future resolved by the loop thread (no nested
# loop waits, and — in pipelined mode — a hard timeout that turns a
# stalled pool into a diagnosable error instead of a silent hang; the
# legacy A/B arm keeps the PR-5 watchdog + unbounded wait semantics).
# --------------------------------------------------------------------------

# extra slack on top of (rpc_timeout + timeout_after_k_min) before a
# pipelined join gives up on its fan-out: first exchanges against a cold
# server legitimately include connects and warmup compiles
JOIN_GRACE_S = float(os.environ.get("LAH_DISPATCH_JOIN_GRACE_S", "30"))


class DispatchJoinTimeout(RuntimeError):
    """A DispatchFuture.join exceeded its hard deadline: the fan-out
    coroutine never resolved.  The fan-out task is cancelled before this
    is raised, so the loop is left clean.  Suspect a stalled/black-holed
    pool (a peer accepting connections but never replying) — the
    condition the legacy path's dispatch-wait watchdog could only WARN
    about is a clean, catchable error on the future-based path."""


class DispatchFuture:
    """A joinable in-flight expert fan-out.

    Created on the caller's host thread by the fire half of a dispatch
    (``RemoteMixtureOfExperts.dispatch_async`` / ``backward_async``)
    AFTER payload serialization: construction submits the quorum fan-out
    coroutine to the ``lah-client`` loop and returns immediately — it
    never blocks on the loop (sanitizer site ``rpc.DispatchFuture.fire``
    would be the place to assert that, but construction does no waiting
    by construction).  :meth:`join` blocks the calling host thread until
    the fan-out resolves, runs the supplied finalizer on its results,
    and reports how much of the in-flight window the caller actually
    hid behind other work (the ``overlap fraction`` observable).

    Join semantics by dispatch mode:

    - ``join_timeout`` set (pipelined): hard deadline; on expiry the
      fan-out task is cancelled and :class:`DispatchJoinTimeout` raises.
    - ``join_timeout`` None (legacy A/B arm): unbounded wait guarded by
      the once-per-process ``dispatch_wait_watchdog`` — the exact PR-5
      behavior, kept as the regression baseline.
    """

    def __init__(
        self,
        kind: str,
        coro: Coroutine,
        finalize: Callable[[Any], Any],
        *,
        join_timeout: Optional[float] = None,
        watchdog_rtt: Optional[float] = None,
        what: str = "dispatch",
        on_join_exit: Optional[Callable[["DispatchFuture"], None]] = None,
    ):
        self.kind = kind
        self._finalize = finalize
        self._join_timeout = join_timeout
        self._watchdog_rtt = watchdog_rtt
        self._what = what
        self._on_join_exit = on_join_exit
        self.joined = False
        self.cancelled = False
        # overlap accounting (read by the finalizer/owner after join):
        # fired_at -> completed_at is the in-flight window; the slice of
        # it NOT spent blocked inside join() was hidden behind caller
        # compute.  completed_at is stamped on the loop thread the moment
        # the fan-out coroutine settles (plain float store — no lock; the
        # join thread only reads it after the future resolved).
        self.completed_at: Optional[float] = None
        self.blocked_s: float = 0.0
        self.fired_at = time.monotonic()
        self._cf = client_loop().submit(self._timed(coro))

    async def _timed(self, coro: Coroutine):
        try:
            return await coro
        finally:
            self.completed_at = time.monotonic()

    def done(self) -> bool:
        return self._cf.done()

    def cancel(self) -> None:
        """Best-effort cancel of the in-flight fan-out (the
        ticket-eviction path).  Marks the future consumed and runs the
        join-exit hook once, so the owner's in-flight accounting drains
        — an evicted, never-joined ticket must not leak the
        ``inflight_dispatches`` gauge."""
        self.cancelled = True
        self._cf.cancel()
        if not self.joined:
            self.joined = True
            if self._on_join_exit is not None:
                self._on_join_exit(self)

    # ---- overlap observables (valid after join) ----

    def inflight_s(self) -> float:
        end = self.completed_at
        if end is None:
            end = time.monotonic()
        return max(end - self.fired_at, 0.0)

    def overlap_fraction(self) -> float:
        """Fraction of the in-flight window hidden behind caller compute
        (0.0 = the caller joined immediately and ate the whole wait —
        the serial regime; → 1.0 = the replies were already in when the
        caller finally joined)."""
        inflight = self.inflight_s()
        if inflight <= 0.0:
            return 0.0
        return max(0.0, min(1.0, (inflight - self.blocked_s) / inflight))

    @sanitizer.runs_on("host", site="rpc.DispatchFuture.join")
    def join(self, timeout: Optional[float] = None) -> Any:
        """Block this host thread until the fan-out resolves; return the
        finalizer's output.  Never call from a loop thread: the wait
        would starve the loop that must resolve it (asserted via the
        sanitizer site above; ``BackgroundLoop.run``'s always-on guard
        covers the submit-side shape)."""
        if self.joined:
            raise RuntimeError(f"{self.kind} DispatchFuture joined twice")
        self.joined = True
        deadline = timeout if timeout is not None else self._join_timeout
        t_block = time.monotonic()
        try:
            if deadline is None:
                # legacy arm: unbounded wait under the PR-5 watchdog —
                # the hang class stays diagnosable there, not fatal
                with dispatch_wait_watchdog(
                    self._watchdog_rtt, what=self._what
                ):
                    results = self._cf.result()
            else:
                try:
                    results = self._cf.result(deadline)
                except concurrent.futures.TimeoutError:
                    self._cf.cancel()
                    raise DispatchJoinTimeout(
                        f"{self._what}: fan-out did not resolve within "
                        f"{deadline:.1f}s of join — cancelled the in-flight "
                        "task.  A pool is stalled (accepting but never "
                        "replying), or the join deadline is below the "
                        "server's warmup-compile window; see "
                        "LAH_DISPATCH_JOIN_GRACE_S."
                    ) from None
        finally:
            self.blocked_s = time.monotonic() - t_block
            if self._on_join_exit is not None:
                self._on_join_exit(self)
        return self._finalize(results)


def client_loop() -> BackgroundLoop:
    global _loop
    with _lock:
        if _loop is None or _loop._shutdown:
            _loop = BackgroundLoop(name="lah-client")
        return _loop


def pool_registry() -> PoolRegistry:
    global _registry
    with _lock:
        if _registry is None:
            _registry = PoolRegistry()
        return _registry


def reset_client_rpc() -> None:
    """Close all client connections and the loop (test teardown helper)."""
    global _loop, _registry
    # the caller is declaring the client side idle: every fired dispatch
    # should have been joined or cancelled by now — audit the gauges
    # before tearing the loop down (sanitizer-gated, no-op in production)
    sanitizer.quiesce_point("client")
    with _lock:
        if _registry is not None:
            registry = _registry
            _registry = None
            if _loop is not None and not _loop._shutdown:

                async def _close():
                    registry.close()

                try:
                    _loop.run(_close(), timeout=5)
                except Exception as e:
                    # best-effort teardown, but never silent (R6): a close
                    # that fails repeatedly is an FD leak worth seeing
                    logger.debug("client pool close failed during reset: "
                                 "%s: %s", type(e).__name__, e)
        if _loop is not None:
            _loop.shutdown()
            _loop = None
