"""Shared client-side RPC machinery: one background loop + pool registry.

All client stubs (RemoteExpert, RemoteMixtureOfExperts) in a process share a
single asyncio loop thread and a per-endpoint connection-pool registry —
the TPU-build replacement for the reference's thread-per-call dispatch.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop
from learning_at_home_tpu.utils.connection import PoolRegistry, force_protocol_v1

_lock = threading.Lock()
_loop: Optional[BackgroundLoop] = None
_registry: Optional[PoolRegistry] = None
_sync_dispatch_set = False

# Dispatch data-path regime.  "pipelined" (default): serialization happens
# on the caller's host thread (pack-once fan-out, WireTensors), frames go
# out via vectored writes, and connections negotiate protocol v2
# multiplexing.  "legacy": the pre-PR-2 path — per-call wire_cast +
# pack_message ON the client event loop, one RPC per socket (protocol v1
# forced).  Kept alive as the same-session A/B baseline (bench.py) and as
# an escape hatch (LAH_CLIENT_PIPELINE=0).
_dispatch_mode = (
    "legacy"
    if os.environ.get("LAH_CLIENT_PIPELINE", "1") in ("0", "legacy")
    else "pipelined"
)
if _dispatch_mode == "legacy":
    force_protocol_v1(True)


def dispatch_mode() -> str:
    return _dispatch_mode


def set_dispatch_mode(mode: str) -> None:
    """Switch the client dispatch regime at runtime (bench A/B)."""
    global _dispatch_mode
    if mode not in ("pipelined", "legacy"):
        raise ValueError(f"dispatch mode must be pipelined|legacy, got {mode!r}")
    _dispatch_mode = mode
    force_protocol_v1(mode == "legacy")


def ensure_sync_cpu_dispatch() -> None:
    """Disable XLA:CPU async dispatch — REQUIRED before any host-callback
    dispatch path (RemoteExpert / RemoteMixtureOfExperts).

    With async dispatch on, the CPU runtime can invoke an ``io_callback``
    whose input buffers are still being produced by thunks queued on the
    same (small) execution pool; the callback's ``np.asarray(arg)`` then
    waits on a computation that needs the thread the callback occupies —
    a deadlock.  Reproduced minimally on 1-core hosts at batch 2048
    (2026-07-29); anything that blocks inside a callback (our RPC quorum
    waits) is exposed.  Sync dispatch trades a little eager-mode pipelining
    for correctness; the pod-mode jitted path is unaffected.
    """
    global _sync_dispatch_set
    if _sync_dispatch_set:
        return
    import jax

    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        _sync_dispatch_set = True
        import logging

        # loud on purpose: this is a PROCESS-WIDE side effect — merely
        # constructing a swarm client object slows unrelated eager
        # XLA:CPU work in the same process (round-4 verdict weak #5)
        logging.getLogger(__name__).warning(
            "XLA:CPU async dispatch disabled process-wide (required for "
            "host-callback RPC paths; see ensure_sync_cpu_dispatch). "
            "Unrelated eager CPU work in this process loses pipelining."
        )
    except Exception as e:  # unknown option on this jax version
        import logging

        logging.getLogger(__name__).warning(
            "could not disable XLA:CPU async dispatch (%s: %s) — blocking "
            "host callbacks may deadlock under load; see ensure_sync_cpu_"
            "dispatch docstring", type(e).__name__, e,
        )
        _sync_dispatch_set = True


def client_loop() -> BackgroundLoop:
    global _loop
    with _lock:
        if _loop is None or _loop._shutdown:
            _loop = BackgroundLoop(name="lah-client")
        return _loop


def pool_registry() -> PoolRegistry:
    global _registry
    with _lock:
        if _registry is None:
            _registry = PoolRegistry()
        return _registry


def reset_client_rpc() -> None:
    """Close all client connections and the loop (test teardown helper)."""
    global _loop, _registry
    with _lock:
        if _registry is not None:
            registry = _registry
            _registry = None
            if _loop is not None and not _loop._shutdown:

                async def _close():
                    registry.close()

                try:
                    _loop.run(_close(), timeout=5)
                except Exception:
                    pass
        if _loop is not None:
            _loop.shutdown()
            _loop = None
