"""Shared client-side RPC machinery: one background loop + pool registry.

All client stubs (RemoteExpert, RemoteMixtureOfExperts) in a process share a
single asyncio loop thread and a per-endpoint connection-pool registry —
the TPU-build replacement for the reference's thread-per-call dispatch.
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
from typing import Optional

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop
from learning_at_home_tpu.utils.connection import PoolRegistry, force_protocol_v1

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_loop: Optional[BackgroundLoop] = None
_registry: Optional[PoolRegistry] = None
_sync_dispatch_set = False

# Dispatch data-path regime.  "pipelined" (default): serialization happens
# on the caller's host thread (pack-once fan-out, WireTensors), frames go
# out via vectored writes, and connections negotiate protocol v2
# multiplexing.  "legacy": the pre-PR-2 path — per-call wire_cast +
# pack_message ON the client event loop, one RPC per socket (protocol v1
# forced).  Kept alive as the same-session A/B baseline (bench.py) and as
# an escape hatch (LAH_CLIENT_PIPELINE=0).
_dispatch_mode = (
    "legacy"
    if os.environ.get("LAH_CLIENT_PIPELINE", "1") in ("0", "legacy")
    else "pipelined"
)
if _dispatch_mode == "legacy":
    force_protocol_v1(True)


def dispatch_mode() -> str:
    return _dispatch_mode


def set_dispatch_mode(mode: str) -> None:
    """Switch the client dispatch regime at runtime (bench A/B)."""
    global _dispatch_mode
    if mode not in ("pipelined", "legacy"):
        raise ValueError(f"dispatch mode must be pipelined|legacy, got {mode!r}")
    _dispatch_mode = mode
    force_protocol_v1(mode == "legacy")


def ensure_sync_cpu_dispatch() -> None:
    """Disable XLA:CPU async dispatch — REQUIRED before any host-callback
    dispatch path (RemoteExpert / RemoteMixtureOfExperts).

    With async dispatch on, the CPU runtime can invoke an ``io_callback``
    whose input buffers are still being produced by thunks queued on the
    same (small) execution pool; the callback's ``np.asarray(arg)`` then
    waits on a computation that needs the thread the callback occupies —
    a deadlock.  Reproduced minimally on 1-core hosts at batch 2048
    (2026-07-29); anything that blocks inside a callback (our RPC quorum
    waits) is exposed.  Sync dispatch trades a little eager-mode pipelining
    for correctness; the pod-mode jitted path is unaffected.
    """
    global _sync_dispatch_set
    if _sync_dispatch_set:
        return
    import jax

    try:
        jax.config.update("jax_cpu_enable_async_dispatch", False)
        _sync_dispatch_set = True
        import logging

        # loud on purpose: this is a PROCESS-WIDE side effect — merely
        # constructing a swarm client object slows unrelated eager
        # XLA:CPU work in the same process (round-4 verdict weak #5)
        logging.getLogger(__name__).warning(
            "XLA:CPU async dispatch disabled process-wide (required for "
            "host-callback RPC paths; see ensure_sync_cpu_dispatch). "
            "Unrelated eager CPU work in this process loses pipelining."
        )
    except Exception as e:  # unknown option on this jax version
        import logging

        logging.getLogger(__name__).warning(
            "could not disable XLA:CPU async dispatch (%s: %s) — blocking "
            "host callbacks may deadlock under load; see ensure_sync_cpu_"
            "dispatch docstring", type(e).__name__, e,
        )
        _sync_dispatch_set = True


# --------------------------------------------------------------------------
# dispatch-wait watchdog (ISSUE 5 satellite): the jitted-client
# io_callback deadlock class (ROUND5_NOTES "hazards") presents as a
# SILENT hang — the host thread blocks in client_loop().run() forever
# while the loop waits on buffers the blocked thread will never release.
# A watchdog timer armed around the dispatch wait turns that into a
# diagnosable event: one WARNING per process, with every thread's stack.
# --------------------------------------------------------------------------

_watchdog_lock = sanitizer.lock("client.rpc.watchdog")
_watchdog_fired = False


def reset_dispatch_watchdog() -> None:
    """Re-arm the once-per-process watchdog warning (test hook)."""
    global _watchdog_fired
    with _watchdog_lock:
        _watchdog_fired = False


def _all_thread_stacks() -> str:
    import sys
    import traceback

    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        out.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        out.append("".join(traceback.format_stack(frame)))
    return "\n".join(out)


def _watchdog_fire(budget: float, what: str) -> None:
    global _watchdog_fired
    with _watchdog_lock:
        if _watchdog_fired:
            return
        _watchdog_fired = True
    logger.warning(
        "dispatch-wait watchdog: %s has waited > %.2fs (watchdog budget = "
        "LAH_DISPATCH_WATCHDOG_MULT x pool RTT-EMA).  If this never "
        "completes, suspect the jitted-client io_callback deadlock "
        "(ROUND5_NOTES hazards).  Thread stacks:\n%s",
        what, budget, _all_thread_stacks(),
    )


@contextlib.contextmanager
def dispatch_wait_watchdog(rtt_ema: Optional[float], what: str = "dispatch"):
    """Arm a timer for the enclosed blocking dispatch wait.

    Budget = ``LAH_DISPATCH_WATCHDOG_MULT`` (default 20) x the slowest
    involved pool's RTT EMA, floored at ``LAH_DISPATCH_WATCHDOG_MIN_S``
    (default 5 s — cold pools' first exchanges legitimately include
    connects and server-side warmup compiles).  Disabled when the
    multiple is <= 0 or no RTT has ever been measured (nothing to scale
    from).  Firing logs ONE warning per process with all thread stacks
    and never interrupts the wait — diagnosis, not intervention."""
    if _watchdog_fired or rtt_ema is None:
        # once the single warning is out there is nothing left to arm —
        # don't pay a Timer-thread create/cancel per dispatch forever
        yield
        return
    try:
        mult = float(os.environ.get("LAH_DISPATCH_WATCHDOG_MULT", "20"))
        floor = float(os.environ.get("LAH_DISPATCH_WATCHDOG_MIN_S", "5"))
    except ValueError:
        mult, floor = 20.0, 5.0
    if mult <= 0:
        yield
        return
    budget = max(mult * rtt_ema, floor)
    timer = threading.Timer(budget, _watchdog_fire, args=(budget, what))
    timer.daemon = True
    timer.start()
    try:
        yield
    finally:
        timer.cancel()


def client_loop() -> BackgroundLoop:
    global _loop
    with _lock:
        if _loop is None or _loop._shutdown:
            _loop = BackgroundLoop(name="lah-client")
        return _loop


def pool_registry() -> PoolRegistry:
    global _registry
    with _lock:
        if _registry is None:
            _registry = PoolRegistry()
        return _registry


def reset_client_rpc() -> None:
    """Close all client connections and the loop (test teardown helper)."""
    global _loop, _registry
    with _lock:
        if _registry is not None:
            registry = _registry
            _registry = None
            if _loop is not None and not _loop._shutdown:

                async def _close():
                    registry.close()

                try:
                    _loop.run(_close(), timeout=5)
                except Exception as e:
                    # best-effort teardown, but never silent (R6): a close
                    # that fails repeatedly is an FD leak worth seeing
                    logger.debug("client pool close failed during reset: "
                                 "%s: %s", type(e).__name__, e)
        if _loop is not None:
            _loop.shutdown()
            _loop = None
