"""Shared client-side RPC machinery: one background loop + pool registry.

All client stubs (RemoteExpert, RemoteMixtureOfExperts) in a process share a
single asyncio loop thread and a per-endpoint connection-pool registry —
the TPU-build replacement for the reference's thread-per-call dispatch.
"""

from __future__ import annotations

import threading
from typing import Optional

from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop
from learning_at_home_tpu.utils.connection import PoolRegistry

_lock = threading.Lock()
_loop: Optional[BackgroundLoop] = None
_registry: Optional[PoolRegistry] = None


def client_loop() -> BackgroundLoop:
    global _loop
    with _lock:
        if _loop is None or _loop._shutdown:
            _loop = BackgroundLoop(name="lah-client")
        return _loop


def pool_registry() -> PoolRegistry:
    global _registry
    with _lock:
        if _registry is None:
            _registry = PoolRegistry()
        return _registry


def reset_client_rpc() -> None:
    """Close all client connections and the loop (test teardown helper)."""
    global _loop, _registry
    with _lock:
        if _registry is not None:
            registry = _registry
            _registry = None
            if _loop is not None and not _loop._shutdown:

                async def _close():
                    registry.close()

                try:
                    _loop.run(_close(), timeout=5)
                except Exception:
                    pass
        if _loop is not None:
            _loop.shutdown()
            _loop = None
