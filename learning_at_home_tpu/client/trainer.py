"""Pipelined swarm trainer: overlap RPC waits with local compute.

The sequential swarm step serializes every MoE layer's forward fan-out,
quorum wait, and backward fan-out — the host CPU idles during each network
round-trip, which is why round-1 swarm throughput sat ~11× below pod mode
on like hardware (BASELINE.md).  The reference's whole philosophy is
asynchronous, staleness-tolerant training (server experts already apply
delayed updates on every backward RPC), so the trainer can be asynchronous
too: run several micro-batch steps concurrently and apply trunk/gate
updates as each finishes — delayed parameter updates, the same contract as
the server side.

Mechanics: ``n_workers`` Python threads each run the EAGER train step of
``SwarmDMoETransformerLM`` on their own micro-batch.  The two long poles —
XLA trunk compute (releases the GIL) and the MoE dispatch's asyncio quorum
wait (blocks on a future, releases the GIL) — interleave across workers,
so while one step waits on expert replies another traces/computes.  A lock
serializes only the optimizer apply; gradients are computed against the
params snapshot taken at step start, i.e. updates may be ``n_workers - 1``
steps stale (bounded staleness, same tolerance class as the server-side
async SGD).

Convergence note: this is hogwild-style on the trunk; use the same LR you
would for small async staleness.  ``n_workers=1`` reproduces the exact
sequential semantics.

Multi-trainer synchronization: in async-DP runs each trainer owns its own
trunk/gate state.  :meth:`attach_averaging` plugs in an
``averaging.AveragingSession`` — between local steps the session
snapshots the params (consistent read under the apply lock), runs a
DHT-matched group all-reduce with the other trainers in the background,
and applies the group delta atomically (``params += mean - snapshot``;
local steps taken during the round survive — delayed updates, the same
staleness class as everything else here).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

import jax
import optax

from learning_at_home_tpu.utils import sanitizer

__all__ = ["PipelinedSwarmTrainer"]


class PipelinedSwarmTrainer:
    """Runs concurrent micro-batch train steps against a swarm model.

    Usage::

        trainer = PipelinedSwarmTrainer(model, optimizer, params, n_workers=4)
        result = trainer.train(batches, steps=100, on_log=print)
        params = trainer.params
    """

    def __init__(
        self,
        model: Any,  # SwarmDMoETransformerLM-shaped: loss_fn(params, ids, tgt)
        optimizer: optax.GradientTransformation,
        params: Any,
        opt_state: Any = None,
        n_workers: int = 2,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.model = model
        self.optimizer = optimizer
        self.params = params
        self.opt_state = opt_state if opt_state is not None else optimizer.init(params)
        self.n_workers = n_workers
        self._apply_lock = sanitizer.lock("trainer.apply")
        self._batch_lock = sanitizer.lock("trainer.batch")
        self._grad_fn = jax.value_and_grad(model.loss_fn)
        self.losses: list[float] = []
        self.step_count = 0
        self.errors: list[BaseException] = []
        self._averaging = None  # AveragingSession via attach_averaging

    # ---- internals ----

    def _next_batch(self, it: Iterator, budget: list[int]):
        """Thread-safe batch claim; returns (step_idx, batch) or None."""
        with self._batch_lock:
            if budget[0] <= 0:
                return None
            budget[0] -= 1
            try:
                batch = next(it)
            except StopIteration:
                budget[0] = 0
                return None
            step_idx = self.step_count + 0  # informational only
            return step_idx, batch

    def _worker(self, it, budget, on_step: Optional[Callable]):
        while True:
            try:
                claim = self._next_batch(it, budget)
            except BaseException as e:  # iterator failure must not be silent
                self.errors.append(e)
                with self._batch_lock:
                    budget[0] = 0
                return
            if claim is None:
                return
            _, (ids, tgt) = claim
            params_snapshot = self.params  # delayed-update read
            try:
                loss, grads = self._grad_fn(params_snapshot, ids, tgt)
            except BaseException as e:  # surface, don't strand the budget
                self.errors.append(e)
                with self._batch_lock:
                    budget[0] = 0
                return
            with self._apply_lock:
                updates, self.opt_state = self.optimizer.update(
                    grads, self.opt_state, self.params
                )
                self.params = optax.apply_updates(self.params, updates)
                self.step_count += 1
                self.losses.append(float(loss))
                step_now = self.step_count
            if on_step is not None:
                on_step(step_now, float(loss))
            if self._averaging is not None:
                self._averaging.notify_step(step_now)

    # ---- public API ----

    def attach_averaging(self, session) -> None:
        """Plug in an ``averaging.AveragingSession``: it snapshots params
        between steps and applies the group mean atomically."""
        session.attach_trainer(
            snapshot_fn=lambda: self.snapshot()[0],
            apply_fn=self.apply_param_transform,
        )
        self._averaging = session

    def apply_param_transform(self, transform) -> None:
        """Atomically replace ``params`` with ``transform(params)`` under
        the apply lock (the averaging-apply entry point — never races an
        optimizer update)."""
        with self._apply_lock:
            self.params = transform(self.params)

    def averaging_stats(self) -> dict | None:
        return (
            self._averaging.averaging_stats()
            if self._averaging is not None else None
        )

    def snapshot(self) -> tuple:
        """A CONSISTENT (params, opt_state, step_count) triple — the three
        are only mutated together under the apply lock, so checkpointing
        callers must read them under it too."""
        with self._apply_lock:
            return self.params, self.opt_state, self.step_count

    def train(
        self,
        batches: Iterable,
        steps: int,
        log_every: int = 10,
        on_log: Optional[Callable[[dict], None]] = None,
        tokens_per_batch: Optional[int] = None,
    ) -> dict:
        """Consume ``steps`` micro-batches with ``n_workers`` concurrent
        steps in flight; returns a summary dict (losses, tokens/sec)."""
        it = iter(batches)
        budget = [steps]
        t0 = time.perf_counter()

        def on_step(step_now: int, loss: float) -> None:
            if on_log is not None and (
                step_now % log_every == 0 or step_now == steps
            ):
                elapsed = time.perf_counter() - t0
                entry = {
                    "step": step_now,
                    "loss": round(loss, 4),
                    "steps_per_sec": round(step_now / elapsed, 2),
                }
                if tokens_per_batch:
                    entry["tokens_per_sec"] = round(
                        step_now * tokens_per_batch / elapsed, 1
                    )
                on_log(entry)

        threads = [
            threading.Thread(
                target=self._worker, args=(it, budget, on_step),
                name=f"swarm-trainer-{i}", daemon=True,
            )
            for i in range(self.n_workers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if self.errors:
            raise self.errors[0]
        elapsed = time.perf_counter() - t0
        return {
            "steps": self.step_count,
            "elapsed_s": elapsed,
            "final_loss": self.losses[-1] if self.losses else None,
            "mean_loss_last_10": (
                sum(self.losses[-10:]) / len(self.losses[-10:])
                if self.losses
                else None
            ),
            "tokens_per_sec": (
                self.step_count * tokens_per_batch / elapsed
                if tokens_per_batch
                else None
            ),
        }
