"""RemoteMixtureOfExperts: the headline DMoE layer.

Contract from the reference's ``hivemind/client/moe.py`` (SURVEY.md §2 [BJ];
unverifiable refs, mount empty): linear gating over a multi-dimensional
expert grid (UIDs like ``ffn.4.17``); per-sample top-k expert choice among
*alive* experts; parallel dispatch; wait for ≥ ``k_min`` replies per sample
then a grace timeout; drop stragglers/failures; return the gate-weighted
mixture.  Backward mirrors this with ``backward_k_min`` — and triggers the
server-side async optimizer step on every expert that participates.

TPU-native structure (who computes what):

- in-graph (jit, differentiable): gate logits ``x @ W_d`` per grid dim,
  score gathering at the chosen coordinates, masked softmax, weighted
  mixture.  Gradients to the gate weights flow through this path.
- host (``io_callback`` under ``jax.custom_vjp``): alive-set lookup,
  per-sample top-k selection, per-expert row dispatch over the framed RPC
  protocol with the k-of-n quorum, and the mirrored backward fan-out.
  Gradients to ``x`` flow through the backward RPCs; the discrete expert
  *choice* contributes zero gradient (straight-through on membership, exact
  on weights — same semantics as the reference).

The forward host call stashes a session (which experts answered, with which
rows) so backward targets exactly the responding experts — the
``_RemoteCallMany`` contract.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import os
import threading
from collections import OrderedDict, deque
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from learning_at_home_tpu.client.routing import (
    CachedAliveSet,
    ExpertSource,
    ReplicaSet,
    RoutingCostModel,
    as_replica_set,
    beam_search_alive,
    filter_valid_uids,
    select_top_k,
)
from learning_at_home_tpu.client.rpc import (
    DispatchFuture,
    client_loop,
    dispatch_mode,
    pool_registry,
)
from learning_at_home_tpu.utils import flight, sanitizer
from learning_at_home_tpu.utils.connection import (
    QUORUM_STRAGGLER_CANCEL,
    RemoteCallError,
)
from learning_at_home_tpu.utils.profiling import new_trace_id, timeline

logger = logging.getLogger(__name__)

# co-activation table bound (ISSUE 16): distinct pairs tracked per MoE —
# a k-of-grid gate selects O(k²) pairs per dispatch, so real workloads
# sit far below this; the cap only bites on pathological gates
COACT_MAX_PAIRS = 4096


class MoEDispatchError(RuntimeError):
    """Total dispatch failure: no expert replied for ANY sample (or no
    experts are alive at all).  Per-sample quorum misses do NOT raise —
    those samples are masked to zero contribution and counted in
    ``samples_dropped`` (the swarm is staleness- and loss-tolerant by
    design; one dead server must degrade the batch, not kill the step)."""


class RemoteMixtureOfExperts:
    """Fault-tolerant mixture over a grid of network-remote experts.

    Usage::

        moe = RemoteMixtureOfExperts(in_features=1024, grid_size=(32, 32),
                                     uid_prefix="ffn", source=dht_or_static)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        y = moe(x, gate)                      # works eagerly and under jit
        grads = jax.grad(loss)(gate, x)       # backward RPCs happen inside

    Gate parameters live client-side (trained by the caller's optimizer);
    expert parameters live server-side (updated asynchronously by each
    backward RPC).
    """

    _call_counter = itertools.count()

    def __init__(
        self,
        *,
        in_features: int,
        grid_size: Sequence[int],
        uid_prefix: str,
        source: ExpertSource,
        k_best: int = 4,
        k_min: int = 1,
        backward_k_min: int = 1,
        timeout_after_k_min: float = 1.0,
        forward_timeout: float = 30.0,
        backward_timeout: float = 30.0,
        alive_ttl: float = 3.0,
        max_sessions: int = 1024,
        compute_dtype=jnp.float32,
        routing: str = "enumerate",
        beam_size: int = 8,
        merge_rpcs: bool = True,
        wire_dtype: Optional[str] = None,
        wire_codec: Optional[str] = None,
        latency_weight: float = 0.0,
        routing_cost_weight: Optional[float] = None,
        telemetry_prefix: str = "swarm",
        hedge_mult: Optional[float] = None,
        hedge_floor_s: Optional[float] = None,
        alive_swr: Optional[bool] = None,
    ):
        if routing not in ("enumerate", "beam"):
            raise ValueError(f"routing must be 'enumerate' or 'beam', got {routing!r}")
        from learning_at_home_tpu.utils.serialization import (
            validate_wire_codec,
            validate_wire_dtype,
        )

        validate_wire_dtype(wire_dtype)
        from learning_at_home_tpu.client.rpc import ensure_sync_cpu_dispatch

        ensure_sync_cpu_dispatch()  # host-callback path: see rpc.py
        self.in_features = in_features
        self.grid_size = tuple(grid_size)
        self.n_dims = len(self.grid_size)
        self.uid_prefix = uid_prefix
        self.k_best, self.k_min = k_best, k_min
        self.backward_k_min = backward_k_min
        self.timeout_after_k_min = timeout_after_k_min
        self.forward_timeout = forward_timeout
        self.backward_timeout = backward_timeout
        self.compute_dtype = compute_dtype
        self.routing = routing
        self.beam_size = beam_size
        # one 'multi' request per peer (overhead per PEER not per expert);
        # False restores the reference's strictly per-expert fan-out
        self.merge_rpcs = merge_rpcs
        # transport encoding for activation/grad payloads ("bfloat16" or
        # "float16"): floating tensors are downcast on the wire BOTH ways
        # (the server upcasts to f32 for compute and downcasts its reply —
        # see server/connection_handler.py).  Halves the payload of the
        # large-row swarm dispatches that dominate dispatch p50; math
        # still runs f32 on both ends.  None = uncompressed f32.
        self.wire_dtype = wire_dtype
        # wire CODEC (ISSUE 5): None = adaptive per-pool selection — the
        # escalation policy in serialization.select_wire_codec picks
        # none→bf16→8-bit from each pool's RTT EMA and measured bytes/sec
        # (unmeasured/fast pools stay on the wire_dtype base, so the
        # default wire is byte-identical to pre-codec builds).  An
        # explicit codec ("none"/"bf16"/"f16"/"u8"/"blockq8") pins every
        # pool; the LAH_WIRE_CODEC environment variable overrides both.
        # Quantized codecs are only ever OFFERED to pools whose hello
        # negotiation echoed the "codec" feature (v1 peers and old builds
        # transparently fall back to the wire_dtype base), and only in
        # pipelined dispatch mode (the legacy A/B arm keeps the exact
        # pre-PR-2 wire).
        env_codec = os.environ.get("LAH_WIRE_CODEC") or None
        validate_wire_codec(env_codec)
        validate_wire_codec(wire_codec)
        self.wire_codec = env_codec or wire_codec
        if self.wire_codec in ("bf16", "f16") and wire_dtype is not None:
            from learning_at_home_tpu.utils.serialization import (
                _DTYPE_TO_CODEC,
            )

            if _DTYPE_TO_CODEC.get(wire_dtype) != self.wire_codec:
                raise ValueError(
                    f"wire_codec={self.wire_codec!r} conflicts with "
                    f"wire_dtype={wire_dtype!r}: a downcast codec pin must "
                    "match the configured wire dtype (or drop one of them)"
                )
        # per-codec payload counts (plain int adds on the host thread;
        # scrape readers copy-with-retry like the deques)
        self.codec_counts: dict[str, int] = {}
        # latency-aware SELECTION (ISSUE 8; cf. TA-MoE / MoETuner): the
        # RoutingCostModel debits each expert's selection score by
        # ``weight × predicted completion time`` — pool RTT EMA + the
        # peer's DHT-advertised queue depth + estimated transfer time at
        # the negotiated codec, minimized over the uid's replica set.
        # Combine weights stay clean-gate (selection-only, like router
        # jitter).  Weight resolution: LAH_ROUTING_COST_WEIGHT env >
        # ``routing_cost_weight`` ctor > the historical ``latency_weight``
        # alias (whose rtt-only behavior the model reproduces bitwise
        # when no load feed or bandwidth measurement exists).  0 = off:
        # bias is None and selection is bitwise today's blind gate.
        env_w = os.environ.get("LAH_ROUTING_COST_WEIGHT")
        if env_w not in (None, ""):
            cost_weight = float(env_w)
        elif routing_cost_weight is not None:
            cost_weight = float(routing_cost_weight)
        else:
            cost_weight = float(latency_weight)
        self.latency_weight = cost_weight  # historical alias, kept readable
        self.telemetry_prefix = telemetry_prefix
        load_getter = (
            self._make_load_getter(source, telemetry_prefix)
            if hasattr(source, "get") and hasattr(source, "declare_experts")
            else None
        )
        from learning_at_home_tpu.utils.serialization import CODEC_WIRE_RATIO

        # placement/routing co-optimization (ISSUE 16): the swarm's
        # published ``links.<prefix>`` RTT/bw EMAs feed the cost model
        # as a prior for endpoints this process never dialed — the same
        # link data the placement solver scores assignments on
        link_getter = (
            self._make_link_getter(source, telemetry_prefix)
            if load_getter is not None
            else None
        )
        self.cost_model = RoutingCostModel(
            cost_weight,
            load_getter=load_getter,
            load_ttl=alive_ttl,
            codec_ratio=CODEC_WIRE_RATIO.get(self.wire_codec or "", 1.0),
            link_getter=link_getter,
        )
        # hedged replica dispatch (ISSUE 8): once a forward fan-out call
        # to a replicated expert outlives ``hedge_mult × the primary
        # pool's RTT EMA`` (floored at hedge_floor_s), the SAME prepared
        # payload is fired at the backup replica and the first successful
        # reply wins — a dying primary costs one hedge window, not a
        # quorum timeout.  mult ≤ 0 disables hedging entirely; backward
        # fan-outs never hedge (the optimizer step is a side effect — a
        # duplicate would apply the same gradients twice).
        if hedge_mult is None:
            try:
                hedge_mult = float(os.environ.get("LAH_HEDGE_MULT", "3"))
            except ValueError:
                hedge_mult = 3.0
        if hedge_floor_s is None:
            try:
                hedge_floor_s = float(
                    os.environ.get("LAH_HEDGE_MIN_S", "0.05")
                )
            except ValueError:
                hedge_floor_s = 0.05
        self.hedge_mult = hedge_mult
        self.hedge_floor_s = hedge_floor_s
        # hedge counters are owned by the lah-client LOOP thread (armed
        # and resolved inside the fan-out coroutine); scrape readers take
        # plain int snapshots — no lock on either side
        self.hedge_fires = 0
        self.hedge_wins = 0
        self.hedges_skipped = 0
        # sole-endpoint rescue (ISSUE 11): non-replicated uids whose only
        # endpoint hard-failed mid-record-TTL, re-resolved via a
        # cache-bypassing alive lookup (same loop-thread ownership)
        self.fresh_retries = 0
        self.fresh_retry_wins = 0
        # replica observability: uid → replica count from the latest
        # alive-set resolution (host-thread writes, copy-on-read scrapes)
        self._replica_counts: dict[str, int] = {}
        self.source = source
        # alive_swr: serve a stale alive set while a background task
        # refreshes it (CachedAliveSet; None → LAH_ALIVE_SWR env) — under
        # churn the discovery lookup can stall behind dead DHT peers and
        # must not block the dispatch path (ISSUE 9)
        self.alive_cache = CachedAliveSet(
            source, uid_prefix, ttl=alive_ttl, swr=alive_swr
        )
        self._sessions: OrderedDict[int, dict] = OrderedDict()
        self._sessions_lock = sanitizer.lock("moe.sessions")
        self.max_sessions = max_sessions
        self._grid_offsets = np.concatenate(
            [[0], np.cumsum(self.grid_size)[:-1]]
        ).astype(np.int32)
        self._dispatch = self._build_dispatch()
        # future-based dispatch (ISSUE 7): tickets for fired-but-unjoined
        # fan-outs, keyed by the handle the fire op returned.  Bounded
        # like _sessions — an evicted ticket cancels its fan-out.
        self._pending: OrderedDict[int, DispatchFuture] = OrderedDict()
        self._pending_bwd: OrderedDict[int, DispatchFuture] = OrderedDict()
        self._fire_op, self._join_op = self._build_async_ops()
        # overlap telemetry: time-weighted accumulators behind
        # lah_client_overlap_fraction (0 in the serial regime)
        self.inflight_seconds = 0.0
        self.join_blocked_seconds = 0.0
        self.inflight_dispatches = 0  # gauge: fired, not yet joined
        # dispatch latency telemetry (north-star: dispatch p50); bounded so
        # long runs don't grow memory
        self.dispatch_times: deque[float] = deque(maxlen=10_000)
        self.dispatches = 0  # cumulative (deques above are windows)
        # per-dispatch selected-uid sets (bounded like dispatch_times)
        self.selection_log: deque[frozenset] = deque(maxlen=10_000)
        # co-activation graph (ISSUE 16): bounded undirected pair counts
        # accumulated at the gate — which experts this trainer fires
        # TOGETHER.  Host-thread-owned plain dict (k_best is small, so a
        # dispatch adds at most k·(k-1)/2 increments); scrape readers
        # copy-with-retry like the deques.  The cap keeps a pathological
        # gate from growing the table unboundedly: increments to new
        # pairs past it are counted as dropped, existing pairs keep
        # counting.
        self.coact_counts: dict[str, int] = {}
        self.coact_dispatches = 0
        self.coact_pairs_dropped = 0
        # per-sample quorum telemetry: samples whose reply count fell below
        # k_min (forward) / backward_k_min (backward) and were masked out
        self.samples_total = 0
        self.samples_dropped = 0
        self.backward_samples_dropped = 0
        # backward-RPC ledger (guarded by _sessions_lock: pipelined
        # trainers run _host_backward concurrently).  ``sent`` counts
        # dispatched grad batches, ``ok`` the replies that came back.
        # The invariant servers' summed ``update_count`` obeys is
        # updates ≤ sent — NOT ≤ ok: a post-quorum straggler cancelled
        # client-side still executes (and updates) server-side, and a
        # task pool may merge concurrent trainers' tasks into one padded
        # batch = one optimizer step.
        self.backward_rpcs_sent = 0
        self.backward_rpcs_ok = 0
        # client hot-path pipeline telemetry (PR 2): host-side serialize
        # time vs loop round-trip wait per dispatch, bytes handed to the
        # wire, and the duplicated wire-encoding the pack-once fan-out
        # avoided (per-call packing downcasts each sample's rows once PER
        # selected expert; pack-once downcasts the batch once)
        self.pack_times: deque[float] = deque(maxlen=10_000)
        self.wait_times: deque[float] = deque(maxlen=10_000)
        self.pack_bytes = 0
        self.pack_bytes_saved = 0
        # always-on headline metrics (ISSUE 4): expose this layer's
        # counters through the process registry via a scrape-time
        # collector — zero hot-path cost, pruned automatically once the
        # MoE is garbage-collected (the weakref returns None)
        import weakref

        from learning_at_home_tpu.utils.metrics import registry as _registry

        ref = weakref.ref(self)

        def _collect():
            moe = ref()
            return None if moe is None else moe._headline_metrics()

        _registry.register_collector(f"moe-{id(self)}", _collect)
        # quiesce-point audit (sanitizer-gated, weakly held): when the
        # client claims idle (reset_client_rpc), every fired dispatch
        # must have been joined or cancelled — a non-zero gauge there is
        # a leaked fan-out holding server-side sessions
        sanitizer.register_quiesce_audit(
            f"client.moe.{id(self):x}", self._quiesce_audit
        )

    def _quiesce_audit(self) -> list:
        leaks = []
        if self.inflight_dispatches:
            leaks.append(
                f"inflight_dispatches gauge is {self.inflight_dispatches} "
                "at client quiesce — fired fan-out never joined/cancelled"
            )
        with self._sessions_lock:
            pending = len(self._pending) + len(self._pending_bwd)
        if pending:
            leaks.append(
                f"{pending} unjoined dispatch ticket(s) at client quiesce"
            )
        return leaks

    @staticmethod
    def _make_load_getter(source, prefix: str):
        """TTL-refreshed ``host:port`` → load-record map from the DHT's
        ``load.<prefix>`` heartbeats (utils/telemetry.py).  Called by the
        cost model on the dispatching HOST thread at most once per TTL
        window — one bounded control-plane loop round-trip, mirroring the
        alive-set cache's refresh discipline."""

        def _get() -> dict:
            from learning_at_home_tpu.utils.telemetry import (
                load_key,
                parse_load_value,
            )

            records = client_loop().run(source.get(load_key(prefix)))
            out = {}
            for subkey, entry in records.items():
                value = entry[0] if isinstance(entry, (tuple, list)) else entry
                parsed = parse_load_value(value)
                if isinstance(subkey, str) and parsed is not None:
                    out[subkey] = parsed
            return out

        return _get

    @staticmethod
    def _make_link_getter(source, prefix: str):
        """TTL-refreshed ``host:port`` → ``{"rtt_s", "bw_bps"}`` map from
        the swarm's ``links.<prefix>`` heartbeats: every publishing
        peer's view of each destination, aggregated per destination by
        MEDIAN rtt (robust to one peer's bad path) and median measured
        bandwidth.  Same refresh discipline as the load getter."""

        def _get() -> dict:
            from learning_at_home_tpu.utils.telemetry import (
                links_key,
                parse_links_value,
            )

            records = client_loop().run(source.get(links_key(prefix)))
            rtts: dict[str, list] = {}
            bws: dict[str, list] = {}
            for _subkey, entry in records.items():
                value = entry[0] if isinstance(entry, (tuple, list)) else entry
                parsed = parse_links_value(value)
                if parsed is None:
                    continue
                for dst, ent in parsed.items():
                    rtts.setdefault(dst, []).append(ent["rtt_s"])
                    if ent["bw_bps"] is not None:
                        bws.setdefault(dst, []).append(ent["bw_bps"])
            out = {}
            for dst, vals in rtts.items():
                out[dst] = {
                    "rtt_s": float(np.median(vals)),
                    "bw_bps": (
                        float(np.median(bws[dst])) if dst in bws else None
                    ),
                }
            return out

        return _get

    # ---- gate parameters ----

    def init_gate_params(self, rng: jax.Array) -> dict:
        keys = jax.random.split(rng, self.n_dims)
        scale = 1.0 / np.sqrt(self.in_features)
        return {
            f"w{d}": jax.random.normal(
                keys[d], (self.in_features, g), self.compute_dtype
            )
            * scale
            for d, g in enumerate(self.grid_size)
        }

    # ---- the public call: gating in-graph, dispatch via host callback ----

    def gate_logits(self, gate_params: dict, x):
        """Concatenated per-dimension gate logits [B, sum(grid)] — THE
        gating math, shared by :meth:`__call__`, the fire half and the
        gateway decode hooks (swarm_decoder / coalescer) so expert
        selection cannot drift between training and serving paths."""
        logits = [x @ gate_params[f"w{d}"] for d in range(self.n_dims)]
        return jnp.concatenate(logits, axis=-1)

    def __call__(self, x, gate_params: dict):
        logits_concat = self.gate_logits(gate_params, x)  # [B, sum(grid)]
        y, idx, mask = self._dispatch(x, logits_concat)
        return self._combine(y, idx, mask, logits_concat)

    def _combine(self, y, idx, mask, logits_concat):
        """Gate-weighted mixture of the dispatch replies — the in-graph,
        differentiable second half shared by :meth:`__call__` and the
        fire/join path (identical ops, so the two paths stay bitwise
        comparable)."""
        # gather each chosen expert's score from the (differentiable) logits
        scores = jnp.zeros(mask.shape, logits_concat.dtype)
        for d in range(self.n_dims):
            flat_idx = idx[:, :, d] + self._grid_offsets[d]
            scores = scores + jnp.take_along_axis(logits_concat, flat_idx, axis=1)
        # finite mask value (not -inf, and dtype-aware so fp16 doesn't
        # overflow it to -inf): a fully-masked row — a sample whose quorum
        # failed and was dropped — must yield zero weights, not NaN
        big_neg = jnp.asarray(jnp.finfo(scores.dtype).min / 2, scores.dtype)
        scores = jnp.where(mask, scores, big_neg)
        weights = jax.nn.softmax(scores, axis=-1)
        weights = jnp.where(mask, weights, 0.0)
        return jnp.einsum("bk,bkd->bd", weights.astype(y.dtype), y)

    def preview_expert_sets(self, logits_concat) -> list:
        """Per-row frozensets of the expert uids a dispatch of these gate
        logits WOULD select — the gateway's coalescing key (gateway/
        coalesce.py groups streams whose sets overlap so one pack-once
        dispatch serves many of them).

        Grid routing only (``routing="beam"`` resolves its alive set per
        fire and has no cacheable preview).  The preview selects with
        ``bias=None``: exact at routing cost weight 0 (bias is None on the
        real dispatch too) and a grouping heuristic otherwise — grouping
        never affects correctness because each group's dispatch reruns its
        own biased selection over its own rows."""
        if self.routing == "beam":
            raise MoEDispatchError(
                "preview_expert_sets requires grid routing (beam resolves "
                "its alive set per dispatch)"
            )
        logits_concat = np.asarray(logits_concat)
        logits = [
            logits_concat[:, off : off + g]
            for off, g in zip(self._grid_offsets, self.grid_size)
        ]
        alive = self.alive_cache.peek_fresh()
        if alive is None:
            alive = client_loop().run(self.alive_cache.get())
        alive_uids = sorted(
            filter_valid_uids(alive, self.uid_prefix, self.grid_size)
        )
        if not alive_uids:
            raise MoEDispatchError(
                f"no alive experts under prefix {self.uid_prefix!r}"
            )
        sel, _ = select_top_k(logits, alive_uids, self.k_best, bias=None)
        return [frozenset(alive_uids[e] for e in row) for row in sel]

    # ---- fire/join: the overlapped two-phase form of __call__ ----

    def fire(self, x, gate_params: dict):
        """Phase one of an overlapped dispatch: in-graph gating, then the
        fire op — selection + payload serialization on the host thread
        and a NON-BLOCKING fan-out submit to the client loop.  Returns
        ``(token, handle, logits_concat)`` for :meth:`join`; everything
        the caller computes between fire and join overlaps the in-flight
        expert RPCs (the ScMoE-style scheduling the overlapped swarm
        step exploits — models/transformer_swarm.py)."""
        logits_concat = self.gate_logits(gate_params, x)
        token, handle = self._fire_op(x, logits_concat)
        return token, handle, logits_concat

    def join(self, token, handle, logits_concat):
        """Phase two: block until the fired fan-out resolves (the single
        join point), then mix replies with gate weights — the same math
        as :meth:`__call__`.  ``fire(...)`` immediately followed by
        ``join(...)`` is the serial schedule and produces bitwise the
        same values as deferring the join."""
        y, idx, mask = self._join_op(token, handle)
        return self._combine(y, idx, mask, logits_concat)

    # ---- custom-vjp dispatch crossing the network ----

    def _build_dispatch(self):
        def specs(x_shape, x_dtype):
            b = x_shape[0]
            return (
                jax.ShapeDtypeStruct((b, self.k_best, x_shape[1]), x_dtype),  # y
                jax.ShapeDtypeStruct((b, self.k_best, self.n_dims), jnp.int32),
                jax.ShapeDtypeStruct((b, self.k_best), jnp.bool_),
                jax.ShapeDtypeStruct((), jnp.int32),  # session id
            )

        @jax.custom_vjp
        def dispatch(x, logits_concat):
            # no-grad primal path (inference): no backward will come, so do
            # NOT store a session — orphans would evict live training sessions
            y, idx, mask, _ = io_callback(
                lambda x, lc: self._host_forward(x, lc, store_session=False),
                specs(x.shape, x.dtype),
                x,
                logits_concat,
            )
            return y, idx, mask

        def fwd(x, logits_concat):
            y, idx, mask, cid = io_callback(
                lambda x, lc: self._host_forward(x, lc, store_session=True),
                specs(x.shape, x.dtype),
                x,
                logits_concat,
            )
            return (y, idx, mask), (cid, x, logits_concat)

        def bwd(residuals, cotangents):
            cid, x, logits_concat = residuals
            gy = cotangents[0]  # [B, k, D]; idx/mask are int/bool: no cotangent
            gx = io_callback(
                self._host_backward,
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                cid,
                gy,
            )
            return gx, jnp.zeros_like(logits_concat)

        dispatch.defvjp(fwd, bwd)
        return dispatch

    # ---- host side: forward fan-out with k-of-n quorum ----

    def _host_forward(self, x, logits_concat, store_session: bool = True):
        # distributed tracing: one compact trace id per dispatch, minted
        # ONLY while profiling is enabled (the disabled path carries no
        # extra meta and records nothing).  It rides in every RPC's meta,
        # is stamped onto the client pack/rpc spans here and the server's
        # stack/dispatch/materialize spans there, and the session carries
        # it into backward — one forward+backward, one joinable trace.
        trace = new_trace_id() if timeline.enabled else None
        with timeline.span(f"moe.dispatch.{self.uid_prefix}", trace=trace):
            return self._host_forward_impl(
                x, logits_concat, store_session, trace
            )

    def _host_forward_impl(
        self, x, logits_concat, store_session: bool = True, trace=None
    ):
        # serial schedule = fire immediately followed by join; the
        # overlapped swarm step calls the same two halves with trunk
        # compute in between, so the paths cannot drift apart
        return self.dispatch_async(
            x, logits_concat, store_session=store_session, trace=trace
        ).join()

    def _join_timeout(self, kind: str):
        """Hard join deadline for the future-based path (None = the
        legacy arm's unbounded watchdog-guarded wait).  Every RPC inside
        the fan-out is already bounded by rpc_timeout and the quorum
        grace, so a fan-out that outlives their sum plus the grace slack
        is stalled, not slow."""
        from learning_at_home_tpu.client.rpc import JOIN_GRACE_S

        if dispatch_mode() == "legacy":
            return None
        base = self.forward_timeout if kind == "forward" else self.backward_timeout
        return base + self.timeout_after_k_min + JOIN_GRACE_S

    @sanitizer.runs_on("host", site="moe.join_exit")
    def _make_join_exit(self, trace):
        """on_join_exit hook: overlap accounting + the in-flight gauge,
        run in join's finally on the joining host thread — it fires even
        when the join times out or the fan-out raised."""

        def _exit(fut: DispatchFuture) -> None:
            import time as _time

            if fut.cancelled:
                # ticket eviction: nothing was joined — drain the gauge
                # but record no overlap evidence (a never-joined window
                # is not hidden latency)
                with self._sessions_lock:
                    self.inflight_dispatches -= 1
                return
            blocked = fut.blocked_s
            inflight = fut.inflight_s()
            self.wait_times.append(blocked)
            timeline.record(
                "client.dispatch.join",
                _time.monotonic() - blocked, blocked, trace=trace,
            )
            with self._sessions_lock:
                self.inflight_dispatches -= 1
                self.inflight_seconds += inflight
                self.join_blocked_seconds += min(blocked, inflight)

        return _exit

    @sanitizer.runs_on("host", site="moe.dispatch_async")
    def dispatch_async(
        self, x, logits_concat, *, store_session: bool = True, trace=None,
        session_id: Optional[int] = None,
    ) -> DispatchFuture:
        """FIRE half of a forward dispatch: alive-set lookup, per-sample
        top-k selection, payload serialization (pipelined mode: pack-once
        on this host thread) and a non-blocking submit of the quorum
        fan-out to the client loop.  Returns a joinable
        :class:`DispatchFuture` immediately — this path never waits for
        expert replies.  Loop touches are control-plane only: grid
        routing pays the once-per-TTL-window alive-set refresh;
        ``routing="beam"`` pays a bounded DHT beam-search round-trip on
        EVERY fire (prefix records are per-logit-row, not cacheable as
        one set) — on real WAN RTTs that lookup shrinks the overlap win
        by its latency, so latency-critical overlapped deployments
        should prefer grid routing or a DHT cache (ROADMAP item 4).

        ``session_id`` pins the backward-session key (the jax-level
        fire/join pair uses the fire handle, so fire's residuals can
        find the backward the join fired)."""
        import time as _time

        t0 = _time.monotonic()
        x = np.asarray(x)
        logits_concat = np.asarray(logits_concat)
        batch = x.shape[0]
        with timeline.span("client.dispatch.fire", trace=trace):
            logits = [
                logits_concat[:, off : off + g]
                for off, g in zip(self._grid_offsets, self.grid_size)
            ]
            if self.routing == "beam":
                # prefix beam search: fetch only the records for each
                # sample's best first-dimension rows — scales to
                # 4096-expert grids without ever reading the full
                # top-level record.  Control-plane: bounded DHT reads,
                # not expert-reply waits.
                alive = client_loop().run(
                    beam_search_alive(
                        self.source,
                        self.uid_prefix,
                        logits,
                        self.grid_size,
                        self.beam_size,
                    )
                )
                alive_uids = sorted(alive)
            else:
                # sync TTL-cache fast path: the fire half must not
                # round-trip the loop per dispatch — only the expired
                # window pays the (bounded, control-plane) refresh
                alive = self.alive_cache.peek_fresh()
                if alive is None:
                    alive = client_loop().run(self.alive_cache.get())
                alive_uids = sorted(
                    filter_valid_uids(alive, self.uid_prefix, self.grid_size)
                )
            # replica-aware resolution: each uid's alive-map value may be
            # a single endpoint (the historical form) or a DHT-advertised
            # replica SET; the cost model orders every set cheapest-first,
            # so entry 0 is the least-loaded primary and entry 1 the
            # hedge backup
            replica_sets: dict[str, ReplicaSet] = {
                uid: self.cost_model.order_replicas(
                    as_replica_set(alive[uid]), nbytes=x.nbytes
                )
                for uid in alive_uids
            }
            alive_uids = [uid for uid in alive_uids if replica_sets[uid]]
            if not alive_uids:
                raise MoEDispatchError(
                    f"no alive experts under prefix {self.uid_prefix!r}"
                )
            self._replica_counts = {
                uid: len(replica_sets[uid]) for uid in alive_uids
            }
            # latency-aware selection bias (None at weight 0 → bitwise
            # the blind gate); combine weights stay clean-gate
            bias = self.cost_model.bias(
                alive_uids, replica_sets, nbytes=x.nbytes
            )
            sel, coords = select_top_k(
                logits, alive_uids, self.k_best, bias=bias
            )  # [B, k']
            k_eff = sel.shape[1]
            # which experts this dispatch actually selected — the observable
            # the latency-aware-routing tests assert on (mechanism, not clock)
            chosen = sorted({alive_uids[e] for e in np.unique(sel)})
            self.selection_log.append(frozenset(chosen))
            # co-activation accumulation (ISSUE 16): every pair selected
            # together this dispatch feeds the placement solver's graph
            self.coact_dispatches += 1
            for i in range(len(chosen)):
                for j in range(i + 1, len(chosen)):
                    key = f"{chosen[i]}|{chosen[j]}"
                    n = self.coact_counts.get(key)
                    if n is not None:
                        self.coact_counts[key] = n + 1
                    elif len(self.coact_counts) < COACT_MAX_PAIRS:
                        self.coact_counts[key] = 1
                    else:
                        self.coact_pairs_dropped += 1

            # group rows by chosen expert: expert -> (rows, slots)
            jobs: dict[int, tuple[np.ndarray, np.ndarray]] = {}
            for j in range(k_eff):
                for e in np.unique(sel[:, j]):
                    rows = np.nonzero(sel[:, j] == e)[0]
                    if e in jobs:
                        jobs[e] = (
                            np.concatenate([jobs[e][0], rows]),
                            np.concatenate([jobs[e][1], np.full(len(rows), j)]),
                        )
                    else:
                        jobs[e] = (rows, np.full(len(rows), j))

            # least-loaded replica pick: the job targets the cheapest
            # replica; the second-cheapest (if any) rides along as the
            # hedge backup for the fan-out's hedged fallback
            backups: dict[str, Optional[tuple]] = {
                alive_uids[e]: (
                    replica_sets[alive_uids[e]][1]
                    if len(replica_sets[alive_uids[e]]) > 1 else None
                )
                for e in jobs
            }
            prepared = None
            if dispatch_mode() == "pipelined":
                # payload slot left empty: _prepare_payloads slices each
                # expert's rows from the ONE wire-cast batch — materializing
                # x[rows] here too would double the hot-path memcpy
                uid_jobs, prepared = self._prepare_payloads(
                    "forward",
                    {
                        alive_uids[e]: (
                            replica_sets[alive_uids[e]][0], None, rows, slots
                        )
                        for e, (rows, slots) in jobs.items()
                    },
                    x_full=x,
                    trace=trace,
                )
            else:
                uid_jobs = {
                    alive_uids[e]: (
                        replica_sets[alive_uids[e]][0], x[rows], rows, slots
                    )
                    for e, (rows, slots) in jobs.items()
                }

        coro = self._quorum_fanout(
            msg_type="forward",
            jobs=uid_jobs,
            batch=batch,
            quorum=self.k_min,
            rpc_timeout=self.forward_timeout,
            prepared=prepared,
            trace=trace,
            # hedging is a pipelined-path behavior: the legacy arm stays
            # the exact pre-replica A/B baseline
            backups=backups if dispatch_mode() == "pipelined" else None,
        )

        fut_box: list = []

        def finalize(results):
            # dispatch latency ends when the FAN-OUT resolved (stamped on
            # the loop thread), not when the caller got around to joining:
            # under the overlapped schedule now-minus-t0 would fold the
            # deliberately hidden trunk compute into the north-star
            # dispatch p50 and make overlap read as a latency regression
            t_end = fut_box[0].completed_at if fut_box else None
            return self._finalize_forward(
                results, x=x, coords=coords, sel=sel, batch=batch,
                store_session=store_session, session_id=session_id,
                trace=trace, t0=t0, t_end=t_end,
            )

        fut = DispatchFuture(
            "forward", coro, finalize,
            join_timeout=self._join_timeout("forward"),
            watchdog_rtt=(
                self._slowest_rtt(uid_jobs)
                if dispatch_mode() == "legacy" else None
            ),
            what=f"forward dispatch ({self.uid_prefix}, {batch} rows)",
            on_join_exit=self._make_join_exit(trace),
        )
        fut_box.append(fut)
        with self._sessions_lock:
            self.inflight_dispatches += 1
        return fut

    @sanitizer.runs_on("host", site="moe._finalize_forward")
    def _finalize_forward(
        self, results, *, x, coords, sel, batch, store_session, session_id,
        trace, t0, t_end=None,
    ):
        """JOIN-side accumulation of a forward fan-out's replies into the
        (y, idx, mask, cid) quadruple — quorum accounting, per-sample
        degradation, and the backward-session store.  Runs on the joining
        host thread via DispatchFuture's finalizer."""
        import time as _time

        k_eff = sel.shape[1]
        y = np.zeros((batch, self.k_best, x.shape[1]), x.dtype)
        mask = np.zeros((batch, self.k_best), bool)
        idx = np.zeros((batch, self.k_best, self.n_dims), np.int32)
        idx[:, :k_eff] = coords[sel]
        session: dict[str, tuple] = {}
        for uid, (endpoint, x_rows, rows, slots, reply) in results.items():
            if reply is None:
                continue
            arr = np.asarray(reply[0], x.dtype)
            if arr.shape != (len(rows), x.shape[1]):
                # wrong-arity reply from a buggy/malicious expert: treat it
                # exactly like a failed RPC, never slice-and-accept
                logger.warning(
                    "expert %s returned shape %s, expected %s — discarding",
                    uid, arr.shape, (len(rows), x.shape[1]),
                )
                continue
            y[rows, slots] = arr
            mask[rows, slots] = True
            session[uid] = (endpoint, x_rows, rows, slots)

        per_sample_ok = mask.sum(axis=1)
        dropped = per_sample_ok < self.k_min
        self.samples_total += batch
        if dropped.any():
            if dropped.all():
                raise MoEDispatchError(
                    f"total dispatch failure: no sample of {batch} reached "
                    f"k_min={self.k_min} expert replies"
                )
            # per-sample degradation: below-quorum samples contribute zero
            # (their mask rows go all-False → zero mixture weights) and are
            # counted, but the step survives
            n_drop = int(dropped.sum())
            self.samples_dropped += n_drop
            mask[dropped] = False
            y[dropped] = 0.0
            logger.warning(
                "quorum miss: %d of %d samples below k_min=%d — masked to "
                "zero contribution", n_drop, batch, self.k_min,
            )

        cid = -1
        if store_session:
            cid = session_id if session_id is not None else next(
                self._call_counter
            )
            with self._sessions_lock:
                # the forward-dropped mask rides along so the backward path
                # doesn't re-count those samples as backward failures; the
                # trace id rides too — backward joins the forward's trace
                self._sessions[cid] = (session, dropped.copy(), trace)
                while len(self._sessions) > self.max_sessions:
                    self._sessions.popitem(last=False)
        dispatch_s = (t_end if t_end is not None else _time.monotonic()) - t0
        self.dispatch_times.append(dispatch_s)
        # sketch-backed registry histogram (ISSUE 19): feeds TRUE fleet
        # dispatch-latency quantiles via mergeable sketches in telemetry,
        # alongside the deque-based single-process p50/p99 above
        from learning_at_home_tpu.utils.metrics import registry as _registry

        _registry.histogram(
            "lah_client_dispatch_seconds",
            "end-to-end dispatch latency (fire → join done)",
        ).observe(dispatch_s)
        self.dispatches += 1
        return y, idx, mask, np.int32(cid)

    @staticmethod
    def _slowest_rtt(uid_jobs: dict):
        """Worst involved pool's RTT EMA (the dispatch-wait watchdog's
        scale); None when nothing has been measured yet."""
        registry = pool_registry()
        worst = None
        for job in uid_jobs.values():
            pool = registry.peek(job[0])
            if pool is not None and pool.rtt_ema is not None:
                worst = (
                    pool.rtt_ema if worst is None
                    else max(worst, pool.rtt_ema)
                )
        return worst

    # ---- host-thread serialization (the off-loop half of the pipeline) ----

    def _base_codec(self) -> str:
        from learning_at_home_tpu.utils.serialization import _DTYPE_TO_CODEC

        return _DTYPE_TO_CODEC.get(self.wire_dtype, "none")

    def _select_codec(self, kind: str, endpoint, nbytes: int) -> str:
        """Per-pool wire codec for one fan-out request (docs/PROTOCOL.md
        escalation policy).  Override (LAH_WIRE_CODEC / constructor) wins;
        otherwise the adaptive selector escalates none→bf16→8-bit from
        the pool's RTT EMA + measured bytes/sec.  Quantized codecs are
        only offered to pools whose hello echoed the ``codec`` feature —
        v1 peers, old builds and not-yet-negotiated pools fall back to
        the wire_dtype base."""
        from learning_at_home_tpu.utils.serialization import (
            QUANTIZED_CODECS,
            select_wire_codec,
        )

        base = self._base_codec()
        pool = pool_registry().peek(endpoint)
        if self.wire_codec is not None:
            codec = self.wire_codec
        else:
            codec = select_wire_codec(
                kind, nbytes,
                pool.rtt_ema if pool is not None else None,
                pool.bw_ema if pool is not None else None,
                base=base,
            )
        if codec in QUANTIZED_CODECS and (
            pool is None or not pool.supports("codec")
        ):
            return base
        return codec

    @staticmethod
    def _wire_meta_for(codec: str, headers: list):
        """meta ``{"wire": ...}`` value for one request's payload."""
        from learning_at_home_tpu.utils.serialization import (
            _CODEC_TO_DTYPE,
            QUANTIZED_CODECS,
        )

        if codec in QUANTIZED_CODECS or any(
            isinstance(h, dict) and h.get("c") in QUANTIZED_CODECS
            for h in headers
        ):
            return {"c": codec, "h": headers}
        return _CODEC_TO_DTYPE.get(codec)  # legacy string, or None for raw

    @sanitizer.runs_on("host", site="moe._prepare_payloads")
    def _prepare_payloads(self, kind: str, uid_jobs: dict,
                          x_full=None, gy_full=None,
                          trace=None) -> tuple[dict, dict]:
        """Serialize the fan-out's payloads ON THIS host thread (the
        caller is already blocked inside io_callback) so the client event
        loop only writes ready buffers — the client-side mirror of PR 1's
        no-work-on-the-loop rule.

        Pack-once contract: the wire encode (downcast OR 8-bit quantize —
        ISSUE 5) runs once over the FULL batch (``x`` forward, ``gy``
        backward) per codec actually selected, and every expert's payload
        — including its per-tensor quantization header — is a slice of
        that one encoding (blockq8 blocks never cross the trailing axis,
        so row gathers keep block alignment); per-call packing would
        re-encode each sample's rows once per selected expert (k× the
        work).  The prepared blobs are immutable and shared across the
        merged ``multi`` call and any disaggregated per-expert retry.
        Backward reuses the forward's already-encoded rows stored in the
        session — identical bytes, so the server differentiates at
        exactly the point it evaluated — and encodes only the gradients
        (``blockq8`` when quantizing: gradient-safe per-block stats).

        The codec is chosen PER POOL (one codec per endpoint per
        direction, so a merged ``multi`` request stays one wire form);
        swarms with heterogeneous link speeds may encode the batch under
        more than one codec, each once.

        Returns ``(jobs, prepared)``: jobs with payload slots replaced by
        the wire-encoded arrays (sessions then store wire rows — wrapped
        with their headers for quantized codecs), and uid →
        ``(WireTensors, wire_meta)``.  ``pack_bytes_saved`` accumulates
        the wire-encode bytes avoided vs per-call packing."""
        import time as _time

        from learning_at_home_tpu.utils.serialization import (
            EncodedBatch,
            LazyDecode,
            QUANTIZED_CODECS,
            WireTensors,
            is_float_dtype,
            wire_cast,
        )

        t0 = _time.monotonic()
        wd = self.wire_dtype
        out_jobs: dict = {}
        prepared: dict = {}
        saved = 0
        itemsize = 4  # selection estimates assume f32 payloads

        # one codec per endpoint per direction: estimate each pool's
        # total payload and ask the selector once
        ep_bytes: dict = {}
        for uid, job in uid_jobs.items():
            rows = job[2]
            feat = (
                int(np.prod(x_full.shape[1:])) if kind == "forward"
                else int(gy_full.shape[-1]) * 2
            )
            ep_bytes[job[0]] = ep_bytes.get(job[0], 0) + len(rows) * feat * itemsize
        ep_codec = {
            ep: self._select_codec(kind, ep, nb) for ep, nb in ep_bytes.items()
        }

        enc_cache: dict = {}

        def batch_enc(arr, codec, key) -> EncodedBatch:
            eb = enc_cache.get((key, codec))
            if eb is None:
                eb = enc_cache[(key, codec)] = EncodedBatch.encode(arr, codec)
            return eb

        dup: dict = {}
        if kind == "forward":
            for uid, (ep, _x_rows, rows, slots) in uid_jobs.items():
                codec = ep_codec[ep]
                eb = batch_enc(x_full, codec, "x")
                x_pay, h = eb.take(rows)
                dup[codec] = dup.get(codec, 0) + x_pay.nbytes
                # the session stores exactly the bytes the server saw, so
                # backward can resend them verbatim
                stored = (
                    LazyDecode(x_pay, h)
                    if isinstance(h, dict) and h.get("c") in QUANTIZED_CODECS
                    else x_pay
                )
                out_jobs[uid] = (ep, stored, rows, slots)
                prepared[uid] = (
                    WireTensors.prepare([x_pay]),
                    self._wire_meta_for(codec, [h]),
                )
                self.codec_counts[codec] = self.codec_counts.get(codec, 0) + 1
                timeline.count(f"client.pack.codec.{codec}")
                timeline.count(f"client.pack.codec.{codec}.bytes", x_pay.nbytes)
            for codec, nbytes_dup in dup.items():
                if codec != "none":
                    saved += max(0, nbytes_dup - enc_cache[("x", codec)].wire.nbytes)
        else:
            for uid, (ep, x_stored, rows, slots) in uid_jobs.items():
                codec = ep_codec[ep]
                eb = batch_enc(gy_full, codec, "gy")
                g_pay, gh = eb.take((rows, slots))
                # input half: resend the forward's exact wire bytes
                if isinstance(x_stored, LazyDecode):
                    pool = pool_registry().peek(ep)
                    if pool is not None and pool.supports("codec"):
                        x_pay, xh = x_stored.wire, x_stored.header
                        saved += x_stored.wire_nbytes  # re-encode avoided
                    else:  # peer demoted mid-session: decode locally
                        x_pay, xh = np.asarray(x_stored, np.float32), None
                        if codec in ("bf16", "f16"):
                            from learning_at_home_tpu.utils.serialization import (  # noqa: E501
                                _CODEC_TO_DTYPE,
                            )

                            # downcast request: all floats must match
                            x_pay = wire_cast(
                                [x_pay], _CODEC_TO_DTYPE[codec]
                            )[0]
                            xh = {"c": codec}
                else:
                    from learning_at_home_tpu.utils.serialization import (
                        _CODEC_TO_DTYPE,
                        _DTYPE_TO_CODEC,
                    )

                    x_pay = np.asarray(x_stored)
                    xh = None
                    if is_float_dtype(x_pay.dtype) and x_pay.dtype != np.dtype(
                        np.float32
                    ):
                        # session rows already downcast by the forward
                        name = _DTYPE_TO_CODEC.get(x_pay.dtype.name)
                        if codec in ("bf16", "f16") and name == codec:
                            saved += x_pay.nbytes  # reuse, same form
                            xh = {"c": codec}
                        elif name is not None and codec in QUANTIZED_CODECS:
                            # quantized request: the dict form declares
                            # the downcast per tensor — reuse the bytes
                            saved += x_pay.nbytes
                            xh = {"c": name}
                        else:
                            # form mismatch (adaptive drift between
                            # directions, or a legacy-mode forward):
                            # send exact f32 rather than violate the
                            # all-floats-compressed legacy contract
                            x_pay = np.asarray(x_pay, np.float32)
                    elif (
                        is_float_dtype(x_pay.dtype)
                        and codec in ("bf16", "f16")
                    ):
                        # f32 session rows under a downcast request: the
                        # legacy string form compresses ALL floats, x too
                        x_pay = wire_cast(
                            [x_pay], _CODEC_TO_DTYPE[codec]
                        )[0]
                        xh = {"c": codec}
                wire_meta = self._wire_meta_for(codec, [xh, gh])
                if not isinstance(wire_meta, dict):
                    xh = None  # legacy string form: headers don't travel
                out_jobs[uid] = (ep, x_pay, rows, slots, g_pay)
                prepared[uid] = (
                    WireTensors.prepare([x_pay, g_pay]), wire_meta
                )
                self.codec_counts[codec] = self.codec_counts.get(codec, 0) + 1
                timeline.count(f"client.pack.codec.{codec}")
                timeline.count(
                    f"client.pack.codec.{codec}.bytes",
                    x_pay.nbytes + g_pay.nbytes,
                )
        dt = _time.monotonic() - t0
        nbytes = sum(p[0].nbytes for p in prepared.values())
        self.pack_times.append(dt)
        self.pack_bytes += nbytes
        self.pack_bytes_saved += saved
        timeline.record(f"client.pack.{kind}", t0, dt, trace=trace)
        timeline.count("client.pack.bytes", nbytes)
        timeline.count("client.pack_once.bytes_saved", saved)
        return out_jobs, prepared

    def _headline_metrics(self) -> dict:
        """The ~always-on headline counters this layer contributes to the
        unified metrics registry (utils/metrics.py) — plain attribute
        reads plus two scrape-time percentiles, never hot-path work.
        ``dispatch_stats()`` and the Prometheus/JSON endpoints all read
        THIS dict, so the numbers cannot drift apart."""

        def snap(d):
            # scrape threads race the training thread's appends; deque
            # appends are atomic but ITERATION during one raises
            # RuntimeError — retry rather than putting a lock on the
            # per-dispatch hot path just for telemetry reads
            for _ in range(4):
                try:
                    return list(d)
                except RuntimeError:
                    continue
            return []

        def p_ms(d, q):
            arr = np.asarray(snap(d))
            return (
                round(float(np.percentile(arr, q)) * 1e3, 3)
                if arr.size else 0.0
            )

        codec_counts = self._snap_codec_counts()
        # time-weighted overlap: the fraction of all in-flight RPC time
        # this layer's caller hid behind its own compute (0.0 in the
        # serial regime, > 0 once a scheduler defers its joins)
        inflight_s = self.inflight_seconds
        blocked_s = self.join_blocked_seconds
        overlap = (
            max(0.0, min(1.0, 1.0 - blocked_s / inflight_s))
            if inflight_s > 0 else 0.0
        )
        replica_counts = self._snap_replica_counts()
        replicated = sum(1 for n in replica_counts.values() if n > 1)
        return {
            **{
                f"lah_client_wire_codec_payloads_total_codec_{c}": n
                for c, n in codec_counts.items()
            },
            # latency-aware routing + hedged replica dispatch (ISSUE 8)
            "lah_client_routing_bias_applied_total": (
                self.cost_model.bias_applied
            ),
            "lah_client_hedge_fires_total": self.hedge_fires,
            "lah_client_hedge_wins_total": self.hedge_wins,
            "lah_client_hedges_skipped_total": self.hedges_skipped,
            "lah_client_fresh_retries_total": self.fresh_retries,
            "lah_client_fresh_retry_wins_total": self.fresh_retry_wins,
            "lah_client_replicated_experts": replicated,
            "lah_client_replicas_max": max(
                replica_counts.values(), default=0
            ),
            "lah_client_overlap_fraction": round(overlap, 4),
            "lah_client_inflight_dispatches": self.inflight_dispatches,
            "lah_client_inflight_seconds_total": round(inflight_s, 3),
            "lah_client_join_blocked_seconds_total": round(blocked_s, 3),
            "lah_client_dispatches_total": self.dispatches,
            "lah_client_samples_total": self.samples_total,
            "lah_client_samples_dropped_total": self.samples_dropped,
            "lah_client_backward_samples_dropped_total": (
                self.backward_samples_dropped
            ),
            "lah_client_backward_rpcs_sent_total": self.backward_rpcs_sent,
            "lah_client_backward_rpcs_ok_total": self.backward_rpcs_ok,
            "lah_client_pack_bytes_total": self.pack_bytes,
            "lah_client_pack_once_bytes_saved_total": self.pack_bytes_saved,
            "lah_client_dispatch_p50_ms": p_ms(self.dispatch_times, 50),
            "lah_client_dispatch_p99_ms": p_ms(self.dispatch_times, 99),
            "lah_client_pack_p50_ms": p_ms(self.pack_times, 50),
            "lah_client_wait_p50_ms": p_ms(self.wait_times, 50),
            # placement measurement (ISSUE 16): the co-activation graph
            # this gate observed + routing's swarm-link-prior usage
            "lah_placement_coact_pairs": len(self._snap_coact_counts()),
            "lah_placement_coact_dispatches_total": self.coact_dispatches,
            "lah_placement_coact_pairs_dropped_total": (
                self.coact_pairs_dropped
            ),
            "lah_placement_link_fallbacks_total": (
                self.cost_model.link_fallbacks
            ),
        }

    def dispatch_stats(self) -> dict:
        """Client hot-path counters for benchmarks/telemetry: serialize
        vs wait breakdown, bytes on the wire, pack-once savings, and the
        per-pool multiplexed in-flight high-water mark.  Plumbed through
        the same ``_headline_metrics`` dict the registry exports (ISSUE
        4: no more hand-rolled parallel dicts) plus the process-wide
        transport counters from the connection-pool registry."""
        m = self._headline_metrics()

        def nz(v):  # deques empty → None, the historical contract
            return v if v else None

        pools = pool_registry().pools()
        return {
            "pack_p50_ms": nz(m["lah_client_pack_p50_ms"]),
            "wait_p50_ms": nz(m["lah_client_wait_p50_ms"]),
            "pack_bytes": int(m["lah_client_pack_bytes_total"]),
            "pack_once_bytes_saved": int(
                m["lah_client_pack_once_bytes_saved_total"]
            ),
            "dispatches": int(m["lah_client_dispatches_total"]),
            # who is actually overlapping (ISSUE 7): time-weighted hidden
            # fraction of the in-flight RPC windows + the live gauge of
            # fired-but-unjoined dispatches
            "overlap_fraction": m["lah_client_overlap_fraction"],
            "inflight_dispatches": int(m["lah_client_inflight_dispatches"]),
            "bytes_sent": int(sum(p.bytes_sent for p in pools)),
            "bytes_received": int(sum(p.bytes_received for p in pools)),
            "inflight_depth_max": max(
                (p.inflight_max for p in pools), default=0
            ),
            "protocol": "v2" if any(p._proto == 2 for p in pools) else "v1",
            # per-codec payload counts: which wire encoding dispatches
            # actually negotiated+selected (the codec-smoke observable);
            # copy-with-retry — a scrape racing the host thread's first
            # insert of a new codec key must not crash on "dict changed
            # size during iteration"
            "codecs": self._snap_codec_counts(),
            # latency-aware routing + replica/hedge observability
            # (ISSUE 8): what the cost model actually did this run
            "routing": {
                "cost_weight": self.cost_model.weight,
                "bias_applied": int(
                    m["lah_client_routing_bias_applied_total"]
                ),
                "load_refresh_failures": (
                    self.cost_model.load_refresh_failures
                ),
                "hedge_fires": int(m["lah_client_hedge_fires_total"]),
                "hedge_wins": int(m["lah_client_hedge_wins_total"]),
                "hedges_skipped": int(
                    m["lah_client_hedges_skipped_total"]
                ),
                "fresh_retries": int(m["lah_client_fresh_retries_total"]),
                "fresh_retry_wins": int(
                    m["lah_client_fresh_retry_wins_total"]
                ),
                "replicated_experts": int(
                    m["lah_client_replicated_experts"]
                ),
                "replica_counts": self._snap_replica_counts(),
            },
            # placement measurement (ISSUE 16): what the rebalancer's
            # snapshot builder scrapes off this trainer — the observed
            # co-activation graph (top pairs), this process's measured
            # per-destination link EMAs, and the mean payload size the
            # solver turns into transfer-time terms
            "placement": self.placement_stats(),
        }

    def placement_stats(self, top_pairs: int = 64) -> dict:
        """Serializable placement-measurement section: bounded top-N of
        the co-activation pair counts (count-desc then key, so the map
        is deterministic for a given graph), the swarm-wire link
        snapshot from this process's connection pools, and dispatch
        bytes.  Shapes match what ``tools/lah_rebalance.py`` merges into
        the solver snapshot."""
        from learning_at_home_tpu.utils.telemetry import link_snapshot

        coact = self._snap_coact_counts()
        top = dict(
            sorted(coact.items(), key=lambda kv: (-kv[1], kv[0]))
            [:top_pairs]
        )
        dispatches = self.dispatches
        return {
            "coact": top,
            "coact_pairs": len(coact),
            "coact_dispatches": self.coact_dispatches,
            "coact_pairs_dropped": self.coact_pairs_dropped,
            "links": link_snapshot(),
            "link_fallbacks": self.cost_model.link_fallbacks,
            "bytes_per_dispatch": (
                round(self.pack_bytes / dispatches, 1) if dispatches else 0.0
            ),
        }

    def _snap_codec_counts(self) -> dict:
        for _ in range(4):
            try:
                return dict(self.codec_counts)
            except RuntimeError:
                continue
        return {}

    def _snap_coact_counts(self) -> dict:
        # copy-with-retry: scrapes race the host thread's pair inserts
        for _ in range(4):
            try:
                return dict(self.coact_counts)
            except RuntimeError:
                continue
        return {}

    def _snap_replica_counts(self) -> dict:
        # copy-with-retry: the host thread replaces this dict wholesale
        # per dispatch; a scrape racing the swap must never crash
        for _ in range(4):
            try:
                return dict(self._replica_counts)
            except RuntimeError:
                continue
        return {}

    # ---- hedge accounting (owned by the lah-client LOOP thread: armed
    #      and resolved inside the fan-out coroutine — docs/CONCURRENCY.md
    #      invariant 9; no locks, scrapes read plain-int snapshots) ----

    @sanitizer.runs_on("not:lah-runtime", site="moe.hedge_arm")
    def _arm_hedge(self, primary, backup) -> None:
        """Hedge-fire entry point: the primary outlived its RTT-derived
        deadline (or failed) and the backup replica is being dispatched."""
        self.hedge_fires += 1
        timeline.count("client.hedge.fires")
        flight.record(
            "client", "hedge_fire", primary=str(primary), backup=str(backup)
        )
        logger.debug("hedge fired: primary %s → backup %s", primary, backup)

    @sanitizer.runs_on("not:lah-runtime", site="moe.hedge_arm")
    def _hedge_skipped(self, backup) -> None:
        """A due hedge NOT fired: the backup pool cannot accept the
        prepared wire form (codec never negotiated) — counted, never
        silently dropped."""
        self.hedges_skipped += 1
        timeline.count("client.hedge.skipped")

    # ---- host side: backward fan-out to exactly the responders ----

    def _host_backward(self, cid, gy):
        gy = np.asarray(gy)
        with self._sessions_lock:
            entry = self._sessions.pop(int(cid), None)
        if entry is None:
            raise MoEDispatchError(
                f"no dispatch session {int(cid)}: backward without forward, "
                "or session evicted (raise max_sessions?)"
            )
        session, fwd_dropped, trace = entry
        with timeline.span(f"moe.backward.{self.uid_prefix}", trace=trace):
            return self._host_backward_impl(session, fwd_dropped, trace, gy)

    def _host_backward_impl(self, session, fwd_dropped, trace, gy):
        return self.backward_async(session, fwd_dropped, trace, gy).join()

    @sanitizer.runs_on("host", site="moe.backward_async")
    def backward_async(self, session, fwd_dropped, trace, gy) -> DispatchFuture:
        """FIRE half of a backward dispatch: serialize the gradient
        fan-out (reusing the forward's already-encoded session rows) and
        submit it non-blocking — the mirror of :meth:`dispatch_async`,
        so backward trunk compute can overlap the grad RPCs too."""
        batch = gy.shape[0]
        with self._sessions_lock:
            self.backward_rpcs_sent += len(session)
        with timeline.span("client.dispatch.fire", trace=trace):
            prepared = None
            if dispatch_mode() == "pipelined":
                uid_jobs, prepared = self._prepare_payloads(
                    "backward", session, gy_full=gy, trace=trace
                )
            else:
                uid_jobs = {
                    uid: (ep, x_rows, rows, slots, gy[rows, slots])
                    for uid, (ep, x_rows, rows, slots) in session.items()
                }
        coro = self._quorum_fanout(
            msg_type="backward",
            jobs=uid_jobs,
            batch=batch,
            quorum=self.backward_k_min,
            rpc_timeout=self.backward_timeout,
            prepared=prepared,
            trace=trace,
        )

        def finalize(results):
            return self._finalize_backward(
                results, session=session, fwd_dropped=fwd_dropped,
                gy=gy, batch=batch,
            )

        fut = DispatchFuture(
            "backward", coro, finalize,
            join_timeout=self._join_timeout("backward"),
            watchdog_rtt=(
                self._slowest_rtt(uid_jobs)
                if dispatch_mode() == "legacy" else None
            ),
            what=f"backward dispatch ({self.uid_prefix}, {batch} rows)",
            on_join_exit=self._make_join_exit(trace),
        )
        with self._sessions_lock:
            self.inflight_dispatches += 1
        return fut

    @sanitizer.runs_on("host", site="moe._finalize_backward")
    def _finalize_backward(self, results, *, session, fwd_dropped, gy, batch):
        gx = np.zeros((batch, gy.shape[-1]), gy.dtype)
        ok = np.zeros(batch, np.int64)
        with self._sessions_lock:
            # a reply means the expert ran backward AND queued its async
            # update, whether or not the grad shape below survives
            # client-side validation
            self.backward_rpcs_ok += sum(
                1 for p in results.values() if p[-1] is not None
            )
        for uid, payload in results.items():
            reply = payload[-1]
            if reply is None:
                continue
            _, _, rows, slots = session[uid][:4]
            arr = np.asarray(reply[0], gy.dtype)
            if arr.shape != (len(rows), gy.shape[-1]):
                logger.warning(
                    "expert %s returned grad shape %s, expected %s — discarding",
                    uid, arr.shape, (len(rows), gy.shape[-1]),
                )
                continue
            gx[rows] += arr
            ok[rows] += 1
        # samples already dropped in forward contributed zero to the loss;
        # their missing grads are expected, not a second failure
        below = (ok < self.backward_k_min) & ~fwd_dropped
        active = ~fwd_dropped
        if below.any():
            if active.any() and below[active].all():
                raise MoEDispatchError(
                    f"total backward failure: no live sample of {batch} "
                    f"reached backward_k_min={self.backward_k_min} grad replies"
                )
            # mirror the forward degradation: below-quorum samples get zero
            # input-gradient instead of killing the whole training step
            n_drop = int(below.sum())
            self.backward_samples_dropped += n_drop
            gx[below] = 0.0
            logger.warning(
                "backward quorum miss: %d of %d samples below "
                "backward_k_min=%d — zero input-grad", n_drop, batch,
                self.backward_k_min,
            )
        return gx

    # ---- jax-level fire/join ops (the overlapped step's host bridge) ----

    @staticmethod
    def _host_call(cb, specs, *args):
        """``io_callback`` when TRACED (jit); a direct host invocation on
        the caller's thread when eager.

        Eagerly, routing the callback through XLA's host-callback
        machinery executes it on an XLA-owned thread that shares the
        (small) CPU execution pool with any program the caller launches
        between fire and join — on 1-core hosts the callback's
        ``np.asarray(arg)`` then deadlocks against exactly the trunk
        compute the overlapped schedule runs concurrently (the
        round-2/ROUND5 hazard shape; reproduced 2026-08-04 with eager
        overlap at d_model ≥ 256).  A direct call has identical
        semantics — fire never blocks, join blocks in plain Python — with
        no XLA thread in the loop, so the hazard cannot exist there.
        Under jit every operand is a tracer and the io_callback path is
        taken; there XLA owns the whole schedule (one program contains
        fire, trunk and join) and the pinned regression test covers it."""
        if any(isinstance(a, jax.core.Tracer) for a in args):
            return io_callback(cb, specs, *args)
        return cb(*[np.asarray(a) for a in args])

    def _build_async_ops(self):
        """The fire/join custom-vjp pair behind the overlapped swarm step.

        ``fire_op(x, logits) -> (token, handle)``: the host callback runs
        the fire half (selection + payload prep + non-blocking fan-out
        submit) and returns an int32 ticket; ``token`` is ``x`` passed
        through so the graph keeps a float path from input to output.
        ``join_op(token, handle) -> (y, idx, mask)``: the host callback
        joins the ticket's DispatchFuture — the SINGLE blocking point.
        Only the scalar handle crosses into the join callback, so the
        blocking callback never waits on large input buffers (the ROUND5
        io_callback-hang ingredient).

        Backward mirrors the structure in reverse order: join's bwd
        FIRES the backward fan-out (its io_callback returns a zeros
        cotangent for ``token`` purely to keep the backward graph
        ordered), and fire's bwd JOINS it — so the backward trunk
        compute scheduled between them overlaps the grad RPCs exactly
        like the forward."""
        int_spec = jax.ShapeDtypeStruct((), jnp.int32)

        def join_specs(b, d, dtype):
            return (
                jax.ShapeDtypeStruct((b, self.k_best, d), dtype),  # y
                jax.ShapeDtypeStruct((b, self.k_best, self.n_dims), jnp.int32),
                jax.ShapeDtypeStruct((b, self.k_best), jnp.bool_),
                jax.ShapeDtypeStruct((), jnp.int32),  # session id
            )

        @jax.custom_vjp
        def fire_op(x, logits_concat):
            # no-grad primal path (inference): no backward will come, so
            # the join must not store a session
            handle = self._host_call(
                lambda xx, lc: self._host_fire(xx, lc, store_session=False),
                int_spec, x, logits_concat,
            )
            return x, handle

        def fire_fwd(x, logits_concat):
            handle = self._host_call(
                lambda xx, lc: self._host_fire(xx, lc, store_session=True),
                int_spec, x, logits_concat,
            )
            return (x, handle), (handle, x, logits_concat)

        def fire_bwd(residuals, cotangents):
            handle, x, logits_concat = residuals
            g_token = cotangents[0]  # handle is int: no cotangent
            # join the backward fan-out the join op's bwd fired; the
            # g_token operand orders this callback after that one
            gx = self._host_call(
                self._host_join_backward,
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                handle, g_token,
            )
            # token is an identity passthrough of x: any OTHER consumer's
            # cotangent (g_token — zeros in the fire/join pairing) adds
            # to the experts' input-gradient
            return gx + g_token, jnp.zeros_like(logits_concat)

        fire_op.defvjp(fire_fwd, fire_bwd)

        @jax.custom_vjp
        def join_op(token, handle):
            y, idx, mask, _cid = self._host_call(
                self._host_join,
                join_specs(token.shape[0], token.shape[1], token.dtype),
                handle,
            )
            return y, idx, mask

        def join_fwd(token, handle):
            y, idx, mask, cid = self._host_call(
                self._host_join,
                join_specs(token.shape[0], token.shape[1], token.dtype),
                handle,
            )
            return (y, idx, mask), (cid, token)

        def join_bwd(residuals, cotangents):
            cid, token = residuals
            gy = cotangents[0]  # idx/mask are int/bool: no cotangent
            g_token = self._host_call(
                self._host_fire_backward,
                jax.ShapeDtypeStruct(token.shape, token.dtype),
                cid, gy,
            )
            # handle (int32) takes a float0 cotangent
            handle_cot = np.zeros((), dtype=jax.dtypes.float0)
            return g_token, handle_cot

        join_op.defvjp(join_fwd, join_bwd)
        return fire_op, join_op

    def _host_fire(self, x, logits_concat, store_session: bool = True):
        trace = new_trace_id() if timeline.enabled else None
        fid = next(self._call_counter)
        fut = self.dispatch_async(
            x, logits_concat, store_session=store_session, trace=trace,
            session_id=fid,
        )
        evicted = []
        with self._sessions_lock:
            self._pending[fid] = fut
            while len(self._pending) > self.max_sessions:
                evicted.append(self._pending.popitem(last=False))
        # cancel OUTSIDE the lock: the future's join-exit hook re-acquires
        # it to drain the in-flight gauge
        for stale_fid, stale in evicted:
            stale.cancel()
            logger.warning(
                "evicted un-joined dispatch ticket %d — a fire without "
                "a join leaks an in-flight fan-out (raise max_sessions, "
                "or join what you fire)", stale_fid,
            )
        return np.int32(fid)

    def _host_join(self, handle):
        fid = int(handle)
        with self._sessions_lock:
            fut = self._pending.pop(fid, None)
        if fut is None:
            raise MoEDispatchError(
                f"no in-flight dispatch {fid}: join without fire, or the "
                "ticket was evicted (raise max_sessions?)"
            )
        try:
            return fut.join()
        except Exception as e:
            # a failed/timed-out join must surface as THE diagnosable
            # dispatch error, never a hang (the retired ROUND5 class)
            if isinstance(e, MoEDispatchError):
                raise
            raise MoEDispatchError(
                f"dispatch {fid} join failed: {type(e).__name__}: {e}"
            ) from e

    def _host_fire_backward(self, cid, gy):
        gy = np.asarray(gy)
        cid = int(cid)
        with self._sessions_lock:
            entry = self._sessions.pop(cid, None)
        if entry is None:
            raise MoEDispatchError(
                f"no dispatch session {cid}: backward without forward, "
                "or session evicted (raise max_sessions?)"
            )
        session, fwd_dropped, trace = entry
        fut = self.backward_async(session, fwd_dropped, trace, gy)
        evicted = []
        with self._sessions_lock:
            self._pending_bwd[cid] = fut
            while len(self._pending_bwd) > self.max_sessions:
                evicted.append(self._pending_bwd.popitem(last=False))
        for _sf, stale in evicted:  # outside the lock: see _host_fire
            stale.cancel()
        # the zeros cotangent for token: pure graph ordering (the joining
        # fire_bwd callback consumes it, so it runs after this one)
        return np.zeros((gy.shape[0], gy.shape[-1]), gy.dtype)

    def _host_join_backward(self, handle, _g_token):
        fid = int(handle)
        with self._sessions_lock:
            fut = self._pending_bwd.pop(fid, None)
        if fut is None:
            raise MoEDispatchError(
                f"no in-flight backward {fid}: the join op's bwd never "
                "fired (session evicted?)"
            )
        try:
            return fut.join()
        except Exception as e:
            # same contract as _host_join: a failed/timed-out backward
            # join surfaces as THE diagnosable dispatch error
            if isinstance(e, MoEDispatchError):
                raise
            raise MoEDispatchError(
                f"backward dispatch {fid} join failed: "
                f"{type(e).__name__}: {e}"
            ) from e

    def discard(self, token=None, handle=None, logits_concat=None) -> None:
        """Error-path cleanup for a fired-but-unjoined dispatch: pop the
        ticket and cancel its fan-out (draining the in-flight gauge),
        so an exception between :meth:`fire` and :meth:`join` never
        leaks an in-flight fan-out until eviction.  Accepts the full
        ``fire(...)`` return tuple (``discard(*pending)``); a no-op for
        already-joined tickets and for tracers (under jit the callbacks
        never ran at trace time — there is nothing to cancel)."""
        try:
            fid = int(handle)
        except TypeError:
            return
        with self._sessions_lock:
            fut = self._pending.pop(fid, None)
        if fut is not None:
            fut.cancel()

    # ---- the k-of-n gather loop (shared by forward and backward) ----

    async def _quorum_fanout(
        self, msg_type: str, jobs: dict, batch: int, quorum: int,
        rpc_timeout: float, prepared: Optional[dict] = None,
        trace: Optional[str] = None, backups: Optional[dict] = None,
    ) -> dict:
        """Run the fan-out in parallel; once every sample has ≥ quorum
        successful replies, wait a grace period then cancel stragglers (the
        reference's k_min + timeout_after_k_min contract).

        ``backups`` (uid → backup replica endpoint or None; FORWARD only)
        arms hedged fallback per group: once the primary's call outlives
        ``hedge_mult × its RTT EMA`` (floor ``hedge_floor_s``) — or fails
        outright — the SAME prepared payload fires at the backup replica
        and the first successful reply wins.  Cancel semantics
        (docs/PROTOCOL.md): a primary that lost to its hedge is cancelled
        WITH ``QUORUM_STRAGGLER_CANCEL`` (it exceeded the hedge deadline,
        so its elapsed wait folds into its RTT EMA), while a backup that
        lost the race is cancelled UNMARKED — its short unfinished wait
        is evidence about the race, not the peer, and must never reach
        the EMA.  Backward fan-outs never hedge: the server-side
        optimizer step is a side effect a duplicate request would apply
        twice (same reasoning as the no-retry rule below).

        Jobs for experts co-hosted on ONE endpoint travel as a single
        ``multi`` request (per-part replies) — per-request overhead is paid
        per peer, not per expert, and the failure/straggler granularity
        this coarsens to is the real one: co-hosted experts share a
        process, so they die (and straggle) together anyway.

        ``prepared`` (pipelined mode) maps uid → WireTensors serialized on
        the host thread; this coroutine then never casts or packs tensor
        bytes on the loop — merged calls concatenate blob REFERENCES, and
        a disaggregated retry reuses the same buffers."""
        loop = asyncio.get_running_loop()
        registry = pool_registry()
        groups: dict = {}  # endpoint -> [uid, ...]
        for uid, job in jobs.items():
            groups.setdefault(job[0], []).append(uid)
        group_list = list(groups.items())
        if not self.merge_rpcs:
            group_list = [
                (ep, [uid]) for ep, uids in group_list for uid in uids
            ]

        def cast(arr):
            """Downcast floating payloads to the wire dtype (transport
            encoding only; replies are upcast back at the accumulation
            sites via ``np.asarray(reply, dtype)``)."""
            from learning_at_home_tpu.utils.serialization import wire_cast

            return wire_cast([arr], self.wire_dtype)[0]

        async def call_single(endpoint, uid) -> dict:
            meta = (
                {"uid": uid}
                if msg_type == "forward"
                else {"uid": uid, "n_inputs": 1}
            )
            if trace is not None:
                # the trace id rides in the SAME meta on the merged call,
                # the disaggregated retry, and the v1 fallback — the
                # server stamps it onto its pool/runtime spans
                meta["trace"] = trace
            pool = registry.get(endpoint)
            if prepared is not None:
                wire_obj, wmeta = prepared[uid]
                if wmeta is not None:
                    # wmeta is built per-endpoint by the adaptive codec
                    # selector, which only offers encoded (dict) forms to
                    # pools whose hello negotiated "codec" — the gate is
                    # upstream of this function, out of static reach
                    # lah-lint: ignore[R14]
                    meta["wire"] = wmeta
                tensors, _ = await pool.rpc_prepared(
                    msg_type, wire_obj, meta, timeout=rpc_timeout
                )
            else:
                if self.wire_dtype is not None:
                    meta["wire"] = self.wire_dtype
                job = jobs[uid]
                payload = (
                    [cast(job[1])]
                    if msg_type == "forward"
                    else [cast(job[1]), cast(job[4])]
                )
                tensors, _ = await pool.rpc(
                    msg_type, payload, meta, timeout=rpc_timeout
                )
            return {uid: tensors}

        async def call_group(endpoint, uids) -> dict:
            """Returns uid -> reply tensors (None for failed parts)."""
            if len(uids) == 1:
                return await call_single(endpoint, uids[0])
            n_payload = 1 if msg_type == "forward" else 2
            parts = []
            for uid in uids:
                part = {"uid": uid, "n_tensors": n_payload}
                if msg_type == "backward":
                    part["n_inputs"] = 1
                parts.append(part)
            multi_meta = {"op": msg_type, "parts": parts}
            if trace is not None:
                multi_meta["trace"] = trace
            pool = registry.get(endpoint)
            if prepared is not None:
                from learning_at_home_tpu.utils.serialization import (
                    WireTensors,
                )

                # spec/blob reference concat — the per-uid buffers packed
                # once on the host thread serve the merged request as-is.
                # One codec per endpoint (prepared enforces it), so the
                # merged wire meta is the first uid's form with the
                # per-tensor headers concatenated in parts order.
                wire = WireTensors.concat(
                    [prepared[uid][0] for uid in uids]
                )
                wmeta = prepared[uids[0]][1]
                if isinstance(wmeta, dict):
                    wmeta = {
                        "c": wmeta["c"],
                        "h": [
                            h for uid in uids for h in prepared[uid][1]["h"]
                        ],
                    }
                if wmeta is not None:
                    # same contract as call_single: the codec selector
                    # only prepares dict wire forms for endpoints whose
                    # hello negotiated "codec", so the supports() gate
                    # sits upstream of this merged-call path
                    # lah-lint: ignore[R14]
                    multi_meta["wire"] = wmeta
                reply_tensors, reply_meta = await pool.rpc_prepared(
                    "multi", wire, multi_meta, timeout=rpc_timeout
                )
            else:
                if self.wire_dtype is not None:
                    multi_meta["wire"] = self.wire_dtype
                payload = []
                for uid in uids:
                    job = jobs[uid]
                    payload.extend(
                        [cast(job[1])]
                        if msg_type == "forward"
                        else [cast(job[1]), cast(job[4])]
                    )
                reply_tensors, reply_meta = await pool.rpc(
                    "multi", payload, multi_meta, timeout=rpc_timeout
                )
            # reply meta is peer-supplied: any structural lie fails the
            # whole group (equivalent to a failed RPC), never misbinds
            rparts = reply_meta.get("parts")
            if not isinstance(rparts, list) or len(rparts) != len(uids):
                raise RemoteCallError(f"{endpoint}: malformed multi reply")
            out, off = {}, 0
            for uid, rp in zip(uids, rparts):
                if not isinstance(rp, dict) or rp.get("uid") != uid:
                    raise RemoteCallError(
                        f"{endpoint}: multi reply part order mismatch"
                    )
                if rp.get("ok"):
                    n = rp.get("n_tensors")
                    if (
                        not isinstance(n, int) or n < 0
                        or off + n > len(reply_tensors)
                    ):
                        raise RemoteCallError(
                            f"{endpoint}: multi reply tensor counts lie"
                        )
                    out[uid] = reply_tensors[off : off + n]
                    off += n
                else:
                    logger.warning(
                        "%s multi part for %s failed at %s: %s",
                        msg_type, uid, endpoint, rp.get("message"),
                    )
                    out[uid] = None
            if off != len(reply_tensors):
                raise RemoteCallError(
                    f"{endpoint}: multi reply parts cover {off} tensors, "
                    f"reply has {len(reply_tensors)}"
                )
            return out

        # ---- hedged replica fallback (ISSUE 8; forward only) ----

        def _cancel_with(task, e: asyncio.CancelledError) -> None:
            """Forward an outer cancellation (quorum straggler marker or
            unmarked teardown) to a hedge leg unchanged, so the pool's
            RTT-EMA marker semantics survive the extra wrapper layer."""
            if task is not None and not task.done():
                msg = e.args[0] if e.args else None
                if msg is not None:
                    task.cancel(msg=msg)
                else:
                    task.cancel()

        def _hedge_delay(endpoint) -> Optional[float]:
            """RTT-EMA-derived hedge deadline for one primary; None (no
            timed hedge, fast-failure failover only) until the pool has
            any latency measurement to scale from."""
            pool = registry.peek(endpoint)
            if pool is None or pool.rtt_ema is None:
                return None
            return max(self.hedge_mult * pool.rtt_ema, self.hedge_floor_s)

        async def _hedge_wire_ok(backup_ep, uids) -> bool:
            """The hedge resends the SAME prepared bytes; a quantized
            (dict-form) payload needs the backup pool to have negotiated
            the ``codec`` feature — re-encoding on this loop is exactly
            what the pack-once contract forbids."""
            if prepared is None:
                return True
            if not any(isinstance(prepared[u][1], dict) for u in uids):
                return True
            pool = registry.get(backup_ep)
            try:
                await pool.ensure_negotiated(timeout=min(rpc_timeout, 5.0))
            except Exception:
                return False
            return pool.supports("codec")

        def _common_backup(uids):
            """The group's backup endpoint: hedging is per fate-shared
            group, so all its uids must agree on one backup replica host
            (disaggregated retries are single-uid groups and always
            qualify when a backup exists)."""
            if backups is None or msg_type != "forward" or self.hedge_mult <= 0:
                return None
            eps = {backups.get(uid) for uid in uids}
            backup = eps.pop() if len(eps) == 1 else None
            return backup

        async def run_group(endpoint, uids) -> tuple[dict, tuple]:
            """One group's exchange with hedged fallback.  Returns
            ``(uid → reply tensors, winner endpoint)`` — the winner is
            what the backward session must target."""
            t1 = asyncio.ensure_future(call_group(endpoint, uids))
            backup = _common_backup(uids)
            if backup is None:
                try:
                    return await t1, endpoint
                except asyncio.CancelledError as e:
                    _cancel_with(t1, e)
                    raise
            t2 = None
            try:
                primary_exc = None
                await asyncio.wait({t1}, timeout=_hedge_delay(endpoint))
                if t1.done():
                    primary_exc = t1.exception()
                    if primary_exc is None:
                        # awaiting a finished task yields its result
                        # without touching the loop (lint-clean R2 form)
                        return await t1, endpoint
                # the primary exceeded its hedge deadline (or failed
                # outright): fire the backup replica, first reply wins
                if not await _hedge_wire_ok(backup, uids):
                    self._hedge_skipped(backup)
                    if primary_exc is not None:
                        raise primary_exc
                    return await t1, endpoint
                self._arm_hedge(endpoint, backup)
                t2 = asyncio.ensure_future(call_group(backup, uids))
                racing = {t2} if primary_exc is not None else {t1, t2}
                last_exc = primary_exc
                while racing:
                    done, racing = await asyncio.wait(
                        racing, return_when=asyncio.FIRST_COMPLETED
                    )
                    winner = next(
                        (
                            t for t in done
                            if not t.cancelled() and t.exception() is None
                        ),
                        None,
                    )
                    if winner is t2:
                        # first-reply-wins, backup took it: cancel the
                        # loser primary WITH the straggler marker — it
                        # exceeded its hedge deadline, so the elapsed
                        # wait IS slowness evidence for its RTT EMA
                        self.hedge_wins += 1
                        if not t1.done():
                            t1.cancel(msg=QUORUM_STRAGGLER_CANCEL)
                        return await t2, backup
                    if winner is t1:
                        # the primary answered after the hedge fired:
                        # cancel the loser backup UNMARKED — its short
                        # unfinished wait says nothing about the peer
                        # and must not poison its RTT EMA
                        if not t2.done():
                            t2.cancel()
                        return await t1, endpoint
                    for t in done:
                        if not t.cancelled() and t.exception() is not None:
                            last_exc = t.exception()
                if last_exc is not None:
                    raise last_exc
                raise RemoteCallError(
                    f"{endpoint}: hedged {msg_type} group failed"
                )
            except asyncio.CancelledError as e:
                # outer cancel (quorum grace / teardown): forward the
                # SAME marker to both legs so straggler evidence folds
                # exactly as it would without the hedge layer
                _cancel_with(t1, e)
                _cancel_with(t2, e)
                raise

        async def _rescue_single(failed_ep, uid) -> tuple[dict, tuple]:
            """Sole-endpoint rescue (ISSUE 11): a NON-replicated uid has
            no hedge backup, so when its only endpoint hard-fails inside
            the record-TTL window the sample would lose the expert
            outright.  One cache-bypassing refresh — record cache AND
            alive-set cache both skipped (``get_alive_experts_fresh``) —
            re-resolves the uid (a restarted/migrated host re-declares
            within a heartbeat), and the SAME prepared payload retries
            once at the fresh endpoint."""
            self.fresh_retries += 1
            alive = await self.alive_cache.get(force_refresh=True)
            entry = alive.get(uid)
            fresh_ep = None
            if entry is not None:
                fresh_ep = next(
                    (
                        ep for ep in as_replica_set(entry)
                        if tuple(ep) != tuple(failed_ep)
                    ),
                    None,
                )
            if fresh_ep is None:
                raise RemoteCallError(
                    f"{uid}: sole endpoint {failed_ep} failed and the "
                    f"fresh lookup found no replacement"
                )
            if not await _hedge_wire_ok(fresh_ep, [uid]):
                raise RemoteCallError(
                    f"{uid}: fresh endpoint {fresh_ep} cannot accept "
                    f"the prepared wire form"
                )
            replies = await call_single(fresh_ep, uid)
            self.fresh_retry_wins += 1
            return replies, fresh_ep

        pending = {
            asyncio.ensure_future(run_group(ep, uids)): (ep, uids)
            for ep, uids in group_list
        }
        retried: set = set()  # endpoints whose merged call was disaggregated
        rescued: set = set()  # uids given their one sole-endpoint rescue
        rows_of = {uid: job[2] for uid, job in jobs.items()}
        per_sample = np.zeros(batch, np.int64)
        results = {uid: (*job, None) for uid, job in jobs.items()}
        deadline: Optional[float] = None
        while pending:
            timeout = None if deadline is None else max(0.0, deadline - loop.time())
            done, _ = await asyncio.wait(
                pending, timeout=timeout, return_when=asyncio.FIRST_COMPLETED
            )
            if not done:
                break  # grace period expired — drop stragglers
            for task in done:
                endpoint, uids = pending.pop(task)
                try:
                    # lah-lint: ignore[R2] task came out of asyncio.wait's
                    # done set — result() on a finished Task never blocks
                    group_replies, winner_ep = task.result()
                except Exception as e:
                    logger.warning(
                        "%s RPC to %s (%d experts) failed: %s: %s",
                        msg_type, endpoint, len(uids), type(e).__name__, e,
                    )
                    # a MERGED request is one fate-shared unit; a transient
                    # whole-group failure (reply drop, timeout) must not
                    # cost the per-expert independence the k-of-n quorum
                    # exploits — disaggregate ONCE into per-expert singles.
                    # FORWARD ONLY: backward applies the server-side
                    # optimizer step as a side effect, and a lost REPLY
                    # does not mean the request wasn't executed — a retry
                    # would apply the same gradients twice.  Failed
                    # backward groups just count as missing, exactly like
                    # the per-expert fan-out with no retry.
                    if (
                        msg_type == "forward"
                        and len(uids) > 1
                        and endpoint not in retried
                    ):
                        retried.add(endpoint)
                        for uid in uids:
                            # run_group so each retried single keeps its
                            # hedge backup (a merged-call failure is often
                            # the dying-primary case hedging exists for)
                            pending[
                                asyncio.ensure_future(
                                    run_group(endpoint, [uid])
                                )
                            ] = (endpoint, [uid])
                    elif (
                        msg_type == "forward"
                        and len(uids) == 1
                        and backups is not None
                        and backups.get(uids[0]) is None
                        and uids[0] not in rescued
                    ):
                        # non-replicated uid, sole endpoint dead: one
                        # fresh cache-bypassing re-resolution + retry
                        # instead of burning the sample's quorum slot
                        # on a stale record (ISSUE 11)
                        rescued.add(uids[0])
                        pending[
                            asyncio.ensure_future(
                                _rescue_single(endpoint, uids[0])
                            )
                        ] = (endpoint, [uids[0]])
                    continue
                for uid in uids:
                    tensors = group_replies.get(uid)
                    if tensors is None:
                        continue
                    # row-count check HERE, before the reply counts toward
                    # quorum: a fast wrong-shaped (buggy/malicious) reply
                    # must not arm the grace deadline and get honest
                    # stragglers cancelled (callers re-validate full shapes)
                    if not tensors or tensors[0].shape[0] != len(rows_of[uid]):
                        logger.warning(
                            "%s reply from %s has %s rows, expected %d — "
                            "treating as failed",
                            msg_type, uid,
                            tensors[0].shape[0] if tensors else "no",
                            len(rows_of[uid]),
                        )
                        continue
                    # the WINNER endpoint replaces the job's primary so
                    # the backward session targets the replica that
                    # actually evaluated this forward
                    results[uid] = (winner_ep, *jobs[uid][1:], tensors)
                    per_sample[rows_of[uid]] += 1
            if deadline is None:
                # arm the grace period once every sample is either quorate
                # or HOPELESS (even if all its still-pending RPCs landed it
                # could not reach quorum) — a crashed expert must not keep
                # the whole gather waiting on other samples' stragglers.
                # (A black-holed-but-pending RPC still counts as hope; the
                # hard bound for those is rpc_timeout.)
                still_possible = np.zeros(batch, np.int64)
                for _, uids in pending.values():
                    for uid in uids:
                        still_possible[rows_of[uid]] += 1
                settled = (per_sample >= quorum) | (
                    per_sample + still_possible < quorum
                )
                if settled.all():
                    deadline = loop.time() + self.timeout_after_k_min
        for task in pending:
            # explicit marker (NOT an elapsed-time heuristic): the pool
            # folds the straggler's elapsed wait into its RTT EMA however
            # short the configured grace period, while unmarked teardown
            # cancels are never mistaken for slowness (ADVICE r5 item 3)
            task.cancel(msg=QUORUM_STRAGGLER_CANCEL)
        return results
