from learning_at_home_tpu.client.expert import RemoteExpert
from learning_at_home_tpu.client.rpc import (
    client_loop,
    pool_registry,
    reset_client_rpc,
)

__all__ = ["RemoteExpert", "client_loop", "pool_registry", "reset_client_rpc"]
