from learning_at_home_tpu.client.expert import RemoteExpert
from learning_at_home_tpu.client.rpc import (
    client_loop,
    pool_registry,
    reset_client_rpc,
)
from learning_at_home_tpu.client.trainer import PipelinedSwarmTrainer

__all__ = [
    "RemoteExpert",
    "PipelinedSwarmTrainer",
    "client_loop",
    "pool_registry",
    "reset_client_rpc",
]
