"""RemoteExpert: a network-remote expert that behaves like a local function.

Contract from the reference's ``hivemind/client/expert.py`` (SURVEY.md §2;
unverifiable refs, mount empty): ``RemoteExpert`` is an ``nn.Module`` whose
forward serializes inputs and RPCs the server; a custom autograd Function
makes ``backward`` issue a second RPC that returns input-gradients (and, as
a side effect, triggers the server's async optimizer step).

TPU-native realization: a ``jax.custom_vjp`` function whose primal and
cotangent rules are **host callbacks** (``jax.experimental.io_callback``)
doing the framed RPC.  This composes with jit: a training step containing
remote experts compiles into one XLA program with host-offload points where
the network call happens; grads flow through ``jax.grad`` transparently.
Faults here RAISE (single-expert semantics, matching the reference);
k-of-n fault *tolerance* lives in RemoteMixtureOfExperts.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import io_callback

from learning_at_home_tpu.client.rpc import client_loop, pool_registry
from learning_at_home_tpu.utils.connection import Endpoint

logger = logging.getLogger(__name__)


class RemoteExpert:
    """Stub for one expert hosted on a remote Server.

    Output specs (io_callback needs static result shapes) resolve in
    priority order:

    1. an explicit ``output_spec_fn(*input_specs) -> spec-or-tuple``;
    2. the server's published ``output_schema`` (per-row leaf shapes +
       dtypes, set once the expert has warmed up or served a forward) —
       fetched lazily with one ``info`` RPC and cached, this also enables
       **multi-output experts** with no client-side configuration;
    3. fallback: output shaped like the first input (the standard blocks).
    """

    def __init__(
        self,
        uid: str,
        endpoint: Endpoint,
        timeout: float = 30.0,
        output_spec_fn: Optional[Callable] = None,
        wire_dtype: Optional[str] = None,
    ):
        from learning_at_home_tpu.client.rpc import ensure_sync_cpu_dispatch

        ensure_sync_cpu_dispatch()  # host-callback path: see rpc.py
        from learning_at_home_tpu.utils.serialization import validate_wire_dtype

        validate_wire_dtype(wire_dtype)
        # transport encoding: floating payloads downcast both ways (server
        # computes in f32 — see server/connection_handler.py).  NB
        # forward_blocking/backward_blocking then RETURN wire-dtype arrays;
        # the jit path upcasts them to the output specs' dtype.
        self.wire_dtype = wire_dtype
        self.uid = uid
        self.endpoint = (endpoint[0], int(endpoint[1]))
        self.timeout = timeout
        self.output_spec_fn = output_spec_fn
        self._server_output_schema = ()  # () = not fetched yet; None = absent
        self._structure_checked = False
        self._call = self._build_custom_vjp()

    # ---- blocking host-side RPCs (also used by the MoE layer) ----

    async def _rpc(self, msg_type, tensors, meta):
        pool = pool_registry().get(self.endpoint)
        return await pool.rpc(msg_type, tensors, meta, timeout=self.timeout)

    async def _rpc_prepared(self, msg_type, wire, meta):
        pool = pool_registry().get(self.endpoint)
        return await pool.rpc_prepared(msg_type, wire, meta, timeout=self.timeout)

    def _wire_cast(self, arrs) -> list:
        from learning_at_home_tpu.utils.serialization import wire_cast

        return wire_cast(arrs, self.wire_dtype)

    def _wire_meta(self, meta: dict) -> dict:
        if self.wire_dtype is not None:
            meta["wire"] = self.wire_dtype
        return meta

    def _call_blocking(self, msg_type: str, tensors, meta: dict):
        """One exchange with serialization on THIS thread (pipelined
        mode): the wire cast above and the spec/blob walk both run on the
        host thread already blocked inside io_callback, so the shared
        ``lah-client`` loop only writes ready buffers.  Legacy mode keeps
        the old serialize-on-the-loop path (the bench A/B baseline)."""
        from learning_at_home_tpu.client.rpc import dispatch_mode

        if dispatch_mode() == "pipelined":
            from learning_at_home_tpu.utils.serialization import WireTensors

            wire = WireTensors.prepare(tensors)
            out, _ = client_loop().run(self._rpc_prepared(msg_type, wire, meta))
        else:
            out, _ = client_loop().run(self._rpc(msg_type, tensors, meta))
        return out

    def forward_blocking(self, inputs: Sequence[np.ndarray]) -> list[np.ndarray]:
        return self._call_blocking(
            "forward", self._wire_cast(inputs),
            self._wire_meta({"uid": self.uid}),
        )

    def backward_blocking(
        self, inputs: Sequence[np.ndarray], grad_outputs: Sequence[np.ndarray]
    ) -> list[np.ndarray]:
        return self._call_blocking(
            "backward",
            self._wire_cast([*inputs, *grad_outputs]),
            self._wire_meta({"uid": self.uid, "n_inputs": len(inputs)}),
        )

    def info(self) -> dict:
        _, meta = client_loop().run(self._rpc("info", (), {"uid": self.uid}))
        return meta

    # ---- the jax-transformable call path ----

    def _output_specs(self, input_specs: tuple) -> tuple:
        """Static output specs for io_callback (see class docstring for
        the resolution order).  Always returns a tuple of specs."""
        if self.output_spec_fn is not None:
            spec = self.output_spec_fn(*input_specs)
            return tuple(spec) if isinstance(spec, (tuple, list)) else (spec,)
        if self._server_output_schema == ():
            # cache ONLY a published schema; on RPC failure or a not-yet-
            # warmed server (no schema in info) fall back for THIS trace
            # and re-fetch on the next one — the schema appears as soon as
            # the expert serves its first forward
            try:
                schema = self.info().get("output_schema")
            except Exception:
                logger.warning(
                    "info RPC for %s failed; falling back to "
                    "first-input-shaped output spec", self.uid, exc_info=True
                )
                schema = None
            if schema:
                self._server_output_schema = schema
        else:
            schema = self._server_output_schema
        if schema:
            rows = input_specs[0].shape[0]
            return tuple(
                jax.ShapeDtypeStruct(
                    (rows, *s["shape"]), np.dtype(s["dtype"])
                )
                for s in schema
            )
        return (input_specs[0],)

    def _build_custom_vjp(self):
        def host_backward(n_in, args):
            arrs = [np.asarray(a) for a in args]
            grads = self.backward_blocking(arrs[:n_in], arrs[n_in:])
            if len(grads) != n_in:
                raise ValueError(
                    f"expert {self.uid} returned {len(grads)} input-grads "
                    f"for {n_in} inputs"
                )
            return grads

        @jax.custom_vjp
        def remote_call(*inputs):
            specs = self._output_specs(
                tuple(jax.ShapeDtypeStruct(np.shape(x), x.dtype) for x in inputs)
            )

            def cb(*xs):
                outs = self.forward_blocking([np.asarray(x) for x in xs])
                if len(outs) != len(specs):
                    raise ValueError(
                        f"expert {self.uid} returned {len(outs)} outputs, "
                        f"client expected {len(specs)}"
                    )
                return tuple(
                    np.asarray(o, dtype=s.dtype) for o, s in zip(outs, specs)
                )

            out = io_callback(cb, specs, *inputs)
            return out[0] if len(specs) == 1 else tuple(out)

        def fwd(*inputs):
            return remote_call(*inputs), inputs

        def bwd(residual_inputs, grad_out):
            grads_out = (
                list(grad_out)
                if isinstance(grad_out, (tuple, list))
                else [grad_out]
            )
            n_in = len(residual_inputs)
            # integer wire inputs (e.g. det_dropout's per-row seed) take
            # float0 cotangents, which io_callback cannot produce — the
            # callback ships ALL inputs to the server (it needs them to
            # re-forward) but returns grads only for the float primals
            diff_idx = tuple(
                i for i, x in enumerate(residual_inputs)
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)
            )
            diff_specs = tuple(
                jax.ShapeDtypeStruct(
                    np.shape(residual_inputs[i]), residual_inputs[i].dtype
                )
                for i in diff_idx
            )
            def cb(*args):
                grads = host_backward(n_in, args)
                return tuple(
                    np.asarray(grads[i], dtype=s.dtype)
                    for i, s in zip(diff_idx, diff_specs)
                )

            diff_grads = io_callback(cb, diff_specs, *residual_inputs, *grads_out)
            by_idx = dict(zip(diff_idx, diff_grads))
            return tuple(
                by_idx.get(i, np.zeros(np.shape(x), jax.dtypes.float0))
                for i, x in enumerate(residual_inputs)
            )

        remote_call.defvjp(fwd, bwd)
        return remote_call

    def __call__(self, *inputs):
        """Jit/grad-compatible remote forward; backward RPCs on the vjp.

        Arguments may be arbitrary pytrees of arrays — they are flattened
        to the wire's flat-tensor order (jax flattening), and on the first
        nested call the client checks its structure against the server's
        published ``input_schema`` so a flatten-order mismatch (e.g.
        OrderedDict vs plain dict) fails loudly instead of silently
        binding tensors to the wrong arguments."""
        leaves = jax.tree_util.tree_leaves(inputs)
        if len(leaves) != len(inputs) and not self._structure_checked:
            self._check_structure(inputs)
        return self._call(*leaves)

    def _check_structure(self, inputs: tuple) -> None:
        from learning_at_home_tpu.utils.nested import schema_from_tree

        server_schema = self.info().get("input_schema")
        if server_schema is not None:
            client_tree = inputs[0] if len(inputs) == 1 else tuple(inputs)
            client_schema = schema_from_tree(client_tree)
            if client_schema != server_schema:
                raise ValueError(
                    f"input structure mismatch for expert {self.uid}: "
                    f"client sends {client_schema}, server expects "
                    f"{server_schema} — tensors would bind to the wrong "
                    "arguments"
                )
        self._structure_checked = True

    def __repr__(self) -> str:
        return f"RemoteExpert({self.uid!r} @ {self.endpoint[0]}:{self.endpoint[1]})"
