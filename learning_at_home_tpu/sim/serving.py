"""Virtual serving layer: real control plane, modeled data plane.

The REAL code under simulation (never reimplemented here):

- ``gateway.scheduler.SlotScheduler`` — driven by calling
  ``_iteration()`` directly, the same no-decode-thread pattern
  ``analysis/verify.py`` established; slots/pages bookkeeping is the
  real ``PagedKVCache`` via verify's ``_FakePagedDecoder``;
- ``gateway.admission.AdmissionController`` — every arrival passes
  through ``admit()``; the worst-queue snapshot refreshes inline via
  ``maybe_refresh()`` on the virtual clock;
- ``client.routing`` — ``CachedAliveSet`` (TTL on the clock seam) over
  a real DHT read, ``select_top_k`` + ``RoutingCostModel.bias`` for
  expert selection, ``order_replicas`` for replica choice;
- ``dht.node.DHTNode`` / ``dht.protocol.DHTProtocol`` — every
  declare/lookup is a real iterative Kademlia exchange over
  :mod:`~learning_at_home_tpu.sim.net`.

What is MODELED (docs/SIMULATION.md "simulated vs real"):

- per-link RTT/bandwidth (:class:`LinkModel`, seeded distributions on a
  clustered topology);
- expert-server compute: a scalar work backlog per server that drains
  in virtual time (:class:`VirtualExpertServer.dispatch`);
- trunk math: token arithmetic from ``_FakePagedDecoder`` — the content
  of tokens never affects timing, only their count does.
"""

from __future__ import annotations

import asyncio
import random
from typing import Optional, Sequence

import numpy as np

from learning_at_home_tpu.client.routing import (
    CachedAliveSet,
    RoutingCostModel,
    as_replica_set,
    endpoint_key,
    select_top_k,
)
from learning_at_home_tpu.dht.node import DHTNode
from learning_at_home_tpu.dht.protocol import PLAIN_SUBKEY
from learning_at_home_tpu.gateway.admission import AdmissionController
from learning_at_home_tpu.gateway.scheduler import SlotScheduler
from learning_at_home_tpu.sim.net import SIM_HOST, SimNetwork, spawn_node
from learning_at_home_tpu.utils import flight
from learning_at_home_tpu.utils.telemetry import (
    MAX_ADVERTISED_LINKS,
    links_key,
    load_key,
    parse_links_value,
    parse_load_value,
)
from learning_at_home_tpu.utils.timed_storage import get_dht_time


def pair_rng(seed: int, a, b, salt: str) -> random.Random:
    """Seeded RNG for an unordered pair — stable across processes (string
    seeding hashes with sha512, never the salted builtin ``hash``)."""
    lo, hi = (a, b) if str(a) <= str(b) else (b, a)
    return random.Random(f"{seed}|{lo}|{hi}|{salt}")


class LinkModel:
    """Seeded per-link RTT/bandwidth on a clustered topology.

    Ports are assigned to ``n_clusters`` "regions"; intra-cluster links
    are fast/fat, inter-cluster links slow/thin.  Every draw is a pure
    function of (seed, port pair), cached, symmetric — the same numbers
    feed the SimNetwork delivery delay, the servers' published
    ``links.<prefix>`` records, and the placement snapshot, so routing
    and placement optimize against one consistent world.
    """

    def __init__(
        self,
        seed: int,
        *,
        n_clusters: int = 4,
        intra_rtt_s: tuple = (0.002, 0.012),
        inter_rtt_s: tuple = (0.030, 0.120),
        intra_bw_bps: tuple = (200e6, 1000e6),
        inter_bw_bps: tuple = (20e6, 200e6),
    ):
        self.seed = int(seed)
        self.n_clusters = max(1, int(n_clusters))
        self.intra_rtt_s = intra_rtt_s
        self.inter_rtt_s = inter_rtt_s
        self.intra_bw_bps = intra_bw_bps
        self.inter_bw_bps = inter_bw_bps
        self._cache: dict[tuple, tuple] = {}

    def cluster_of(self, port: int) -> int:
        # stable region assignment; ports are allocated densely from 1
        return int(port) % self.n_clusters

    def link(self, a_port: int, b_port: int) -> tuple:
        """(rtt_s, bw_bps) for the unordered port pair; rtt is the full
        request+reply round trip."""
        if a_port == b_port:
            return (0.0002, 1000e6)
        key = (min(a_port, b_port), max(a_port, b_port))
        hit = self._cache.get(key)
        if hit is not None:
            return hit
        rng = pair_rng(self.seed, *key, salt="link")
        same = self.cluster_of(a_port) == self.cluster_of(b_port)
        rtt_lo, rtt_hi = self.intra_rtt_s if same else self.inter_rtt_s
        bw_lo, bw_hi = self.intra_bw_bps if same else self.inter_bw_bps
        out = (rng.uniform(rtt_lo, rtt_hi), rng.uniform(bw_lo, bw_hi))
        self._cache[key] = out
        return out

    def rtt_s(self, a_port: int, b_port: int) -> float:
        return self.link(a_port, b_port)[0]

    def delivery_delay(self, src_port: int, dst_port: int) -> float:
        """SimNetwork ``latency_fn``: one RPC costs one round trip."""
        return self.rtt_s(src_port, dst_port)


class NullPoolRegistry:
    """RoutingCostModel registry stub: the sim gateway never dials a real
    socket, so there are no local pool EMAs — every prediction falls
    back to the swarm-published link prior + queue depth, which is
    exactly the cold-start path ISSUE 16 built."""

    def peek(self, endpoint):
        return None


class DhtExpertSource:
    """``ExpertSource`` over a raw ``DHTNode`` (the facade's subkey
    parsing, minus its cache/loop bridge — the sim runs everything on
    one loop already).  Subkey forms as in ``dht/__init__._get_alive``."""

    def __init__(self, node: DHTNode):
        self.node = node

    @staticmethod
    def _parse_endpoint(v) -> Optional[tuple]:
        if (
            isinstance(v, (list, tuple)) and len(v) == 2
            and isinstance(v[0], str)
        ):
            try:
                return (v[0], int(v[1]))
            except (TypeError, ValueError):
                return None
        return None

    async def get_alive_experts(self, prefix: str) -> dict:
        records = await self.node.get(prefix)
        eps: dict[str, list] = {}
        for subkey in sorted(records, key=str):
            value, _exp = records[subkey]
            endpoint = self._parse_endpoint(value)
            if endpoint is None:
                continue
            if subkey == PLAIN_SUBKEY or not isinstance(subkey, str):
                uid = prefix
            elif subkey.startswith("@"):
                uid = prefix
            elif "@" in subkey:
                uid = subkey.rsplit("@", 1)[0]
            else:
                uid = subkey
            bucket = eps.setdefault(uid, [])
            if endpoint not in bucket:
                bucket.append(endpoint)
        return {
            uid: (lst[0] if len(lst) == 1 else tuple(sorted(lst)))
            for uid, lst in eps.items()
        }

    async def get_alive_experts_fresh(self, prefix: str) -> dict:
        return await self.get_alive_experts(prefix)


class VirtualExpertServer:
    """One expert host: a real DHT node + a scalar compute model.

    Work arrives through :meth:`dispatch` as seconds-of-compute; the
    backlog drains at one virtual second per virtual second, so queueing
    delay emerges from load instead of being scripted.  Heartbeats
    publish the REAL record bundle (per-uid declares + prefix fan-in +
    ``load``/``links`` sidecars) through real ``store_many`` calls.
    """

    def __init__(
        self,
        dht: DHTNode,
        *,
        clock,
        link_model: LinkModel,
        prefix: str,
        experts: list,
        rng: random.Random,
        base_service_s: float = 0.004,
        per_token_s: float = 0.0002,
        hb_period_s: float = 20.0,
        record_ttl_s: float = 60.0,
    ):
        self.dht = dht
        self.clock = clock
        self.link_model = link_model
        self.prefix = prefix
        self.experts = list(experts)
        self.rng = rng
        self.base_service_s = base_service_s
        self.per_token_s = per_token_s
        self.hb_period_s = hb_period_s
        self.record_ttl_s = record_ttl_s
        self.alive = True
        self.backlog_s = 0.0
        self._drained_at = clock.monotonic()
        self.dispatches_total = 0
        self.heartbeats_total = 0
        self._hb_task: Optional[asyncio.Task] = None
        self.peer_ports: list = []  # advertised link destinations

    @property
    def port(self) -> int:
        return self.dht.protocol.listen_port

    @property
    def endpoint(self) -> tuple:
        return (SIM_HOST, self.port)

    # ---- the compute model ----

    def _drain(self, now: float) -> None:
        self.backlog_s = max(0.0, self.backlog_s - (now - self._drained_at))
        self._drained_at = now

    def queue_delay_s(self, now: float) -> float:
        self._drain(now)
        return self.backlog_s

    def q_depth(self, now: float) -> float:
        """Advertised queue depth: backlog in units of mean batches."""
        return self.queue_delay_s(now) / max(1e-9, self.base_service_s)

    def dispatch(self, now: float, tokens: int) -> float:
        """Accept one expert dispatch; returns virtual seconds until its
        reply (queue wait + service)."""
        wait = self.queue_delay_s(now)
        work = self.base_service_s + self.per_token_s * int(tokens)
        self.backlog_s += work
        self.dispatches_total += 1
        return wait + work

    # ---- the declare/heartbeat path (real DHT stores) ----

    def heartbeat_entries(self) -> list:
        now = get_dht_time()
        exp = now + self.record_ttl_s
        value = [self.endpoint[0], int(self.endpoint[1])]
        ep_key = endpoint_key(self.endpoint)
        entries: list = []
        for uid in self.experts:
            entries.append((uid, f"@{ep_key}", value, exp))
            entries.append((self.prefix, f"{uid}@{ep_key}", value, exp))
        q = round(self.q_depth(self.clock.monotonic()), 3)
        entries.append((
            load_key(self.prefix), f"@{ep_key}",
            {"q": q, "n": len(self.experts)}, exp,
        ))
        if self.peer_ports:
            links = {
                f"{SIM_HOST}:{p}": [
                    round(self.link_model.rtt_s(self.port, p), 6),
                    round(self.link_model.link(self.port, p)[1], 1),
                ]
                for p in self.peer_ports[:MAX_ADVERTISED_LINKS]
            }
            entries.append((
                links_key(self.prefix), f"@{ep_key}", {"l": links}, exp,
            ))
        return entries

    async def heartbeat_once(self) -> None:
        acks = await self.dht.store_many(self.heartbeat_entries())
        self.heartbeats_total += 1
        if not all(acks):
            # best-effort like the real declare loop: count, don't raise
            pass

    async def heartbeat_forever(self) -> None:
        # deterministic phase offset so 2k servers don't stampede the
        # same virtual instant
        await asyncio.sleep(self.rng.uniform(0.0, self.hb_period_s))
        while self.alive:
            await self.heartbeat_once()
            await asyncio.sleep(self.hb_period_s)

    def start_heartbeat(self) -> None:
        self._hb_task = asyncio.get_running_loop().create_task(
            self.heartbeat_forever(), name=f"hb-{self.port}"
        )

    async def kill(self, network: SimNetwork) -> None:
        """Fail-stop: drop off the fabric mid-TTL, records left to decay
        — the failure mode the record-expiry detector exists for."""
        self.alive = False
        if self._hb_task is not None:
            self._hb_task.cancel()
            try:
                await self._hb_task
            except asyncio.CancelledError:
                pass
            self._hb_task = None
        network.unregister(self.dht.protocol)


class TelemetryMirror:
    """The gateway's cached control-plane view: periodic REAL DHT reads
    of the ``load.<prefix>`` / ``links.<prefix>`` families, parsed with
    the production telemetry parsers, served to the cost model and the
    admission controller as plain sync getters (the same
    read-async/serve-sync split the real client uses)."""

    def __init__(self, node: DHTNode, prefix: str, *, period_s: float = 5.0):
        self.node = node
        self.prefix = prefix
        self.period_s = period_s
        self._loads: dict = {}
        self._links: dict = {}
        self.refreshes_total = 0
        self._task: Optional[asyncio.Task] = None

    async def refresh_once(self) -> None:
        load_recs = await self.node.get(load_key(self.prefix))
        loads: dict = {}
        for subkey in sorted(load_recs, key=str):
            value, _exp = load_recs[subkey]
            if not (isinstance(subkey, str) and subkey.startswith("@")):
                continue
            parsed = parse_load_value(value)
            if parsed is not None:
                loads[subkey[1:]] = parsed
        link_recs = await self.node.get(links_key(self.prefix))
        links: dict = {}
        for subkey in sorted(link_recs, key=str):
            value, _exp = link_recs[subkey]
            parsed = parse_links_value(value)
            if parsed is None:
                continue
            for dst, ent in sorted(parsed.items()):
                cur = links.get(dst)
                # best prior wins: keep the smallest published rtt
                if cur is None or ent["rtt_s"] < cur["rtt_s"]:
                    links[dst] = ent
        self._loads, self._links = loads, links
        self.refreshes_total += 1

    def load_getter(self) -> dict:
        return self._loads

    def link_getter(self) -> dict:
        return self._links

    async def run_forever(self) -> None:
        while True:
            await self.refresh_once()
            await asyncio.sleep(self.period_s)

    def start(self) -> None:
        self._task = asyncio.get_running_loop().create_task(
            self.run_forever(), name=f"mirror-{self.prefix}"
        )

    def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            self._task = None


class SimGateway:
    """One gateway: the real scheduler/admission/routing stack pumped by
    a coroutine on the virtual clock.

    ``_iteration()`` itself is bookkeeping and costs zero virtual time;
    the iteration's virtual duration is then modeled as base step time
    plus the slowest selected expert's (link round trip + queue wait +
    service) and slept, so fleet throughput, TTFT and ITL all emerge
    from load, placement and the trace.  Token timestamps are taken at
    the END of the step that produced them.
    """

    def __init__(
        self,
        name: str,
        dht: DHTNode,
        *,
        clock,
        network: SimNetwork,
        link_model: LinkModel,
        servers_by_port: dict,
        prefix: str,
        n_experts: int,
        seed: int,
        max_slots: int = 64,
        seq_len: int = 96,
        page_len: int = 4,
        pages_per_slot: float = 6.0,
        fanout_k: int = 2,
        cost_weight: float = 1.0,
        alive_ttl_s: float = 3.0,
        base_step_s: float = 0.002,
        idle_wait_s: float = 0.01,
        dead_dispatch_s: float = 0.25,
        max_pending: Optional[int] = None,
        mirror_period_s: float = 5.0,
    ):
        from learning_at_home_tpu.analysis.verify import _FakePagedDecoder

        self.name = name
        self.dht = dht
        self.clock = clock
        self.network = network
        self.link_model = link_model
        self.servers_by_port = servers_by_port
        self.prefix = prefix
        self.n_experts = int(n_experts)
        self.fanout_k = int(fanout_k)
        self.base_step_s = base_step_s
        self.idle_wait_s = idle_wait_s
        self.dead_dispatch_s = dead_dispatch_s
        self.decoder = _FakePagedDecoder(
            max_slots=max_slots, seq_len=seq_len, page_len=page_len,
            num_pages=int(max_slots * pages_per_slot),
        )
        self.sched = SlotScheduler(
            self.decoder, idle_wait_s=0.0, stream_ttl_s=10_000.0,
            prefill_chunk_tokens=8,
        )
        self.mirror = TelemetryMirror(dht, prefix, period_s=mirror_period_s)
        self.adm = AdmissionController(
            self.sched,
            max_pending=max_pending,
            load_fn=self.mirror.load_getter,
            refresh_period_s=mirror_period_s,
        )
        self.cost = RoutingCostModel(
            cost_weight,
            registry=NullPoolRegistry(),
            load_getter=self.mirror.load_getter,
            link_getter=self.mirror.link_getter,
        )
        self.alive_set = CachedAliveSet(
            DhtExpertSource(dht), prefix, ttl=alive_ttl_s, swr=False,
        )
        self.np_rng = np.random.RandomState(
            int(pair_rng(seed, name, "gw", "gate").random() * 2**31)
        )
        # per-stream bookkeeping (sim-side observability, not scheduler
        # internals): sid -> [submitted_at, first_token_at, cursor,
        # bucket, last_emit_at]
        self.inflight: dict[str, list] = {}
        self.arrival_queue: list = []  # (prompt, max_new, bucket) FIFO
        self.completed = 0
        self.errored = 0
        self.shed = 0
        self.tokens_served = 0
        self.ttfts: list = []   # (bucket, seconds) samples
        self.itls: list = []    # (bucket, seconds) samples
        # co-activation + routing observability shared with placement
        self.coact: dict[tuple, int] = {}
        self.activations: dict[str, int] = {}
        self.selection_rounds = 0
        self.no_alive_rounds = 0
        self._stopping = False
        self._task: Optional[asyncio.Task] = None

    @property
    def port(self) -> int:
        return self.dht.protocol.listen_port

    # ---- arrivals ----

    def submit_arrival(self, prompt: list, max_new: int, bucket: str) -> bool:
        """Admission + real submit; False = shed."""
        self.adm.maybe_refresh()
        pages = self.decoder.pages_needed(len(prompt), max_new)
        accepted, _retry, _reason = self.adm.admit(pages_needed=pages)
        if not accepted:
            self.shed += 1
            # virtual-clock-aware flight event (the seam in sim/clock.py
            # stamps t_mono from the scenario clock)
            flight.record(
                f"sim.{self.name}", "shed", reason=_reason, bucket=bucket,
                pages_needed=pages,
            )
            return False
        sid = self.sched.submit(prompt, max_new)
        now = self.clock.monotonic()
        self.inflight[sid] = [now, None, 0, bucket, now]
        return True

    # ---- expert selection (real routing code) ----

    async def _select_experts(self, tokens_this_step: int) -> list:
        """One routing decision for this iteration's microbatch; returns
        [(uid, endpoint, dispatch_cost_s)] for the chosen experts."""
        alive = await self.alive_set.get()
        if not alive:
            self.no_alive_rounds += 1
            return []
        uids = sorted(alive)
        replica_sets = {uid: as_replica_set(alive[uid]) for uid in uids}
        logits = [self.np_rng.randn(1, self.n_experts).astype(np.float32)]
        bias = self.cost.bias(uids, replica_sets, nbytes=tokens_this_step * 8)
        sel, _coords = select_top_k(
            logits, uids, min(self.fanout_k, len(uids)), bias=bias
        )
        now = self.clock.monotonic()
        chosen = []
        for j in sel[0]:
            uid = uids[int(j)]
            replicas = self.cost.order_replicas(
                replica_sets[uid], nbytes=tokens_this_step * 8
            )
            ep = replicas[0]
            server = self.servers_by_port.get(int(ep[1]))
            if server is None or not server.alive:
                # routed to a corpse mid-TTL: pay the timeout, learn
                # nothing (the alive set corrects itself at expiry)
                cost = self.dead_dispatch_s
            else:
                cost = (
                    self.link_model.rtt_s(self.port, server.port)
                    + server.dispatch(now, tokens_this_step)
                )
            chosen.append((uid, ep, cost))
        for i, (u, _e, _c) in enumerate(chosen):
            self.activations[u] = (
                self.activations.get(u, 0) + tokens_this_step
            )
            for v, _e2, _c2 in chosen[i + 1:]:
                if u == v:
                    continue
                key = (min(u, v), max(u, v))
                self.coact[key] = self.coact.get(key, 0) + 1
        self.selection_rounds += 1
        return chosen

    # ---- the pump ----

    def _harvest(self, stamp: float) -> None:
        """Fold newly produced tokens / finished streams into the
        sim-side accounting; tokens emitted this step complete at its
        END (``stamp``)."""
        done = []
        for sid in list(self.inflight):
            rec = self.inflight[sid]
            out = self.sched.poll(sid, rec[2])
            if out is None:
                done.append(sid)
                continue
            new = len(out["tokens"])
            if new:
                if rec[1] is None:
                    rec[1] = stamp
                    self.ttfts.append((rec[3], stamp - rec[0]))
                else:
                    # the gap since this stream last emitted is one ITL
                    # sample; extra tokens landing in the SAME step are
                    # simultaneous (zero-gap samples would only dilute
                    # percentiles, so they are not counted)
                    self.itls.append((rec[3], stamp - rec[4]))
                rec[4] = stamp
                rec[2] = out["cursor"]
            if out["done"]:
                if out["error"]:
                    self.errored += 1
                else:
                    self.completed += 1
                    self.tokens_served += rec[2]
                done.append(sid)
        for sid in done:
            self.inflight.pop(sid, None)

    async def run_forever(self) -> None:
        while True:
            self.adm.maybe_refresh()
            if self.sched.pending_count() + len(self.inflight) == 0:
                if self._stopping:
                    return
                await asyncio.sleep(self.idle_wait_s)
                continue
            worked = self.sched._iteration()
            if not worked:
                self._harvest(self.clock.monotonic())
                await asyncio.sleep(self.idle_wait_s)
                continue
            tokens_this_step = max(
                1, int((self.decoder.live | self.decoder.prefilling).sum())
            )
            chosen = await self._select_experts(tokens_this_step)
            step_dt = self.base_step_s + (
                max(c for _u, _e, c in chosen) if chosen else 0.0
            )
            await asyncio.sleep(step_dt)
            self._harvest(self.clock.monotonic())

    def start(self) -> None:
        self.mirror.start()
        self._task = asyncio.get_running_loop().create_task(
            self.run_forever(), name=f"gw-{self.name}"
        )

    async def drain_and_stop(self) -> None:
        self._stopping = True
        if self._task is not None:
            await self._task
            self._task = None
        self.mirror.stop()
