"""Deterministic whole-system macro-simulator (ISSUE 18).

One process, one virtual clock, thousands of virtual expert servers +
gateways + DHT nodes running the REAL scheduler / admission / routing /
placement code against simulated network latency and compute-time
models.  See docs/SIMULATION.md for the clock-seam contract, the trace
schema, and the simulated-vs-real boundary.

Modules:

- :mod:`~learning_at_home_tpu.sim.clock` — the virtual clock, the seam
  patcher, and the virtual-time asyncio event loop;
- :mod:`~learning_at_home_tpu.sim.trace` — arrival-trace segments
  (poisson / burst / diurnal) + scheduled churn events, shared with
  ``experiments/loadgen.py`` and ``experiments/dht_swarm_sim.py``;
- :mod:`~learning_at_home_tpu.sim.net` — the in-process DHT delivery
  fabric (lifted from ``experiments/dht_swarm_sim.py``);
- :mod:`~learning_at_home_tpu.sim.serving` — virtual expert servers,
  gateways wrapping the real ``SlotScheduler``/``AdmissionController``,
  and the telemetry mirror feeding the real routing cost model;
- :mod:`~learning_at_home_tpu.sim.runner` — scenario orchestration and
  the ``python -m learning_at_home_tpu.sim.runner`` CLI behind
  ``bench.py --macro-sim`` and the collect_gate MACRO_SIM smoke.
"""

from learning_at_home_tpu.sim.clock import (  # noqa: F401
    VirtualClock,
    VirtualClockEventLoop,
    installed_clock,
)
from learning_at_home_tpu.sim.trace import ChurnEvent, Trace, TraceSegment  # noqa: F401
