"""Arrival traces and churn schedules (ISSUE 18).

One trace format shared by three consumers:

- ``sim/runner.py`` drives the macro-sim's request injector from it on
  the virtual clock;
- ``experiments/loadgen.py --trace`` replays the same segment spec
  against a REAL gateway on the wall clock;
- ``experiments/dht_swarm_sim.py`` expresses its kill-and-replace
  rounds as the same :class:`ChurnEvent` schedule the macro-sim uses.

Segment spec grammar (comma-separated, colon-delimited fields)::

    poisson:RATE:DURATION            # stationary Poisson arrivals
    burst:RATE:DURATION              # alias naming intent (a burst IS a
                                     # high-rate stationary segment)
    diurnal:RATE:DURATION:DEPTH:PERIOD
        # sinusoidal rate swing: rate(t) = RATE * (1 + DEPTH *
        # sin(2*pi*t/PERIOD)), clipped at 0; DEPTH in [0, 1]

Churn spec grammar (comma-separated)::

    AT:kill:FRACTION                 # at AT seconds, kill FRACTION of
                                     # the eligible population
    AT:join:COUNT                    # at AT seconds, add COUNT nodes

Arrival sampling uses Lewis-Shedler thinning against the segment's peak
rate, so a non-homogeneous (diurnal) segment needs only ``rng.random()``
draws — deterministic for a seeded ``random.Random`` (or any object with
a ``random()`` method, e.g. an adapter over ``np.random.RandomState``).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterator, Sequence


@dataclasses.dataclass(frozen=True)
class TraceSegment:
    """One homogeneous-or-sinusoidal stretch of the arrival process."""

    kind: str            # "poisson" | "burst" | "diurnal"
    rate_hz: float       # mean rate (diurnal: the midline)
    duration_s: float
    depth: float = 0.0   # diurnal swing in [0, 1]
    period_s: float = 0.0

    def rate_at(self, t: float) -> float:
        """Instantaneous rate ``t`` seconds into THIS segment."""
        if self.kind != "diurnal" or self.period_s <= 0:
            return self.rate_hz
        swing = 1.0 + self.depth * math.sin(2.0 * math.pi * t / self.period_s)
        return max(0.0, self.rate_hz * swing)

    @property
    def peak_rate_hz(self) -> float:
        if self.kind == "diurnal":
            return self.rate_hz * (1.0 + max(0.0, self.depth))
        return self.rate_hz


@dataclasses.dataclass(frozen=True)
class ChurnEvent:
    """A scheduled population change."""

    at_s: float
    kind: str            # "kill" | "join"
    fraction: float = 0.0  # kill: fraction of eligible nodes
    count: int = 0         # join: number of nodes to add


@dataclasses.dataclass(frozen=True)
class Trace:
    segments: tuple
    churn: tuple = ()

    @property
    def duration_s(self) -> float:
        return sum(s.duration_s for s in self.segments)

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate at absolute trace time ``t``."""
        for seg in self.segments:
            if t < seg.duration_s:
                return seg.rate_at(t)
            t -= seg.duration_s
        return 0.0

    def iter_arrivals(self, rng) -> Iterator[float]:
        """Absolute arrival times over the whole trace, in order.

        Lewis-Shedler thinning per segment: candidate gaps are
        Exp(peak_rate); a candidate at local time ``t`` survives with
        probability ``rate_at(t) / peak_rate``.  For stationary
        segments the acceptance test is a no-op draw skipped entirely,
        keeping the draw count (and thus the seeded stream) minimal.
        """
        offset = 0.0
        for seg in self.segments:
            peak = seg.peak_rate_hz
            if peak <= 0 or seg.duration_s <= 0:
                offset += seg.duration_s
                continue
            stationary = seg.kind != "diurnal" or seg.depth == 0
            t = 0.0
            while True:
                u = rng.random()
                # inverse-CDF exponential gap; guard log(0)
                t += -math.log(max(u, 1e-12)) / peak
                if t >= seg.duration_s:
                    break
                if stationary or rng.random() * peak <= seg.rate_at(t):
                    yield offset + t
            offset += seg.duration_s


def parse_segments(spec: str) -> tuple:
    """Parse the comma-separated segment spec (see module docstring)."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        fields = part.split(":")
        kind = fields[0]
        if kind in ("poisson", "burst"):
            if len(fields) != 3:
                raise ValueError(
                    f"segment {part!r}: expected {kind}:RATE:DURATION")
            out.append(TraceSegment(kind, float(fields[1]), float(fields[2])))
        elif kind == "diurnal":
            if len(fields) != 5:
                raise ValueError(
                    f"segment {part!r}: expected "
                    "diurnal:RATE:DURATION:DEPTH:PERIOD")
            depth = float(fields[3])
            if not 0.0 <= depth <= 1.0:
                raise ValueError(f"segment {part!r}: DEPTH must be in [0,1]")
            out.append(TraceSegment(
                kind, float(fields[1]), float(fields[2]),
                depth=depth, period_s=float(fields[4]),
            ))
        else:
            raise ValueError(f"unknown segment kind {kind!r} in {part!r}")
    if not out:
        raise ValueError("empty trace spec")
    return tuple(out)


def parse_churn(spec: str) -> tuple:
    """Parse the churn spec; events are returned sorted by time."""
    out = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"churn event {part!r}: expected AT:kill:FRACTION or "
                "AT:join:COUNT")
        at, kind, val = float(fields[0]), fields[1], fields[2]
        if kind == "kill":
            frac = float(val)
            if not 0.0 < frac <= 1.0:
                raise ValueError(f"churn {part!r}: FRACTION in (0,1]")
            out.append(ChurnEvent(at, "kill", fraction=frac))
        elif kind == "join":
            out.append(ChurnEvent(at, "join", count=int(val)))
        else:
            raise ValueError(f"unknown churn kind {kind!r} in {part!r}")
    return tuple(sorted(out, key=lambda e: (e.at_s, e.kind)))


def parse_trace(segments_spec: str, churn_spec: str = "") -> Trace:
    return Trace(
        segments=parse_segments(segments_spec),
        churn=parse_churn(churn_spec) if churn_spec else (),
    )


def churn_rounds(
    rounds: int, fraction: float, *, start_s: float = 0.0, every_s: float = 1.0
) -> tuple:
    """The dht_swarm_sim shape — N evenly spaced kill-and-replace rounds
    — expressed as the shared churn schedule."""
    return tuple(
        ChurnEvent(start_s + i * every_s, "kill", fraction=fraction)
        for i in range(int(rounds))
    )


def trace_to_json(trace: Trace) -> dict:
    """JSON-ready description for embedding in reports (deterministic)."""
    return {
        "segments": [
            {k: v for k, v in dataclasses.asdict(s).items()
             if v not in (0.0, "") or k in ("kind", "rate_hz", "duration_s")}
            for s in trace.segments
        ],
        "churn": [dataclasses.asdict(e) for e in trace.churn],
        "duration_s": trace.duration_s,
    }
