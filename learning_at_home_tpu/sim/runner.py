#!/usr/bin/env python
"""Macro-sim scenario orchestration + CLI (ISSUE 18 tentpole).

Runs a whole-system swarm — plain DHT peers, expert servers, gateways —
in ONE process on ONE virtual clock, driven by a
:mod:`~learning_at_home_tpu.sim.trace` arrival trace with scheduled
churn, and reports fleet throughput, shed fraction, TTFT/ITL tails
per trace segment, join/lookup health and placement-convergence cost as
one seeded, byte-deterministic JSON series.

The report deliberately contains NO wall-clock values, no ids derived
from ``os.urandom``/``uuid`` and no unsorted iteration — two runs at the
same seed and trace produce byte-identical canonical JSON (the
determinism contract tests/test_macro_sim.py pins).  Wall time goes to
stderr only.

Examples::

    python -m learning_at_home_tpu.sim.runner --nodes 200 --servers 48 \\
        --gateways 4 --experts 64 \\
        --trace "poisson:60:6,burst:420:3" --churn "4:kill:0.15" --check

    python -m learning_at_home_tpu.sim.runner --nodes 2048 --servers 256 \\
        --gateways 16 --experts 256 \\
        --trace "poisson:180:40,burst:900:10,diurnal:220:50:0.5:25" \\
        --churn "35:kill:0.1,60:join:26"     # the bench.py --macro-sim shape
"""

from __future__ import annotations

import argparse
import asyncio
import json
import random
import sys
import time
from typing import Optional

from learning_at_home_tpu.dht.routing import DHTID
from learning_at_home_tpu.sim.clock import (
    VirtualClock,
    installed_entropy,
    run_virtual,
)
from learning_at_home_tpu.sim.net import SIM_HOST, SimNetwork, spawn_node
from learning_at_home_tpu.sim.serving import (
    LinkModel,
    SimGateway,
    VirtualExpertServer,
    pair_rng,
)
from learning_at_home_tpu.sim.trace import Trace, parse_trace, trace_to_json
from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.telemetry import links_key, parse_links_value

# @runs_on("host") sites that legitimately execute ON the sim's event
# loop: the whole swarm is single-threaded on the virtual clock, so the
# "never block a loop" rationale behind the assertion does not apply
# (docs/CONCURRENCY.md "The macro-sim relaxation").
RELAXED_SITES = ("routing.cost_bias",)

DEFAULT_PREFIX = "sim_ffn"


def _pct(values, q) -> float:
    # shared percentile engine (ISSUE 19): "nearest" reproduces the
    # macro-sim's original nearest-rank formula exactly (banker's
    # rounding included) — the report stays byte-deterministic per seed
    # (pinned by tests/test_sketch.py against the old inline formula)
    from learning_at_home_tpu.utils.sketch import percentile

    return percentile(values, q, method="nearest", default=0.0)


def canonical_json(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


class Scenario:
    """One macro-sim run's mutable world state."""

    def __init__(self, cfg: dict, clock: VirtualClock):
        self.cfg = cfg
        self.clock = clock
        self.seed = int(cfg["seed"])
        self.prefix = cfg.get("prefix", DEFAULT_PREFIX)
        self.link_model = LinkModel(self.seed, n_clusters=cfg["clusters"])
        self.network = SimNetwork(latency_fn=self.link_model.delivery_delay)
        self.rng_ids = random.Random(f"{self.seed}|ids")
        self.rng_arrivals = random.Random(f"{self.seed}|arrivals")
        self.rng_work = random.Random(f"{self.seed}|work")
        self.rng_churn = random.Random(f"{self.seed}|churn")
        self.rng_probe = random.Random(f"{self.seed}|probe")
        self.plain_nodes: list = []
        self.servers: list = []            # VirtualExpertServer, spawn order
        self.servers_by_port: dict = {}    # port -> VirtualExpertServer
        self.gateways: list = []
        self.join_times: list = []
        self.join_failures = 0
        self.lookup_times: list = []
        self.lookup_hits = 0
        self.lookups_total = 0
        self.placement_rounds: list = []
        self.arrivals = 0
        self.arrivals_by_bucket: dict = {}
        self.shed_by_bucket: dict = {}
        self.killed_servers = 0
        self.joined_servers = 0

    def _next_node_id(self) -> DHTID:
        return DHTID(self.rng_ids.getrandbits(160))

    # ---- swarm construction ----

    async def _spawn_timed(self, peers, **kwargs):
        t0 = self.clock.monotonic()
        node = await spawn_node(
            self.network, initial_peers=peers,
            rpc_timeout=self.cfg["rpc_timeout"], clock=self.clock,
            node_id=self._next_node_id(), **kwargs,
        )
        self.join_times.append(self.clock.monotonic() - t0)
        if not any(
            b.peers for b in node.routing_table.buckets
        ) and peers:
            self.join_failures += 1
        return node

    async def build_swarm(self) -> None:
        cfg = self.cfg
        seed_node = await self._spawn_timed(())
        seed_ep = (SIM_HOST, seed_node.protocol.listen_port)
        self.plain_nodes.append(seed_node)
        n_plain = max(
            0, cfg["nodes"] - 1 - cfg["servers"] - cfg["gateways"]
        )
        batch = max(1, int(cfg["join_batch"]))

        async def join_many(n, **kwargs):
            out = []
            for i in range(0, n, batch):
                out.extend(await asyncio.gather(*(
                    self._spawn_timed((seed_ep,), **kwargs)
                    for _ in range(min(batch, n - i))
                )))
            return out

        self.plain_nodes.extend(await join_many(n_plain))
        server_nodes = await join_many(cfg["servers"])
        gateway_nodes = await join_many(cfg["gateways"])

        uids = [f"{self.prefix}.{i}" for i in range(cfg["experts"])]
        assign: dict[int, list] = {i: [] for i in range(cfg["servers"])}
        for i, uid in enumerate(uids):
            assign[i % cfg["servers"]].append(uid)
        for i, node in enumerate(server_nodes):
            srv = VirtualExpertServer(
                node, clock=self.clock, link_model=self.link_model,
                prefix=self.prefix, experts=assign[i],
                rng=random.Random(f"{self.seed}|srv{i}"),
                base_service_s=cfg["base_service_s"],
                per_token_s=cfg["per_token_s"],
                hb_period_s=cfg["hb_period_s"],
                record_ttl_s=cfg["record_ttl_s"],
            )
            self.servers.append(srv)
            self.servers_by_port[srv.port] = srv
        server_ports = sorted(self.servers_by_port)
        for srv in self.servers:
            k = server_ports.index(srv.port)
            ring = server_ports[k + 1:] + server_ports[:k]
            srv.peer_ports = ring[:16]
        # first declare lands BEFORE traffic so gateways can discover
        for i in range(0, len(self.servers), batch):
            await asyncio.gather(*(
                s.heartbeat_once() for s in self.servers[i:i + batch]
            ))
        for srv in self.servers:
            srv.start_heartbeat()
            srv.dht.start_maintenance(cfg["maintenance_s"])
        for i, node in enumerate(gateway_nodes):
            gw = SimGateway(
                f"gw{i}", node, clock=self.clock, network=self.network,
                link_model=self.link_model,
                servers_by_port=self.servers_by_port,
                prefix=self.prefix, n_experts=cfg["experts"],
                seed=self.seed, max_slots=cfg["slots"],
                fanout_k=cfg["fanout"],
                alive_ttl_s=cfg["alive_ttl_s"],
                mirror_period_s=cfg["mirror_period_s"],
                base_step_s=cfg["base_step_s"],
                max_pending=cfg["max_pending"] or None,
            )
            await gw.mirror.refresh_once()
            gw.start()
            node.start_maintenance(cfg["maintenance_s"])
            self.gateways.append(gw)

    # ---- the actors ----

    async def inject_arrivals(self, trace: Trace) -> None:
        cfg = self.cfg
        seg_ends, acc = [], 0.0
        for s in trace.segments:
            acc += s.duration_s
            seg_ends.append(acc)
        t_start = self.clock.monotonic()
        i = 0
        for t in trace.iter_arrivals(self.rng_arrivals):
            dt = (t_start + t) - self.clock.monotonic()
            if dt > 0:
                await asyncio.sleep(dt)
            seg_idx = next(
                j for j, end in enumerate(seg_ends) if t < end
            )
            bucket = f"seg{seg_idx}_{trace.segments[seg_idx].kind}"
            p_len = self.rng_work.randint(*cfg["prompt_len"])
            max_new = self.rng_work.randint(*cfg["max_new"])
            prompt = [
                self.rng_work.randrange(256) for _ in range(p_len)
            ]
            gw = self.gateways[i % len(self.gateways)]
            i += 1
            self.arrivals += 1
            self.arrivals_by_bucket[bucket] = (
                self.arrivals_by_bucket.get(bucket, 0) + 1
            )
            if not gw.submit_arrival(prompt, max_new, bucket):
                self.shed_by_bucket[bucket] = (
                    self.shed_by_bucket.get(bucket, 0) + 1
                )

    async def run_churn(self, trace: Trace) -> None:
        t_start = self.clock.monotonic()
        for ev in trace.churn:
            dt = (t_start + ev.at_s) - self.clock.monotonic()
            if dt > 0:
                await asyncio.sleep(dt)
            if ev.kind == "kill":
                alive = [s for s in self.servers if s.alive]
                n_kill = max(1, int(len(alive) * ev.fraction))
                for srv in self.rng_churn.sample(alive, min(n_kill, len(alive))):
                    await srv.kill(self.network)
                    self.killed_servers += 1
            elif ev.kind == "join":
                await self._join_servers(ev.count)

    async def _join_servers(self, count: int) -> None:
        """Replacement capacity: new servers adopt the experts with the
        fewest alive hosts (sorted for determinism)."""
        cfg = self.cfg
        coverage: dict[str, int] = {}
        for uid in (f"{self.prefix}.{i}" for i in range(cfg["experts"])):
            coverage[uid] = 0
        for srv in self.servers:
            if srv.alive:
                for uid in srv.experts:
                    if uid in coverage:
                        coverage[uid] += 1
        ranked = sorted(coverage, key=lambda u: (coverage[u], u))
        per = max(1, cfg["experts"] // max(1, cfg["servers"]))
        seed_ep = (SIM_HOST, self.plain_nodes[0].protocol.listen_port)
        for j in range(int(count)):
            node = await self._spawn_timed((seed_ep,))
            take = ranked[j * per:(j + 1) * per] or ranked[:per]
            idx = len(self.servers)
            srv = VirtualExpertServer(
                node, clock=self.clock, link_model=self.link_model,
                prefix=self.prefix, experts=list(take),
                rng=random.Random(f"{self.seed}|srv{idx}"),
                base_service_s=cfg["base_service_s"],
                per_token_s=cfg["per_token_s"],
                hb_period_s=cfg["hb_period_s"],
                record_ttl_s=cfg["record_ttl_s"],
            )
            srv.peer_ports = sorted(
                p for p, s in self.servers_by_port.items() if s.alive
            )[:16]
            self.servers.append(srv)
            self.servers_by_port[srv.port] = srv
            await srv.heartbeat_once()
            srv.start_heartbeat()
            self.joined_servers += 1

    async def probe_lookups(self) -> None:
        cfg = self.cfg
        while True:
            await asyncio.sleep(cfg["lookup_period_s"])
            uid = f"{self.prefix}.{self.rng_probe.randrange(cfg['experts'])}"
            gw = self.gateways[self.rng_probe.randrange(len(self.gateways))]
            t0 = self.clock.monotonic()
            records = await gw.dht.get(uid)
            self.lookup_times.append(self.clock.monotonic() - t0)
            self.lookups_total += 1
            hit = False
            for _sk, (value, _exp) in sorted(
                records.items(), key=lambda kv: str(kv[0])
            ):
                if isinstance(value, (list, tuple)) and len(value) == 2:
                    srv = self.servers_by_port.get(int(value[1]))
                    if srv is not None and srv.alive and uid in srv.experts:
                        hit = True
                        break
            if hit:
                self.lookup_hits += 1

    # ---- placement (real analysis/placement.py over DHT-read links) ----

    async def build_placement_snapshot(self) -> dict:
        experts: dict[str, str] = {}
        for srv in sorted(self.servers, key=lambda s: s.port):
            if not srv.alive:
                continue
            ep = f"{SIM_HOST}:{srv.port}"
            for uid in srv.experts:
                experts.setdefault(uid, ep)
        activations: dict[str, int] = {}
        coact: dict[str, int] = {}
        for gw in self.gateways:
            for uid, n in gw.activations.items():
                activations[uid] = activations.get(uid, 0) + n
            for (u, v), n in gw.coact.items():
                key = f"{u}|{v}"
                coact[key] = coact.get(key, 0) + n
        links: dict[str, dict] = {}
        recs = await self.gateways[0].dht.get(links_key(self.prefix))
        for subkey in sorted(recs, key=str):
            value, _exp = recs[subkey]
            if not (isinstance(subkey, str) and subkey.startswith("@")):
                continue
            parsed = parse_links_value(value)
            if parsed:
                links[subkey[1:]] = {
                    dst: [ent["rtt_s"], ent["bw_bps"]]
                    for dst, ent in sorted(parsed.items())
                }
        return {
            "experts": experts,
            "activations": activations,
            "coact": coact,
            "links": links,
        }

    async def run_placement(self) -> None:
        from learning_at_home_tpu.analysis.placement import solve

        cfg = self.cfg
        while True:
            await asyncio.sleep(cfg["placement_period_s"])
            snapshot = await self.build_placement_snapshot()
            plan = solve(
                snapshot, seed=self.seed,
                max_moves=cfg["placement_moves"],
            )
            by_ep = {
                f"{SIM_HOST}:{p}": s for p, s in self.servers_by_port.items()
            }
            applied = 0
            for mv in plan["moves"]:
                src = by_ep.get(mv["from"])
                dst = by_ep.get(mv["to"])
                if src is None or dst is None or not dst.alive:
                    continue
                if mv["uid"] in src.experts:
                    src.experts.remove(mv["uid"])
                    dst.experts.append(mv["uid"])
                    applied += 1
            self.placement_rounds.append({
                "t": round(self.clock.monotonic(), 3),
                "cost_before": plan["cost_before"],
                "cost_after": plan["cost_after"],
                "moves": len(plan["moves"]),
                "applied": applied,
            })

    # ---- teardown + report ----

    async def shutdown(self) -> None:
        for gw in self.gateways:
            gw.mirror.stop()
        for srv in self.servers:
            if srv.alive:
                await srv.kill(self.network)
        for node in (
            self.plain_nodes
            + [s.dht for s in self.servers]
            + [g.dht for g in self.gateways]
        ):
            await node.shutdown()

    def report(self, trace: Trace) -> dict:
        cfg = self.cfg
        ttfts = [v for gw in self.gateways for (_b, v) in gw.ttfts]
        itls = [v for gw in self.gateways for (_b, v) in gw.itls]
        completed = sum(gw.completed for gw in self.gateways)
        errored = sum(gw.errored for gw in self.gateways)
        shed = sum(gw.shed for gw in self.gateways)
        tokens = sum(gw.tokens_served for gw in self.gateways)
        v_end = round(self.clock.monotonic(), 3)
        buckets = {}
        for bucket in sorted(self.arrivals_by_bucket):
            b_ttft = [
                v for gw in self.gateways
                for (b, v) in gw.ttfts if b == bucket
            ]
            b_itl = [
                v for gw in self.gateways
                for (b, v) in gw.itls if b == bucket
            ]
            buckets[bucket] = {
                "arrivals": self.arrivals_by_bucket[bucket],
                "shed": self.shed_by_bucket.get(bucket, 0),
                "ttft_p50_ms": round(_pct(b_ttft, 50) * 1e3, 1),
                "ttft_p99_ms": round(_pct(b_ttft, 99) * 1e3, 1),
                "itl_p50_ms": round(_pct(b_itl, 50) * 1e3, 1),
                "itl_p99_ms": round(_pct(b_itl, 99) * 1e3, 1),
            }
        placement = {
            "rounds": self.placement_rounds,
            "cost_initial": (
                self.placement_rounds[0]["cost_before"]
                if self.placement_rounds else None
            ),
            "cost_final": (
                self.placement_rounds[-1]["cost_after"]
                if self.placement_rounds else None
            ),
        }
        return {
            "config": {
                "seed": self.seed,
                "nodes": cfg["nodes"],
                "servers": cfg["servers"],
                "gateways": cfg["gateways"],
                "experts": cfg["experts"],
                "slots": cfg["slots"],
                "fanout": cfg["fanout"],
                "trace": trace_to_json(trace),
            },
            "swarm": {
                "joins": len(self.join_times),
                "join_failures": self.join_failures,
                "join_mean_ms": round(
                    sum(self.join_times) / len(self.join_times) * 1e3, 2
                ) if self.join_times else 0.0,
                "join_p99_ms": round(_pct(self.join_times, 99) * 1e3, 2),
                "killed": self.killed_servers,
                "joined": self.joined_servers,
            },
            "traffic": {
                "arrivals": self.arrivals,
                "completed": completed,
                "errored": errored,
                "shed": shed,
                "shed_fraction": round(
                    shed / self.arrivals, 4
                ) if self.arrivals else 0.0,
                "tokens_served": tokens,
                "fleet_tok_s": round(tokens / v_end, 2) if v_end else 0.0,
                "ttft_p50_ms": round(_pct(ttfts, 50) * 1e3, 1),
                "ttft_p99_ms": round(_pct(ttfts, 99) * 1e3, 1),
                "itl_p50_ms": round(_pct(itls, 50) * 1e3, 1),
                "itl_p99_ms": round(_pct(itls, 99) * 1e3, 1),
                "segments": buckets,
            },
            "dht": {
                "lookups": self.lookups_total,
                "hit_rate": round(
                    self.lookup_hits / self.lookups_total, 4
                ) if self.lookups_total else 1.0,
                "lookup_p50_ms": round(
                    _pct(self.lookup_times, 50) * 1e3, 2
                ),
                "lookup_p99_ms": round(
                    _pct(self.lookup_times, 99) * 1e3, 2
                ),
                "rpcs": {k: self.network.rpcs[k]
                         for k in sorted(self.network.rpcs)},
            },
            "routing": {
                "selection_rounds": sum(
                    gw.selection_rounds for gw in self.gateways
                ),
                "no_alive_rounds": sum(
                    gw.no_alive_rounds for gw in self.gateways
                ),
                "bias_applied": sum(
                    gw.cost.bias_applied for gw in self.gateways
                ),
                "link_fallbacks": sum(
                    gw.cost.link_fallbacks for gw in self.gateways
                ),
            },
            "placement": placement,
            "virtual_duration_s": v_end,
        }


async def _run(cfg: dict, clock: VirtualClock, trace: Trace) -> dict:
    sc = Scenario(cfg, clock)
    await sc.build_swarm()
    churn_task = asyncio.get_running_loop().create_task(
        sc.run_churn(trace), name="churn"
    )
    probe_task = asyncio.get_running_loop().create_task(
        sc.probe_lookups(), name="probe"
    )
    placement_task = asyncio.get_running_loop().create_task(
        sc.run_placement(), name="placement"
    )
    await sc.inject_arrivals(trace)
    await churn_task
    for gw in sc.gateways:
        await gw.drain_and_stop()
    for task in (probe_task, placement_task):
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
    report = sc.report(trace)
    await sc.shutdown()
    return report


def run_macro_sim(
    *,
    seed: int = 0,
    nodes: int = 200,
    servers: int = 48,
    gateways: int = 4,
    experts: int = 64,
    trace: str = "poisson:60:6,burst:360:3",
    churn: str = "4:kill:0.15",
    slots: int = 64,
    fanout: int = 2,
    clusters: int = 4,
    prompt_len: tuple = (4, 12),
    max_new: tuple = (8, 16),
    rpc_timeout: float = 0.8,
    join_batch: int = 32,
    hb_period_s: float = 15.0,
    record_ttl_s: float = 45.0,
    alive_ttl_s: float = 3.0,
    mirror_period_s: float = 5.0,
    maintenance_s: float = 60.0,
    base_service_s: float = 0.004,
    per_token_s: float = 0.0002,
    base_step_s: float = 0.002,
    lookup_period_s: float = 1.0,
    placement_period_s: float = 20.0,
    placement_moves: int = 12,
    max_pending: int = 0,
) -> dict:
    """One seeded macro-sim scenario → the deterministic report dict."""
    if servers + gateways + 1 > nodes:
        raise ValueError("nodes must cover servers + gateways + seed node")
    cfg = dict(
        seed=seed, nodes=nodes, servers=servers, gateways=gateways,
        experts=experts, slots=slots, fanout=fanout, clusters=clusters,
        prompt_len=tuple(prompt_len), max_new=tuple(max_new),
        rpc_timeout=rpc_timeout, join_batch=join_batch,
        hb_period_s=hb_period_s, record_ttl_s=record_ttl_s,
        alive_ttl_s=alive_ttl_s, mirror_period_s=mirror_period_s,
        maintenance_s=maintenance_s, base_service_s=base_service_s,
        per_token_s=per_token_s, base_step_s=base_step_s,
        lookup_period_s=lookup_period_s,
        placement_period_s=placement_period_s,
        placement_moves=placement_moves, max_pending=max_pending,
    )
    parsed = parse_trace(trace, churn)
    clock = VirtualClock(step=0.0)
    entropy = random.Random(f"{seed}|entropy")
    with sanitizer.allowed(*RELAXED_SITES), installed_entropy(entropy):
        return run_virtual(_run(cfg, clock, parsed), clock=clock)


def check_report(report: dict, args) -> list:
    """Regression floors; returns failure strings (empty = pass).

    The numeric floors/ceilings are declarative :class:`Threshold` specs
    run through the shared SLO engine (utils/slo.py, ISSUE 19) — same
    evaluator as the rebalancer's gate and the loadgen floors; bounds
    and failure messages unchanged.  The arrivals-accounting identity
    stays inline (it is an equality over three fields, not a
    threshold)."""
    from learning_at_home_tpu.utils.slo import Threshold, evaluate_thresholds

    failures = []
    tr = report["traffic"]
    accounted = tr["completed"] + tr["shed"] + tr["errored"]
    if accounted != tr["arrivals"]:
        failures.append(
            f"accounting: completed+shed+errored {accounted} "
            f"!= arrivals {tr['arrivals']}"
        )
    specs = [
        Threshold("errored_zero", "traffic.errored", "<=", 0.0),
        Threshold("completed_floor", "traffic.completed", ">=",
                  float(args.min_completed)),
        Threshold("shed_floor", "traffic.shed_fraction", ">=",
                  float(args.shed_min)),
        Threshold("shed_ceiling", "traffic.shed_fraction", "<=",
                  float(args.shed_max)),
        Threshold("ttft_p99_ceiling", "traffic.ttft_p99_ms", "<=",
                  float(args.ttft_p99_max_ms)),
        Threshold("hit_rate_floor", "dht.hit_rate", ">=",
                  float(args.hit_rate_floor)),
        Threshold("join_failures_zero", "swarm.join_failures", "<=", 0.0),
    ]
    messages = {
        "errored_zero": f"errored streams: {tr['errored']}",
        "completed_floor": (
            f"completed {tr['completed']} < floor {args.min_completed}"
        ),
        "shed_floor": (
            f"shed_fraction {tr['shed_fraction']} < {args.shed_min} "
            "(the burst never pushed admission into shedding)"
        ),
        "shed_ceiling": (
            f"shed_fraction {tr['shed_fraction']} > {args.shed_max}"
        ),
        "ttft_p99_ceiling": (
            f"ttft_p99_ms {tr['ttft_p99_ms']} > {args.ttft_p99_max_ms}"
        ),
        "hit_rate_floor": (
            f"lookup hit_rate {report['dht']['hit_rate']} < "
            f"{args.hit_rate_floor}"
        ),
        "join_failures_zero": (
            f"join_failures: {report['swarm']['join_failures']}"
        ),
    }
    for v in evaluate_thresholds(report, specs):
        failures.append(messages.get(v["slo"], v["detail"]))
    return failures


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--nodes", type=int, default=200)
    ap.add_argument("--servers", type=int, default=48)
    ap.add_argument("--gateways", type=int, default=4)
    ap.add_argument("--experts", type=int, default=64)
    ap.add_argument("--trace", type=str, default="poisson:60:6,burst:360:3",
                    help="arrival segments (sim/trace.py grammar)")
    ap.add_argument("--churn", type=str, default="4:kill:0.15",
                    help="churn events AT:kill:FRAC / AT:join:COUNT")
    ap.add_argument("--slots", type=int, default=64)
    ap.add_argument("--fanout", type=int, default=2)
    ap.add_argument("--clusters", type=int, default=4)
    ap.add_argument("--placement-period", type=float, default=20.0)
    ap.add_argument("--placement-moves", type=int, default=12)
    ap.add_argument("--check", action="store_true",
                    help="assert the regression floors; print MACRO_SIM_OK")
    ap.add_argument("--min-completed", type=int, default=50)
    ap.add_argument("--shed-min", type=float, default=0.0005)
    ap.add_argument("--shed-max", type=float, default=0.6)
    ap.add_argument("--ttft-p99-max-ms", type=float, default=60_000.0)
    ap.add_argument("--hit-rate-floor", type=float, default=0.95)
    args = ap.parse_args(argv)
    t0 = time.monotonic()
    report = run_macro_sim(
        seed=args.seed, nodes=args.nodes, servers=args.servers,
        gateways=args.gateways, experts=args.experts, trace=args.trace,
        churn=args.churn, slots=args.slots, fanout=args.fanout,
        clusters=args.clusters,
        placement_period_s=args.placement_period,
        placement_moves=args.placement_moves,
    )
    wall = time.monotonic() - t0
    print(canonical_json(report))
    print(f"macro-sim wall: {wall:.1f}s for "
          f"{report['virtual_duration_s']}s virtual", file=sys.stderr)
    if args.check:
        failures = check_report(report, args)
        if failures:
            for f in failures:
                print(f"MACRO_SIM_FAIL: {f}", file=sys.stderr)
            return 1
        tr = report["traffic"]
        print(
            f"MACRO_SIM_OK nodes={args.nodes} arrivals={tr['arrivals']} "
            f"shed_fraction={tr['shed_fraction']} "
            f"ttft_p99_ms={tr['ttft_p99_ms']} "
            f"hit_rate={report['dht']['hit_rate']}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
