"""The virtual clock and its seams (ISSUE 18 tentpole).

``analysis/verify.py`` already proved the pattern: the gateway scheduler
and the server lifecycle read time through module-level ``_monotonic`` /
``_sleep`` indirections precisely so a test can swap a deterministic
clock in.  This module promotes that ad-hoc seam into a first-class
contract:

- :class:`VirtualClock` — one mutable ``now`` shared by every consumer.
  Calling the instance advances by ``step`` and returns the new ``now``
  (the verify.py shape, so its worlds keep working unchanged);
  :meth:`VirtualClock.monotonic` reads without advancing (the macro-sim
  shape, where ONLY the event loop advances time).
- :func:`installed_clock` — a context manager that patches every known
  clock seam in the codebase (scheduler, admission, lifecycle, DHT
  maintenance + routing-table staleness, client routing TTLs, and the
  DHT wall-clock ``get_dht_time`` used for record expirations) and
  restores them on exit.  The full seam list is the contract documented
  in docs/SIMULATION.md — new time reads in covered modules MUST go
  through the module's ``_monotonic`` seam, not ``time.monotonic``.
- :class:`VirtualClockEventLoop` — an asyncio event loop whose timers
  run on the virtual clock: ``select(timeout)`` ADVANCES the clock by
  ``timeout`` instead of blocking, so ``asyncio.sleep`` / ``wait_for``
  / timeout handles all fire deterministically and a simulated hour
  costs only the CPU of the callbacks inside it.  Single-threaded with
  a FIFO ready queue and a deterministic timer heap, so a seeded
  scenario replays byte-identically.
"""

from __future__ import annotations

import asyncio
import contextlib
import importlib
import selectors
import time
from typing import Iterator, Optional

# Epoch for the virtual wall clock backing ``get_dht_time`` — an
# arbitrary fixed instant so DHT record expirations are deterministic
# and never race the host's real wall clock.
DEFAULT_EPOCH = 1_700_000_000.0


class VirtualClock:
    """Deterministic clock with both read styles.

    ``step`` exists for verify.py's worlds, which patch the INSTANCE
    itself over ``_monotonic`` so every read nudges time forward and
    TTL/pacing branches get exercised.  The macro-sim uses ``step=0``:
    reads are pure, and time advances only through the event loop
    (:class:`VirtualClockEventLoop`) or an explicit :meth:`advance`.
    """

    def __init__(self, step: float = 1.0, *, start: float = 0.0,
                 epoch: float = DEFAULT_EPOCH):
        self.now = float(start)
        self.step = float(step)
        self.epoch = float(epoch)

    def __call__(self) -> float:
        self.now += self.step
        return self.now

    # ---- the macro-sim read/advance surface ----

    def monotonic(self) -> float:
        """Read without advancing (drop-in for ``time.monotonic``)."""
        return self.now

    def time(self) -> float:
        """Virtual wall clock (drop-in for ``time.time`` /
        ``get_dht_time``): a fixed epoch plus virtual elapsed time."""
        return self.epoch + self.now

    def advance(self, dt: float) -> float:
        """Move time forward by ``dt`` (negative deltas are ignored —
        the clock is monotonic by construction)."""
        if dt > 0:
            self.now += float(dt)
        return self.now

    def sleep(self, dt: float) -> None:
        """Synchronous sleep = pure time advance (drop-in for the
        ``lifecycle._sleep`` seam)."""
        self.advance(dt)


class WallClock:
    """The production clock behind the same surface, so code written
    against the seam (e.g. ``dht_swarm_sim.run_size``) runs unchanged
    on real time."""

    monotonic = staticmethod(time.monotonic)
    sleep = staticmethod(time.sleep)
    time = staticmethod(time.time)

    def __call__(self) -> float:
        return time.monotonic()


# ---- the seam registry ----
#
# (module, attribute, clock method) triples.  ``installed_clock`` patches
# each module-level seam with the bound clock method and restores the
# original on exit.  Modules are imported lazily so importing sim.clock
# stays cheap.
SEAMS: tuple[tuple[str, str, str], ...] = (
    ("learning_at_home_tpu.gateway.scheduler", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.gateway.admission", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.server.lifecycle", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.server.lifecycle", "_sleep", "sleep"),
    ("learning_at_home_tpu.dht.node", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.dht.routing", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.client.routing", "_monotonic", "monotonic"),
    # flight-recorder event timestamps + SLO burn-rate windows (ISSUE
    # 19): both must advance on the virtual clock so sim scenarios emit
    # deterministic flight rings and drive burn-rate transitions without
    # wall-clock waits.
    ("learning_at_home_tpu.utils.flight", "_monotonic", "monotonic"),
    ("learning_at_home_tpu.utils.slo", "_monotonic", "monotonic"),
    # get_dht_time() — record expirations.  Every importer does
    # ``from ... import get_dht_time``, so the function stays put and
    # only its internal _time_source is swapped.
    ("learning_at_home_tpu.utils.timed_storage", "_time_source", "time"),
)


@contextlib.contextmanager
def installed_clock(clock: VirtualClock) -> Iterator[VirtualClock]:
    """Patch every registered clock seam to ``clock``; restore on exit.

    Reentrant-unsafe by design (nested installs would restore in the
    wrong order); the sim installs once around a whole scenario.
    """
    saved: list[tuple[object, str, object]] = []
    try:
        for mod_name, attr, method in SEAMS:
            mod = importlib.import_module(mod_name)
            saved.append((mod, attr, getattr(mod, attr)))
            setattr(mod, attr, getattr(clock, method))
        yield clock
    finally:
        for mod, attr, orig in reversed(saved):
            setattr(mod, attr, orig)


@contextlib.contextmanager
def installed_entropy(rng) -> Iterator[None]:
    """Patch the DHT's entropy seam (``dht.routing._urandom``) to a
    seeded source; restore on exit.  Bucket-refresh targets steer which
    peers a lookup visits, so OS entropy there is the one remaining
    nondeterminism in an otherwise fully seeded swarm."""
    import learning_at_home_tpu.dht.routing as dht_routing

    def seeded_urandom(n: int) -> bytes:
        return rng.getrandbits(8 * n).to_bytes(n, "big")

    orig = dht_routing._urandom
    dht_routing._urandom = seeded_urandom
    try:
        yield
    finally:
        dht_routing._urandom = orig


class _VirtualTimeSelector(selectors.DefaultSelector):
    """A selector that trades blocking for time travel.

    The sim has no real sockets (the DHT fabric is in-process), so
    ``select(timeout)`` never has events to return; instead it advances
    the shared virtual clock by exactly the timeout the event loop
    computed from its timer heap.  A ``None`` timeout means the loop
    would block forever with nothing scheduled — in a sim that is a
    deadlock, so fail fast instead of spinning.
    """

    def __init__(self, clock: VirtualClock):
        super().__init__()
        self._clock = clock

    def select(self, timeout: Optional[float] = None):
        if timeout is None:
            raise RuntimeError(
                "virtual-time deadlock: event loop blocked with no "
                "scheduled timers and no ready callbacks"
            )
        if timeout > 0:
            self._clock.advance(timeout)
        return []


class VirtualClockEventLoop(asyncio.SelectorEventLoop):
    """``asyncio.SelectorEventLoop`` on virtual time.

    ``time()`` reads the virtual clock, and the selector advances it in
    place of blocking, so every ``asyncio.sleep`` / timeout handle /
    ``loop.call_later`` fires at its virtual deadline with zero wall
    waiting.  Determinism: one thread, FIFO ready queue, and a timer
    heap ordered by (when, tiebreak counter) — all reproducible.
    """

    def __init__(self, clock: VirtualClock):
        super().__init__(selector=_VirtualTimeSelector(clock))
        self.clock = clock

    def time(self) -> float:
        return self.clock.now


def run_virtual(coro, *, clock: Optional[VirtualClock] = None):
    """Run ``coro`` to completion on a fresh virtual-time loop with every
    clock seam installed.  Returns the coroutine's result; the caller
    keeps the clock (pass one in) to read the final virtual time."""
    clock = clock if clock is not None else VirtualClock(step=0.0)
    loop = VirtualClockEventLoop(clock)
    try:
        with installed_clock(clock):
            asyncio.set_event_loop(loop)
            return loop.run_until_complete(coro)
    finally:
        asyncio.set_event_loop(None)
        loop.close()
