"""In-process DHT delivery fabric (lifted from experiments/dht_swarm_sim).

Real sockets cap a single box at a few hundred nodes (fd limits, kernel
accept queues, per-connection buffers) and drown the measurement in
transport noise.  Here every node runs the REAL ``DHTNode`` /
``DHTProtocol`` code — routing tables, iterative lookups, adaptive
timeouts, batched stores — and only the one-request/one-reply exchange
(``DHTProtocol._transport``) is swapped for an in-process delivery shim,
so the control-plane numbers this reports are the protocol's, not the
kernel's.  Dead peers behave like dead sockets: the caller waits its own
adaptive timeout and gets nothing.

ISSUE 18 generalizes the fabric for the macro-sim: per-link latency via
``latency_fn(src_port, dst_port)`` (the macro-sim plugs its seeded
RTT model in; ``dht_swarm_sim`` keeps the constant default), and RTT
measurement through a pluggable clock so the EMAs read VIRTUAL elapsed
time under :class:`~learning_at_home_tpu.sim.clock.VirtualClockEventLoop`.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from learning_at_home_tpu.dht.node import DHTNode
from learning_at_home_tpu.dht.protocol import (
    ADAPTIVE_TIMEOUT_FLOOR,
    ADAPTIVE_TIMEOUT_MULT,
    DHTProtocol,
)
from learning_at_home_tpu.dht.routing import Endpoint

SIM_HOST = "127.0.0.1"


class SimNetwork:
    """Endpoint → protocol registry plus the delivery fabric.

    Delivery to a registered peer invokes its REAL ``_serve`` directly
    (requests/replies are plain msgpack-able dicts on both sides of the
    real wire, so passing them by reference preserves semantics).
    Delivery to an unregistered endpoint — a killed node — costs the
    caller its own adaptive timeout, exactly like a dead socket."""

    def __init__(
        self,
        latency: float = 0.0,
        *,
        latency_fn: Optional[Callable[[int, int], float]] = None,
    ):
        self.latency = latency
        self.latency_fn = latency_fn
        self._by_port: dict[int, DHTProtocol] = {}
        self._next_port = 1
        self.rpcs: dict[str, int] = {}

    def register(self, proto: DHTProtocol) -> int:
        port = self._next_port
        self._next_port += 1
        self._by_port[port] = proto
        return port

    def unregister(self, proto: DHTProtocol) -> None:
        if proto.listen_port is not None:
            self._by_port.pop(proto.listen_port, None)

    def link_latency_s(self, src_port: Optional[int], dst_port: int) -> float:
        """Total request+reply delivery delay for one RPC.  ``latency_fn``
        (when set) models the round trip for the (src, dst) pair; the
        constant fallback preserves dht_swarm_sim's historical meaning
        of ``--latency`` (one sleep per delivery)."""
        if self.latency_fn is not None and src_port is not None:
            return self.latency_fn(src_port, dst_port)
        return self.latency

    async def deliver(
        self, src: "SimDHTProtocol", endpoint: Endpoint, msg_type: str,
        meta: dict,
    ) -> Optional[dict]:
        self.rpcs[msg_type] = self.rpcs.get(msg_type, 0) + 1
        dest = self._by_port.get(int(endpoint[1]))
        if dest is None:
            # dead peer: the caller's OWN adaptive budget bounds the wait
            await asyncio.sleep(src.timeout_for(endpoint))
            return None
        delay = self.link_latency_s(src.listen_port, int(endpoint[1]))
        if delay > 0:
            await asyncio.sleep(delay)
        return dest._serve(msg_type, meta, SIM_HOST)


class SimDHTProtocol(DHTProtocol):
    """The real protocol with the socket layer replaced.

    Overrides exactly the transport seam (``_transport``) plus
    listen/shutdown; envelope building, RPC accounting, reply parsing
    and the adaptive-timeout CONTRACT are the production code.  The RTT
    EMA normally lives in the connection pool, so the sim keeps its own
    per-endpoint EMA with the same fold rule (timeouts count)."""

    def __init__(self, network: SimNetwork, *args, clock=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.network = network
        self.rtt_ema: dict[Endpoint, float] = {}
        # the RTT stopwatch: wall by default (dht_swarm_sim measures real
        # event-loop latency), the shared VirtualClock under the macro-sim
        self._now = clock.monotonic if clock is not None else time.monotonic

    async def listen(self, host: str, port: int) -> int:
        self.listen_port = self.network.register(self)
        return self.listen_port

    async def shutdown(self) -> None:
        self.network.unregister(self)
        self._pools.close()  # never opened a socket; releases bookkeeping

    def timeout_for(self, endpoint: Endpoint) -> float:
        ema = self.rtt_ema.get(endpoint)
        if ema is not None:
            return min(
                max(ADAPTIVE_TIMEOUT_MULT * ema, ADAPTIVE_TIMEOUT_FLOOR),
                self.rpc_timeout,
            )
        return self.rpc_timeout

    async def _transport(
        self, endpoint: Endpoint, msg_type: str, meta: dict
    ) -> Optional[dict]:
        t0 = self._now()
        reply = await self.network.deliver(self, endpoint, msg_type, meta)
        elapsed = self._now() - t0
        ema = self.rtt_ema.get(endpoint)
        # timeouts fold too (the pool's latency-signal rule): a peer that
        # outgrows its budget raises its own budget next call
        self.rtt_ema[endpoint] = (
            elapsed if ema is None else 0.8 * ema + 0.2 * elapsed
        )
        if reply is None:
            raise asyncio.TimeoutError(f"sim peer {endpoint} unreachable")
        return reply


async def spawn_node(
    network: SimNetwork,
    initial_peers=(),
    rpc_timeout: float = 0.8,
    clock=None,
    **node_kwargs,
) -> DHTNode:
    node = DHTNode(rpc_timeout=rpc_timeout, **node_kwargs)
    node.protocol = SimDHTProtocol(
        network, node.node_id, node.routing_table, node.storage, rpc_timeout,
        clock=clock,
    )
    await node.protocol.listen(SIM_HOST, 0)
    if initial_peers:
        await node.bootstrap(initial_peers)
    return node
