"""Ring attention: sequence/context parallelism over the ICI ring.

The reference has NO sequence parallelism (SURVEY.md §5.7 — its scale axis
is the expert dimension), but long-context is first-class in this
framework: sequences longer than one chip's HBM are sharded over a ``seq``
mesh axis, and attention runs as a ring — each device holds one Q chunk
resident and streams K/V chunks around the ring with ``lax.ppermute``,
accumulating output with the online-softmax (flash) recurrence.  Compute
for chunk r overlaps the transfer of chunk r+1 on TPU (XLA schedules the
collective-permute concurrently with the einsums).

Memory per device: O(S_local * d + S_local^2 / n) instead of O(S^2);
communication: n-1 permutes of the K/V chunk, bandwidth-optimal on a ring.

Causal masking across chunks is by chunk index: a Q chunk attends fully to
earlier K/V chunks, triangularly to its own, not at all to later ones —
masked lanes still run (SPMD) but contribute -inf scores.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from learning_at_home_tpu.utils.jax_compat import shard_map


def _online_softmax_update(o, l, m, scores, v_chunk):
    """One flash-attention accumulation step.

    o: [B, Sq, H, hd] running (unnormalized) output
    l: [B, H, Sq]     running softmax denominator
    m: [B, H, Sq]     running max
    scores: [B, H, Sq, Sk]; v_chunk: [B, Sk, H, hd]
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])  # [B, H, Sq, Sk]
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_chunk)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, l_new, m_new


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Call INSIDE shard_map.  q/k/v: [B, S_local, H, hd]; returns the local
    output chunk [B, S_local, H, hd].
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s_local, h, hd = q.shape
    scale = 1.0 / np.sqrt(hd)

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, s_local, h, hd), jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    tri = jnp.tril(jnp.ones((s_local, s_local), bool))

    def body(r, carry):
        o, l, m, kc, vc = carry
        src = (my - r) % n  # which global chunk kc/vc currently is
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q32, kc.astype(jnp.float32)) * scale
        )
        if causal:
            full = src < my  # earlier chunk: attend to everything
            diag = src == my  # own chunk: lower-triangular
            mask = jnp.where(
                full, True, jnp.where(diag, tri[None, None], False)
            )
            scores = jnp.where(mask, scores, -jnp.inf)
        o, l, m = _online_softmax_update(o, l, m, scores, vc)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o, l, m, kc, vc

    o, l, m, _, _ = lax.fori_loop(0, n, body, (o0, l0, m0, k, v))
    # fully-masked rows (can't happen with causal diag) would give l=0
    denom = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    out = o / denom.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_local_zigzag(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
) -> jax.Array:
    """Causal ring attention over the ZIGZAG chunk layout — balanced work.

    The contiguous layout computes every (Q-chunk, K-chunk) block and
    masks the acausal half: ~2× the necessary FLOPs, and skipping the
    masked blocks does not help wall time because every ring step still
    has at least one device with a live block (steps are lock-stepped by
    the ppermute).  The zigzag layout (each device holds global chunks
    ``i`` and ``2n-1-i``) makes the live-block count UNIFORM: every
    device computes exactly one half-chunk block against the arriving
    K/V pair each step (plus the triangular diagonals on step 0), so the
    causal FLOPs savings become wall-clock savings.

    Call INSIDE shard_map.  q/k/v: [B, 2c, H, hd] where the local rows
    are the concatenation (chunk ``my``, chunk ``2n-1-my``) — callers
    permute the global sequence into this layout (``make_ring_attention``
    with ``layout="zigzag"`` does it).  Returns the local output in the
    same zigzag layout.
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s2, h, hd = q.shape
    c = s2 // 2
    scale = 1.0 / np.sqrt(hd)
    tri = jnp.tril(jnp.ones((c, c), bool))
    neg = -jnp.inf

    q32 = q.astype(jnp.float32)
    q_lo, q_hi = q32[:, :c], q32[:, c:]

    def blk(qh, kc_, vc_, olm, mask=None):
        o, l, m = olm
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", qh, kc_.astype(jnp.float32)) * scale
        )
        if mask is not None:
            scores = jnp.where(mask[None, None], scores, neg)
        return _online_softmax_update(o, l, m, scores, vc_)

    def zeros_olm():
        return (
            jnp.zeros((b, c, h, hd), jnp.float32),
            jnp.zeros((b, h, c), jnp.float32),
            jnp.full((b, h, c), -jnp.inf, jnp.float32),
        )

    def body(r, carry):
        lo, hi, kc, vc = carry
        src = (my - r) % n  # the device whose chunk pair just arrived
        klo, khi = kc[:, :c], kc[:, c:]
        vlo, vhi = vc[:, :c], vc[:, c:]
        # chunk indices: Q = (my, 2n-1-my); K = (src, 2n-1-src).
        # q_hi vs klo: klo's index src < n <= 2n-1-my — ALWAYS full attend
        hi = blk(q_hi, klo, vlo, hi)
        # exactly one more block is causally live:
        #   src == my: both diagonals (step 0)
        #   src <  my: q_lo vs klo, full   (klo earlier than chunk my)
        #   src >  my: q_hi vs khi, full   (2n-1-src < 2n-1-my)
        def diag_case(lo, hi):
            return blk(q_lo, klo, vlo, lo, tri), blk(q_hi, khi, vhi, hi, tri)

        def off_diag(lo, hi):
            return lax.cond(
                src < my,
                lambda lo, hi: (blk(q_lo, klo, vlo, lo), hi),
                lambda lo, hi: (lo, blk(q_hi, khi, vhi, hi)),
                lo, hi,
            )

        lo, hi = lax.cond(src == my, diag_case, off_diag, lo, hi)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return lo, hi, kc, vc

    lo, hi, _, _ = lax.fori_loop(0, n, body, (zeros_olm(), zeros_olm(), k, v))

    def norm(olm):
        o, l, m = olm
        denom = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
        return o / denom.transpose(0, 2, 1)[..., None]

    return jnp.concatenate([norm(lo), norm(hi)], axis=1).astype(q.dtype)


def zigzag_indices(seq_len: int, n_shards: int) -> np.ndarray:
    """Global row order realizing the zigzag layout: device i's contiguous
    shard = (chunk i, chunk 2n-1-i), chunk size seq_len/(2n)."""
    if seq_len % (2 * n_shards):
        raise ValueError(
            f"zigzag needs seq_len divisible by 2*{n_shards}, got {seq_len}"
        )
    c = seq_len // (2 * n_shards)
    order = []
    for i in range(n_shards):
        order.append(np.arange(i * c, (i + 1) * c))
        j = 2 * n_shards - 1 - i
        order.append(np.arange(j * c, (j + 1) * c))
    return np.concatenate(order)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "seq", causal: bool = True,
    layout: str = "contiguous", pre_permuted: bool = False,
):
    """shard_map-wrapped ring attention over global [B, S, H, hd] arrays
    sharded on the sequence axis.

    ``layout="zigzag"`` (causal only) runs the balanced minimum-FLOPs
    ring over the zigzag chunk layout (~2× less attention compute at
    scale).  By default each call permutes q/k/v in and the output back
    (4 cross-shard gathers per call); models with several attention
    layers should instead permute the residual stream ONCE at the model
    boundary (see ``DMoETransformerLM.apply``) and pass
    ``pre_permuted=True`` so the ring consumes and produces the zigzag
    order directly.  ``"contiguous"`` is the straightforward ring
    (computes and masks every block; supports non-causal)."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis (axes: {mesh.axis_names})"
        )
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"layout must be contiguous|zigzag, got {layout!r}")
    if layout == "zigzag" and not causal:
        raise ValueError("zigzag layout only balances CAUSAL attention")
    n_shards = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    local_fn = (
        functools.partial(ring_attention_local_zigzag, axis_name=axis_name)
        if layout == "zigzag"
        else functools.partial(
            ring_attention_local, axis_name=axis_name, causal=causal
        )
    )
    inner = shard_map(
        local_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )

    def fn(q, k, v):
        if not (q.shape == k.shape == v.shape):
            raise ValueError(
                f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
            )
        if q.shape[1] % n_shards:
            raise ValueError(
                f"sequence length {q.shape[1]} must divide across the "
                f"{n_shards} shards of mesh axis {axis_name!r}"
            )
        if layout == "zigzag" and q.shape[1] % (2 * n_shards):
            # also guards the pre_permuted path: each shard needs an even
            # local chunk to split into its lo/hi halves
            raise ValueError(
                f"zigzag needs seq_len divisible by 2*{n_shards} shards, "
                f"got {q.shape[1]}"
            )
        if layout == "zigzag" and not pre_permuted:
            zig = zigzag_indices(q.shape[1], n_shards)
            inv = np.argsort(zig)
            out = inner(q[:, zig], k[:, zig], v[:, zig])
            return out[:, inv]
        return inner(q, k, v)

    return fn
