"""Ring attention: sequence/context parallelism over the ICI ring.

The reference has NO sequence parallelism (SURVEY.md §5.7 — its scale axis
is the expert dimension), but long-context is first-class in this
framework: sequences longer than one chip's HBM are sharded over a ``seq``
mesh axis, and attention runs as a ring — each device holds one Q chunk
resident and streams K/V chunks around the ring with ``lax.ppermute``,
accumulating output with the online-softmax (flash) recurrence.  Compute
for chunk r overlaps the transfer of chunk r+1 on TPU (XLA schedules the
collective-permute concurrently with the einsums).

Memory per device: O(S_local * d + S_local^2 / n) instead of O(S^2);
communication: n-1 permutes of the K/V chunk, bandwidth-optimal on a ring.

Causal masking across chunks is by chunk index: a Q chunk attends fully to
earlier K/V chunks, triangularly to its own, not at all to later ones —
masked lanes still run (SPMD) but contribute -inf scores.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _online_softmax_update(o, l, m, scores, v_chunk):
    """One flash-attention accumulation step.

    o: [B, Sq, H, hd] running (unnormalized) output
    l: [B, H, Sq]     running softmax denominator
    m: [B, H, Sq]     running max
    scores: [B, H, Sq, Sk]; v_chunk: [B, Sk, H, hd]
    """
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])  # [B, H, Sq, Sk]
    l_new = l * correction + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_chunk)
    o_new = o * correction.transpose(0, 2, 1)[..., None] + pv
    return o_new, l_new, m_new


def ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "seq",
    causal: bool = True,
) -> jax.Array:
    """Attention over a sequence sharded on ``axis_name``.

    Call INSIDE shard_map.  q/k/v: [B, S_local, H, hd]; returns the local
    output chunk [B, S_local, H, hd].
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, s_local, h, hd = q.shape
    scale = 1.0 / np.sqrt(hd)

    q32 = q.astype(jnp.float32)
    o0 = jnp.zeros((b, s_local, h, hd), jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    m0 = jnp.full((b, h, s_local), -jnp.inf, jnp.float32)
    tri = jnp.tril(jnp.ones((s_local, s_local), bool))

    def body(r, carry):
        o, l, m, kc, vc = carry
        src = (my - r) % n  # which global chunk kc/vc currently is
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q32, kc.astype(jnp.float32)) * scale
        )
        if causal:
            full = src < my  # earlier chunk: attend to everything
            diag = src == my  # own chunk: lower-triangular
            mask = jnp.where(
                full, True, jnp.where(diag, tri[None, None], False)
            )
            scores = jnp.where(mask, scores, -jnp.inf)
        o, l, m = _online_softmax_update(o, l, m, scores, vc)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o, l, m, kc, vc

    o, l, m, _, _ = lax.fori_loop(0, n, body, (o0, l0, m0, k, v))
    # fully-masked rows (can't happen with causal diag) would give l=0
    denom = jnp.maximum(l, jnp.finfo(jnp.float32).tiny)
    out = o / denom.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "seq", causal: bool = True
):
    """shard_map-wrapped ring attention over global [B, S, H, hd] arrays
    sharded on the sequence axis."""
    if axis_name not in mesh.axis_names:
        raise ValueError(
            f"mesh has no {axis_name!r} axis (axes: {mesh.axis_names})"
        )
    n_shards = mesh.shape[axis_name]
    spec = P(None, axis_name, None, None)

    inner = shard_map(
        functools.partial(
            ring_attention_local, axis_name=axis_name, causal=causal
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )

    def fn(q, k, v):
        if not (q.shape == k.shape == v.shape):
            raise ValueError(
                f"q/k/v shapes must match, got {q.shape}/{k.shape}/{v.shape}"
            )
        if q.shape[1] % n_shards:
            raise ValueError(
                f"sequence length {q.shape[1]} must divide across the "
                f"{n_shards} shards of mesh axis {axis_name!r}"
            )
        return inner(q, k, v)

    return fn
