"""Device-mesh helpers for the ICI tier.

The TPU-native communication backend (SURVEY.md §2.3): intra-pod expert
parallelism rides XLA collectives over ICI inside ``shard_map`` programs;
everything off-slice goes through the DHT + RPC tier.  These helpers build
the meshes both tiers hang off.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: dict[str, int] | None = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named mesh; axis sizes must multiply to the device count.

    Default: all devices on a single ``expert`` axis (pure expert
    parallelism — the reference's scaling dimension).  A typical pod-scale
    layout is ``{"data": 4, "expert": 8}``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"expert": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh axes {axes} need {int(np.prod(sizes))} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes a token batch is sharded over (everything but model axes)."""
    return tuple(a for a in mesh.axis_names if a in ("data", "expert"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens sharded across all data-bearing axes; with a ``seq`` axis the
    sequence dimension (axis 1) is context-parallel too."""
    if "seq" in mesh.axis_names:
        return NamedSharding(mesh, P(data_axes(mesh), "seq"))
    return NamedSharding(mesh, P(data_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def expert_sharding(mesh: Mesh) -> NamedSharding:
    """Stacked per-expert params: leading axis split over 'expert'."""
    return NamedSharding(mesh, P("expert"))


def opt_state_shardings(abstract_opt_state, param_shardings, params, mesh: Mesh):
    """Shardings for an optimizer state mirroring the param tree.

    Optimizer states (optax) embed sub-trees shaped like the params (mu/nu
    in Adam); those leaves inherit the matching param's sharding — found by
    matching each opt-state leaf's key-path SUFFIX against param key-paths
    AND requiring the leaf's shape to equal the param's shape.  The shape
    check matters for factored optimizers (adafactor): its ``v_row/v_col/v``
    sub-trees reuse the param key paths but hold reduced-rank statistics,
    which must be replicated, not given the param's (higher-rank) spec.
    Everything else (step counts, scalars) is replicated.  Needed because
    ``jit(opt.init)`` does not propagate NamedShardings to its outputs, and
    a checkpoint restored onto mismatched devices poisons the train step.

    LIMIT of the heuristic (round-2 advisor): the suffix+shape match is
    positional-blind — an optimizer whose state leaf coincidentally has
    the param's exact shape but different per-axis SEMANTICS (e.g. a
    transposed statistic) would silently inherit the param's spec.  The
    optimizers in use (adamw, adafactor, ops.fused_adafactor) are covered
    by tests; new optimizers with same-shape/different-semantics state
    need an explicit sharding override instead of this helper.
    """
    shard_map_ = {
        jax.tree_util.keystr(path): s
        for path, s in jax.tree_util.tree_flatten_with_path(param_shardings)[0]
    }
    shape_map = {
        jax.tree_util.keystr(path): tuple(p.shape)
        for path, p in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    if shard_map_.keys() != shape_map.keys():
        raise ValueError(
            "param_shardings and params trees disagree: "
            f"{sorted(shard_map_.keys() ^ shape_map.keys())[:4]} — a silent "
            "mispairing here would mis-shard the optimizer state"
        )
    param_map = {k: (shard_map_[k], shape_map[k]) for k in shard_map_}
    repl = NamedSharding(mesh, P())

    def assign(path, leaf):
        for i in range(len(path)):
            suffix = jax.tree_util.keystr(path[i:])
            if suffix in param_map:
                sharding, shape = param_map[suffix]
                if tuple(leaf.shape) == shape:
                    return sharding
                return repl
        return repl

    return jax.tree_util.tree_map_with_path(assign, abstract_opt_state)
