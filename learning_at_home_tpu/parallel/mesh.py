"""Device-mesh helpers for the ICI tier.

The TPU-native communication backend (SURVEY.md §2.3): intra-pod expert
parallelism rides XLA collectives over ICI inside ``shard_map`` programs;
everything off-slice goes through the DHT + RPC tier.  These helpers build
the meshes both tiers hang off.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: dict[str, int] | None = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a named mesh; axis sizes must multiply to the device count.

    Default: all devices on a single ``expert`` axis (pure expert
    parallelism — the reference's scaling dimension).  A typical pod-scale
    layout is ``{"data": 4, "expert": 8}``.
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"expert": len(devices)}
    sizes = list(axes.values())
    if int(np.prod(sizes)) != len(devices):
        raise ValueError(
            f"mesh axes {axes} need {int(np.prod(sizes))} devices, "
            f"have {len(devices)}"
        )
    dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, tuple(axes))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes a token batch is sharded over (everything but model axes)."""
    return tuple(a for a in mesh.axis_names if a in ("data", "expert"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Tokens sharded across all data-bearing axes, features replicated."""
    return NamedSharding(mesh, P(data_axes(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def expert_sharding(mesh: Mesh) -> NamedSharding:
    """Stacked per-expert params: leading axis split over 'expert'."""
    return NamedSharding(mesh, P("expert"))
