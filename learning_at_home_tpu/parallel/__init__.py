from learning_at_home_tpu.parallel.mesh import (
    batch_sharding,
    data_axes,
    expert_sharding,
    make_mesh,
    replicated,
)
from learning_at_home_tpu.parallel.multihost import (
    host_local_array_to_global,
    initialize_multihost,
)
from learning_at_home_tpu.parallel.sharded_moe import ShardedMixtureOfExperts

__all__ = [
    "batch_sharding",
    "data_axes",
    "expert_sharding",
    "host_local_array_to_global",
    "initialize_multihost",
    "make_mesh",
    "replicated",
    "ShardedMixtureOfExperts",
]
