"""ShardedMixtureOfExperts: the pod-scale expert-parallel MoE FFN.

This is [BJ] config 5 — the intra-pod realization of the reference's DMoE
(SURVEY.md §2.2 "Expert parallelism", §7 M5): experts live sharded across
the ``expert`` mesh axis as ONE stacked parameter pytree; a token batch,
sharded across all devices, is routed by top-k gating, capacity-bucketed,
and exchanged with **two ``lax.all_to_all`` collectives inside a single
``shard_map`` program** — not N point-to-point RPCs.  Fault tolerance
inside the collective is capacity-dropping (SURVEY.md §7 "k-of-n inside a
collective"); true peer failure handling stays on the DHT/RPC tier.

Data layout through the program (per device; E=global experts, e=local
experts, ep=expert-axis size, n=local tokens, C=capacity, d=model dim):

    x [n,d] ── gate ──▶ plan [n,E,C] ── dispatch ──▶ [E,C,d]
      reshape [ep,e,C,d] ── all_to_all ──▶ [ep,e,C,d]   (tokens arrive)
      regroup [e,ep*C,d] ── batched expert FFN (MXU) ──▶ [e,ep*C,d]
      regroup [ep,e,C,d] ── all_to_all ──▶ [E,C,d]       (outputs return)
      combine ──▶ y [n,d]

Expert compute is one batched einsum over the local expert stack — large,
dense, bfloat16-friendly: exactly what the MXU wants.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learning_at_home_tpu.utils.jax_compat import shard_map

from learning_at_home_tpu.ops.moe_dispatch import (
    choose_dispatch_impl,
    combine_outputs,
    combine_outputs_expert_choice,
    combine_outputs_indexed,
    compute_capacity,
    dispatch_tokens_expert_choice,
    expert_choice_gating,
    dispatch_tokens,
    dispatch_tokens_indexed,
    top_k_gating,
    top_k_gating_indices,
)
from learning_at_home_tpu.parallel.mesh import data_axes

Params = dict[str, jax.Array]


class ShardedMixtureOfExperts:
    """Expert-parallel MoE FFN over a mesh with an ``expert`` axis.

    Parameters (``init_params``):
      gate  [d, E]            — replicated
      w1    [E, d, ffn]       — sharded on axis 0 over ``expert``
      b1    [E, ffn]
      w2    [E, ffn, d]
      b2    [E, d]
    """

    def __init__(
        self,
        mesh: Mesh,
        hidden_dim: int,
        num_experts: int,
        k: int = 2,
        capacity_factor: float = 1.25,
        ffn_mult: int = 4,
        dtype: Any = jnp.bfloat16,
        param_dtype: Any = jnp.float32,
        dispatch_impl: str = "auto",
        router_jitter: float = 0.0,
        gating: str = "topk",
    ):
        if dispatch_impl not in ("auto", "gather", "onehot"):
            raise ValueError(
                "dispatch_impl must be 'auto', 'gather' or 'onehot', "
                f"got {dispatch_impl!r}"
            )
        if gating not in ("topk", "expert_choice"):
            raise ValueError(
                f"gating must be 'topk' or 'expert_choice', got {gating!r}"
            )
        if gating == "expert_choice" and router_jitter:
            raise ValueError(
                "router_jitter applies only to token-choice top-k gating; "
                "expert_choice is balanced by construction — pass "
                "router_jitter=0 (a silently ignored setting would make "
                "recipe comparisons lie)"
            )
        if "expert" not in mesh.axis_names:
            raise ValueError("mesh must have an 'expert' axis")
        self.mesh = mesh
        self.ep = mesh.shape["expert"]
        # optional tensor parallelism: a 'model' mesh axis shards each
        # expert's FFN dimension; the second einsum produces partial sums
        # that one psum over 'model' reduces (Megatron-style column+row
        # split, per expert)
        self.tp = mesh.shape.get("model", 1)
        if num_experts % self.ep:
            raise ValueError(
                f"num_experts={num_experts} must divide over expert axis "
                f"size {self.ep}"
            )
        self.hidden_dim = hidden_dim
        self.num_experts = num_experts
        self.k = k
        self.capacity_factor = capacity_factor
        self.ffn_dim = ffn_mult * hidden_dim
        if self.ffn_dim % self.tp:
            raise ValueError(
                f"ffn_dim={self.ffn_dim} must divide over model axis size {self.tp}"
            )
        self.dtype = dtype
        self.param_dtype = param_dtype
        # 'gather' moves tokens with index gathers/scatters (O(E*C*d) data
        # movement); 'onehot' uses the GShard-style [n,E,C] einsums
        # (O(n*E*C*d) MXU work); 'auto' picks per static shape via
        # ops.moe_dispatch.choose_dispatch_impl (v5e-measured crossover).
        self.dispatch_impl = dispatch_impl
        # deterministic multiplicative routing noise (see
        # ops.moe_dispatch.router_jitter) — breaks routing collapse when
        # many rows are near-identical (byte-level data near init)
        self.router_jitter = router_jitter
        # 'topk' = token-choice with capacity dropping; 'expert_choice' =
        # each expert picks its top-C tokens (perfectly balanced, no aux
        # loss, no capacity drops; routing is batch-dependent — see
        # ops.moe_dispatch.expert_choice_gating for the causality note)
        self.gating = gating
        self._shard = data_axes(mesh)  # axes the token batch is split over

    # ---- parameters ----

    def init_params(self, rng: jax.Array, device_put: bool = True) -> Params:
        """``device_put=False`` returns the raw tree (for callers that
        stack layers under vmap and shard the stacked result themselves)."""
        kg, k1, k2 = jax.random.split(rng, 3)
        d, e, f = self.hidden_dim, self.num_experts, self.ffn_dim
        init = jax.nn.initializers.lecun_normal()
        # near-zero router init: logits start ~flat so top-k routing is
        # near-uniform and the capacity drop starts low (lecun-scale gate
        # measured 0.40-0.48 dropped at init on the 256-expert flagship;
        # small init gives balance a head start and the aux loss keeps it)
        gate_init = jax.nn.initializers.normal(stddev=1e-2)
        params = {
            "gate": gate_init(kg, (d, e), self.param_dtype),
            "w1": init(k1, (e, d, f), self.param_dtype),
            "b1": jnp.zeros((e, f), self.param_dtype),
            "w2": init(k2, (e, f, d), self.param_dtype),
            "b2": jnp.zeros((e, d), self.param_dtype),
        }
        if not device_put:
            return params
        return jax.device_put(params, self.param_shardings())

    def _expert_param_specs(self) -> dict[str, P]:
        if self.tp > 1:
            return {
                "w1": P("expert", None, "model"),  # column split
                "b1": P("expert", "model"),
                "w2": P("expert", "model", None),  # row split
                "b2": P("expert"),
            }
        return {"w1": P("expert"), "b1": P("expert"),
                "w2": P("expert"), "b2": P("expert")}

    def param_specs(self, stacked: bool = False) -> dict[str, P]:
        """PartitionSpec per param; ``stacked=True`` prepends a ``None``
        dim for callers that stack layers of MoE params (lax.scan)."""
        specs = dict(self._expert_param_specs())
        specs["gate"] = P()
        if stacked:
            specs = {name: P(None, *spec) for name, spec in specs.items()}
        return specs

    def param_shardings(self, stacked: bool = False) -> dict[str, NamedSharding]:
        return {
            name: NamedSharding(self.mesh, spec)
            for name, spec in self.param_specs(stacked).items()
        }

    # ---- the sharded program ----

    def __call__(
        self, params: Params, x: jax.Array,
        jitter_salt: jax.Array | int = 0,
        token_mask: jax.Array | None = None,
    ) -> tuple[jax.Array, dict]:
        """x: [n_tokens, d] sharded over the data axes.  Returns (y, aux).

        ``jitter_salt``: static int or traced scalar (e.g. the layer index
        inside a scan-over-layers) folded into the router-jitter key so
        each call site draws a decorrelated noise pattern.

        ``token_mask`` [n_tokens] bool (optional, traced): False =
        padding — routed to no expert, claims no capacity, contributes
        zero output (the batched-decode fix; see ops.moe_dispatch).  The
        None path compiles exactly the unmasked program — no masking ops
        on the training hot path."""
        n_global = x.shape[0]
        n_shards = 1
        for a in self._shard:
            n_shards *= self.mesh.shape[a]
        if n_global % n_shards:
            raise ValueError(
                f"token count {n_global} must divide across {n_shards} shards"
            )
        n_local = n_global // n_shards
        capacity = compute_capacity(
            n_local, self.num_experts, self.k, self.capacity_factor
        )
        if self.gating == "expert_choice":
            # expert-choice selects top-C TOKENS per expert, so C can
            # never exceed the shard's token count; clamping HERE keeps
            # the all_to_all reshapes consistent with the plan shape
            capacity = min(capacity, n_local)

        in_specs = [
            self.param_specs(),
            P(self._shard),
            P(),  # jitter salt: replicated scalar
        ]
        out_specs = (
            P(self._shard),
            {"aux_loss": P(), "router_z_loss": P(), "dropped_fraction": P()},
        )
        if token_mask is None:
            fn = shard_map(
                functools.partial(self._local_forward, capacity=capacity),
                mesh=self.mesh,
                in_specs=tuple(in_specs),
                out_specs=out_specs,
                check_vma=False,
            )
            return fn(params, x, jnp.asarray(jitter_salt, jnp.int32))
        fn = shard_map(
            lambda p, xx, s, m: self._local_forward(
                p, xx, s, capacity=capacity, token_mask=m
            ),
            mesh=self.mesh,
            in_specs=tuple(in_specs) + (P(self._shard),),
            out_specs=out_specs,
            check_vma=False,
        )
        return fn(
            params, x, jnp.asarray(jitter_salt, jnp.int32), token_mask
        )

    def _local_forward(
        self, params: Params, x: jax.Array, jitter_salt: jax.Array,
        capacity: int, token_mask: jax.Array | None = None,
    ) -> tuple[jax.Array, dict]:
        e_local = self.num_experts // self.ep
        d = self.hidden_dim
        compute = self.dtype

        impl = self.dispatch_impl
        if impl == "auto":
            impl = choose_dispatch_impl(
                x.shape[0], self.num_experts * capacity
            )

        # 1) gate + routing plan for MY tokens (logits in f32 for stable softmax)
        logits = (x.astype(compute) @ params["gate"].astype(compute)).astype(
            jnp.float32
        )
        if self.gating == "expert_choice":
            plan = expert_choice_gating(logits, capacity, token_mask)
            x_send = dispatch_tokens_expert_choice(x.astype(compute), plan)
        elif impl == "gather":
            plan = top_k_gating_indices(
                logits, self.k, capacity, jitter=self.router_jitter,
                jitter_salt=jitter_salt, token_mask=token_mask,
            )
            x_send = dispatch_tokens_indexed(x.astype(compute), plan)
        else:
            plan = top_k_gating(
                logits, self.k, capacity, jitter=self.router_jitter,
                jitter_salt=jitter_salt, token_mask=token_mask,
            )
            x_send = dispatch_tokens(x.astype(compute), plan)  # [E, C, d]
        x_send = x_send.reshape(self.ep, e_local, capacity, d)
        x_recv = jax.lax.all_to_all(
            x_send, "expert", split_axis=0, concat_axis=0, tiled=False
        )  # [ep, e_local, C, d] — slice j = tokens from expert-row peer j

        # 3) batched expert FFN on the MXU (one einsum over the local stack).
        # With tensor parallelism the FFN dim f is sharded over 'model':
        # column-split w1 -> local activations, row-split w2 -> partial
        # sums, one psum completes the contraction (Megatron pattern).
        xe = x_recv.transpose(1, 0, 2, 3).reshape(e_local, self.ep * capacity, d)
        w1 = params["w1"].astype(compute)
        b1 = params["b1"].astype(compute)
        w2 = params["w2"].astype(compute)
        b2 = params["b2"].astype(compute)
        h = jax.nn.gelu(jnp.einsum("egd,edf->egf", xe, w1) + b1[:, None, :])
        ye = jnp.einsum("egf,efd->egd", h, w2)
        if self.tp > 1:
            ye = jax.lax.psum(ye, "model")
        ye = ye + b2[:, None, :]

        # 4) return outputs to their source devices
        y_send = ye.reshape(e_local, self.ep, capacity, d).transpose(1, 0, 2, 3)
        y_recv = jax.lax.all_to_all(
            y_send, "expert", split_axis=0, concat_axis=0, tiled=False
        ).reshape(self.num_experts, capacity, d)

        # 5) gate-weighted combine for MY tokens
        if self.gating == "expert_choice":
            y = combine_outputs_expert_choice(
                y_recv, plan, x.shape[0]
            ).astype(x.dtype)
        elif impl == "gather":
            y = combine_outputs_indexed(y_recv, plan).astype(x.dtype)
        else:
            y = combine_outputs(y_recv, plan).astype(x.dtype)

        axes = self._shard
        # router z-loss (ST-MoE): penalizes logit magnitude so the softmax
        # stays in a well-conditioned regime at scale (real tokens only)
        lse2 = jax.scipy.special.logsumexp(logits, axis=-1) ** 2
        if token_mask is None:
            router_z = jnp.mean(lse2)
        else:
            v = token_mask.astype(lse2.dtype)
            router_z = (lse2 * v).sum() / jnp.maximum(v.sum(), 1.0)
        if self.gating == "expert_choice":
            # perfectly balanced by construction: no balance auxiliary;
            # "dropped_fraction" reports tokens selected by NO expert
            aux_loss = jnp.float32(0)
            dropped = plan.uncovered_fraction
        else:
            aux_loss = plan.aux_loss
            dropped = plan.dropped_fraction
        aux = {
            "aux_loss": jax.lax.pmean(aux_loss, axes),
            "router_z_loss": jax.lax.pmean(router_z, axes),
            "dropped_fraction": jax.lax.pmean(dropped, axes),
        }
        return y, aux
