"""Multi-host pod initialization: the DCN-tier bring-up for the ICI tier.

A v5e-32 (or larger) slice spans multiple hosts; JAX exposes all chips as
one device set once every process calls ``jax.distributed.initialize``.
After :func:`initialize_multihost`, the existing mesh builders
(``parallel.mesh.make_mesh``) operate over the GLOBAL device list and the
sharded MoE / ring attention programs run unchanged — XLA routes the
all_to_all/ppermute over ICI within the slice.

This module is deliberately thin: the framework's cross-host *data plane*
inside a pod IS XLA's (SURVEY.md §2.3 tier a); only process bring-up and
per-host batch feeding are host code.  Anything OUTSIDE the pod slice
keeps using the DHT + RPC tier (tier b).

Typical launch (one process per host)::

    initialize_multihost("10.0.0.1:9999", num_processes=4, process_id=i)
    mesh = make_mesh({"data": 4, "expert": 8})       # 32 global chips
    ids_local = next(batches)                         # this host's rows
    ids = host_local_array_to_global(ids_local, mesh) # form the global batch

``initialize_multihost`` itself needs real multiple processes and is not
testable in this sandbox; the batch-assembly helper IS tested on the
8-device virtual CPU mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from learning_at_home_tpu.parallel.mesh import batch_sharding


def initialize_multihost(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_ids: Optional[Sequence[int]] = None,
) -> None:
    """Join this process to the pod's JAX distributed runtime.

    Call ONCE per process before any other JAX API.  After it returns,
    ``jax.devices()`` lists every chip in the slice and
    ``jax.local_devices()`` this host's chips."""
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )


def host_local_array_to_global(
    local_batch: np.ndarray, mesh: Mesh, spec: Optional[P] = None
) -> jax.Array:
    """Assemble per-host batch shards into one global sharded array.

    Each host passes ITS rows; the default layout is exactly
    ``batch_sharding(mesh)`` — the same sharding the train step expects
    (including the sequence axis when the mesh has one), so no resharding
    happens on step entry.

    Constraint: the batch axes of the mesh must be process-major (build
    the mesh with the batch-bearing axes FIRST, as in the examples) so
    each process's local rows cover its addressable shards;
    ``jax.make_array_from_process_local_data`` raises otherwise."""
    sharding = (
        NamedSharding(mesh, spec) if spec is not None else batch_sharding(mesh)
    )
    return jax.make_array_from_process_local_data(
        sharding, np.asarray(local_batch)
    )
