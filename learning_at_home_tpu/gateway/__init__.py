"""Serving gateway: continuous batching + cross-user expert-set
coalescing + admission control over the swarm dispatch path.

See docs/PROTOCOL.md ("Gateway RPC family"), docs/CONCURRENCY.md (slot
table ownership) and README.md (serving quick-start).
"""

from learning_at_home_tpu.gateway.admission import AdmissionController
from learning_at_home_tpu.gateway.coalesce import ExpertCoalescer
from learning_at_home_tpu.gateway.frontdoor import Gateway, GatewayClient
from learning_at_home_tpu.gateway.scheduler import SlotScheduler, StreamState
from learning_at_home_tpu.models.kv_pages import PagedKVCache, PagePressure

__all__ = [
    "AdmissionController",
    "ExpertCoalescer",
    "Gateway",
    "GatewayClient",
    "PagePressure",
    "PagedKVCache",
    "SlotScheduler",
    "StreamState",
]
