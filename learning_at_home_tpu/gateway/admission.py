"""Admission control: shed load BEFORE quality collapses.

Three saturation signals, all cheap to read at admit time:

- **gateway occupancy** — pending streams waiting for a slot.  Slots
  full is normal (that is what continuous batching is for); an unbounded
  pending queue is not: past ``max_pending`` every accepted stream only
  inflates time-to-first-token, so the gateway sheds with a retry-after
  instead (docs/PROTOCOL.md "Gateway RPC family").
- **expert-server queue depth** — the swarm's own backpressure, read
  from the ``load.<prefix>`` DHT heartbeats the servers already publish
  (utils/telemetry.py, the same feed PR 8's routing cost model eats).
  When the WORST advertised queue exceeds ``max_server_queue``, admitting
  more decode work would pile onto servers that are already drowning.
- **KV page pressure** (paged decoder only) — a stream that cannot get
  the physical pages its prompt + budget will occupy would only churn
  the preemption path; when ``pages_needed`` exceeds the pool's free +
  reclaimable headroom (net of a one-page-per-active-slot reserve), the
  gateway sheds with a retry-after instead.  The headroom read is a
  plain-int peek at counters the ``lah-gw-decode`` thread owns — the
  same benign monitoring race as the slot mask, no lock
  (docs/CONCURRENCY.md invariant 12).

Shedding is ALWAYS a well-formed busy frame carrying ``retry_after_s``
(docs/PROTOCOL.md "Gateway RPC family"), never an error frame — page
exhaustion is backpressure, not failure.

The DHT read is a blocking control-plane round trip, so it runs on this
controller's own ``lah-gw-admission`` daemon thread on a fixed period;
``admit()`` itself only reads cached floats and the scheduler's counters
— safe to call from the front door's event loop.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional

logger = logging.getLogger(__name__)

# Clock seam: sim/clock.py swaps this for a virtual clock so the inline
# refresh path (maybe_refresh) paces on simulated time.
_monotonic = time.monotonic


class AdmissionController:
    """Accept/shed decisions for one gateway."""

    def __init__(
        self,
        scheduler,
        *,
        max_pending: Optional[int] = None,
        max_server_queue: float = 64.0,
        load_fn: Optional[Callable[[], dict]] = None,
        refresh_period_s: float = 2.0,
    ):
        self.scheduler = scheduler
        if max_pending is None:
            try:
                max_pending = int(
                    os.environ.get(
                        "LAH_GW_MAX_PENDING",
                        str(4 * scheduler.decoder.max_slots),
                    )
                )
            except ValueError:
                max_pending = 4 * scheduler.decoder.max_slots
        self.max_pending = max_pending
        self.max_server_queue = float(max_server_queue)
        self._load_fn = load_fn
        self.refresh_period_s = refresh_period_s
        self._server_queue_depth = 0.0  # worst advertised depth, cached
        self._last_refresh: Optional[float] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.shed_total = 0
        self.shed_pages_total = 0
        self.admitted_total = 0
        self.load_refresh_failures = 0

    # ---- background server-load watch ----

    def start(self) -> "AdmissionController":
        if self._load_fn is None or self._thread is not None:
            return self

        def watch() -> None:
            while not self._stop.wait(self.refresh_period_s):
                self._refresh_once()

        self._refresh_once()
        self._thread = threading.Thread(
            target=watch, name="lah-gw-admission", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.refresh_period_s + 1)
            self._thread = None

    def maybe_refresh(self) -> bool:
        """Inline alternative to :meth:`start` for single-threaded hosts
        (the macro-sim): refresh the cached worst-queue snapshot when
        ``refresh_period_s`` has elapsed on the clock seam.  Returns
        True when a refresh actually ran."""
        if self._load_fn is None:
            return False
        now = _monotonic()
        if (
            self._last_refresh is not None
            and now - self._last_refresh < self.refresh_period_s
        ):
            return False
        self._last_refresh = now
        self._refresh_once()
        return True

    def _refresh_once(self) -> None:
        try:
            loads = self._load_fn() or {}
            depths = [
                float(rec.get("q", 0.0))
                for rec in loads.values()
                if isinstance(rec, dict)
            ]
            self._server_queue_depth = max(depths) if depths else 0.0
        except Exception as e:
            self.load_refresh_failures += 1
            logger.warning("gateway server-load refresh failed: %s: %s",
                           type(e).__name__, e)

    @property
    def server_queue_depth(self) -> float:
        return self._server_queue_depth

    # ---- the admit-time decision (event-loop safe: no I/O, no waits) ----

    def admit(
        self, pages_needed: int = 0
    ) -> tuple[bool, Optional[float], Optional[str]]:
        """(accepted, retry_after_s, reason).  retry_after_s/reason are
        None on accept.  ``pages_needed`` is the stream's peak KV page
        footprint (0 = dense decoder / skip the page check)."""
        pending = self.scheduler.pending_count()
        if pending >= self.max_pending:
            self.shed_total += 1
            return (
                False,
                self.scheduler.estimate_retry_after_s(),
                f"gateway saturated: {pending} pending >= "
                f"max_pending {self.max_pending}",
            )
        if self._server_queue_depth > self.max_server_queue:
            self.shed_total += 1
            return (
                False,
                self.scheduler.estimate_retry_after_s(),
                f"expert servers saturated: worst advertised queue depth "
                f"{self._server_queue_depth:.0f} > {self.max_server_queue:.0f}",
            )
        if pages_needed > 0:
            headroom = self.scheduler.free_page_headroom()
            if headroom is not None and pages_needed > headroom:
                self.shed_total += 1
                self.shed_pages_total += 1
                return (
                    False,
                    self.scheduler.estimate_retry_after_s(),
                    f"KV page pressure: stream needs {pages_needed} pages, "
                    f"pool headroom {max(0, headroom)}",
                )
        self.admitted_total += 1
        return True, None, None

    def stats(self) -> dict:
        return {
            "max_pending": self.max_pending,
            "max_server_queue": self.max_server_queue,
            "server_queue_depth": self._server_queue_depth,
            "shed_total": self.shed_total,
            "shed_pages_total": self.shed_pages_total,
            "admitted_total": self.admitted_total,
            "load_refresh_failures": self.load_refresh_failures,
        }
