"""Cross-user expert-set coalescing for the gateway's decode step.

At every decode step the swarm decoder hands the MoE hook one row per
live stream.  Without coalescing each stream would pay its own pack-once
dispatch — per-peer RPC overhead × streams × layers × tokens.  The
coalescer previews each row's routed top-k expert set
(``RemoteMixtureOfExperts.preview_expert_sets``) and groups streams whose
sets OVERLAP (task-aware grouping, arXiv:2606.01007): one dispatch per
group slices its rows from one wire-cast batch per expert, so a popular
expert serves many users in one RPC.

Correctness does not depend on grouping: each group's dispatch reruns the
full per-row selection over its own rows (selection is row-independent),
and the gate-weighted combine is row-wise — grouped and ungrouped
per-stream outputs are bitwise equal (tests/test_gateway.py).  Replica
choice inside each dispatch reuses PR 8's ``RoutingCostModel`` untouched.

Groups are fired BEFORE any is joined, so disjoint groups' RPCs overlap
on the wire exactly like the training fan-out.
"""

from __future__ import annotations

import logging

import jax.numpy as jnp
import numpy as np

logger = logging.getLogger(__name__)


class ExpertCoalescer:
    """Stateful MoE-dispatch hook for :class:`SwarmKVDecoder`.

    ``coalesce=False`` degrades to one dispatch per stream — the
    ungrouped arm of the A/B and the bitwise-parity tests.  Counters are
    cumulative across calls; the gateway's metrics collector exports them
    as ``lah_gateway_*`` series (docs/OBSERVABILITY.md).
    """

    def __init__(self, coalesce: bool = True):
        self.coalesce = coalesce
        # serving-trace hook (ISSUE 19): ``fn(stream_id) -> trace | None``
        # — the gateway wires the scheduler's ``trace_of`` here so each
        # group dispatch's ``client.dispatch.{fire,join}`` spans nest
        # under the stream trace that anchored the group
        self.trace_lookup = None
        # one inc per fired group dispatch
        self.group_dispatches_total = 0
        # per-stream dispatches AVOIDED by grouping: Σ (group size - 1)
        self.coalesced_dispatches_total = 0
        self.rows_dispatched_total = 0
        self.preview_failures_total = 0

    def _group_trace(self, group):
        """First member stream's trace id (a coalesced dispatch serves
        many streams; the wire spans ride the anchoring member's trace)."""
        if self.trace_lookup is None:
            return None
        for s in group:
            trace = self.trace_lookup(s)
            if trace is not None:
                return trace
        return None

    # decoder hook signature: (layer, moe, gate_params, x_rows, row_streams)
    def dispatch(self, layer, moe, gate_params, x_rows, row_streams):
        x_rows = jnp.asarray(x_rows)
        logits_concat = moe.gate_logits(gate_params, x_rows)
        x_np = np.asarray(x_rows)
        logits_np = np.asarray(logits_concat)
        # stream -> its row indices, first-appearance order (prefill hands
        # many rows of one stream; decode hands one row per stream)
        stream_rows: dict = {}
        for r, s in enumerate(row_streams):
            stream_rows.setdefault(s, []).append(r)
        groups = self._group(moe, logits_np, stream_rows)
        # fire every group before joining any: disjoint groups' RPCs
        # overlap on the wire
        fired = []
        for group in groups:
            rows = np.asarray(
                sorted(r for s in group for r in stream_rows[s]), np.int64
            )
            fut = moe.dispatch_async(
                x_np[rows], logits_np[rows], store_session=False,
                trace=self._group_trace(group),
            )
            fired.append((rows, fut))
        out = np.zeros((x_np.shape[0], x_np.shape[1]), x_np.dtype)
        for rows, fut in fired:
            y, idx, mask, _cid = fut.join()
            mixed = moe._combine(y, idx, mask, jnp.asarray(logits_np[rows]))
            out[rows] = np.asarray(mixed, x_np.dtype)
        self.group_dispatches_total += len(groups)
        self.coalesced_dispatches_total += len(stream_rows) - len(groups)
        self.rows_dispatched_total += int(x_np.shape[0])
        return out

    def _group(self, moe, logits_np, stream_rows: dict) -> list[list]:
        """Partition streams into overlap groups (union-find keyed by
        expert uid).  Preview failures fall back to singleton groups —
        coalescing is an optimization, never a correctness dependency."""
        streams = list(stream_rows)
        if not self.coalesce or len(streams) <= 1:
            return [[s] for s in streams]
        try:
            row_sets = moe.preview_expert_sets(logits_np)
        except Exception as e:
            self.preview_failures_total += 1
            logger.warning(
                "expert-set preview failed (%s: %s) — dispatching ungrouped",
                type(e).__name__, e,
            )
            return [[s] for s in streams]
        parent = {s: s for s in streams}

        def find(s):
            while parent[s] != s:
                parent[s] = parent[parent[s]]
                s = parent[s]
            return s

        def union(a, b):
            ra, rb = find(a), find(b)
            if ra != rb:
                parent[ra] = rb

        expert_owner: dict = {}
        for s in streams:
            uids = set()
            for r in stream_rows[s]:
                uids |= row_sets[r]
            for uid in uids:
                if uid in expert_owner:
                    union(s, expert_owner[uid])
                else:
                    expert_owner[uid] = s
        grouped: dict = {}
        for s in streams:  # first-appearance order inside each group
            grouped.setdefault(find(s), []).append(s)
        return list(grouped.values())

    def stats(self) -> dict:
        return {
            "coalesce": self.coalesce,
            "group_dispatches_total": self.group_dispatches_total,
            "coalesced_dispatches_total": self.coalesced_dispatches_total,
            "rows_dispatched_total": self.rows_dispatched_total,
            "preview_failures_total": self.preview_failures_total,
        }
