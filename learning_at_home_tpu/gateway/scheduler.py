"""Continuous-batching slot scheduler: the gateway's decode engine.

One dedicated daemon thread (``lah-gw-decode``) EXCLUSIVELY owns the
:class:`SwarmKVDecoder` — its slot table, KV caches and per-slot scalars
are never touched from any other thread or loop (docs/CONCURRENCY.md).
The loop it runs is the whole continuous-batching policy:

1. evict streams cancelled since the last pass (slot + KV rows freed);
2. admit pending streams into free slots (one prefill each — prefill is
   serial, decode is batched, the standard continuous-batching split);
3. one :meth:`decode_step` advances EVERY live stream by one token —
   arrivals join at token boundaries, nothing waits for a batch drain;
4. streams that hit their token budget or cache capacity vacate their
   slot immediately.

Everything the FRONT DOOR touches (the stream table, the pending queue,
per-stream token buffers) is guarded by the ``gateway.streams`` lock with
short critical sections; the decoder itself needs no lock because only
this thread calls it.  Stream results for clients that never poll again
are garbage-collected after ``LAH_GW_STREAM_TTL_S`` (default 600 s).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

from learning_at_home_tpu.utils import sanitizer

logger = logging.getLogger(__name__)

_DEFAULT_STREAM_TTL_S = 600.0


@dataclasses.dataclass
class StreamState:
    sid: str
    prompt: list
    max_new_tokens: int
    tokens: list = dataclasses.field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    cancelled: bool = False
    slot: Optional[int] = None
    submitted_at: float = dataclasses.field(default_factory=time.monotonic)
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None


class SlotScheduler:
    """Stream table + the ``lah-gw-decode`` thread driving the decoder."""

    def __init__(
        self,
        decoder,
        *,
        idle_wait_s: float = 0.02,
        stream_ttl_s: Optional[float] = None,
    ):
        self.decoder = decoder
        self.idle_wait_s = idle_wait_s
        if stream_ttl_s is None:
            try:
                stream_ttl_s = float(
                    os.environ.get("LAH_GW_STREAM_TTL_S",
                                   str(_DEFAULT_STREAM_TTL_S))
                )
            except ValueError:
                stream_ttl_s = _DEFAULT_STREAM_TTL_S
        self.stream_ttl_s = stream_ttl_s
        self._lock = sanitizer.lock("gateway.streams")
        self._streams: dict[str, StreamState] = {}
        self._pending: deque[str] = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sid_counter = itertools.count()
        self._sid_salt = uuid.uuid4().hex[:6]
        # counters (read by metrics collector / stats; guarded by _lock)
        self.streams_total = 0
        self.streams_finished_total = 0
        self.streams_errored_total = 0
        self.streams_cancelled_total = 0
        self.tokens_total = 0
        # decode-step wall time EMA (seconds) — the admission controller's
        # retry-after scale
        self.step_time_ema: Optional[float] = None
        self._last_gc = time.monotonic()

    # ---- lifecycle ----

    def start(self) -> "SlotScheduler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="lah-gw-decode", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # ---- front-door surface (any thread/loop; short lock sections) ----

    def submit(self, prompt, max_new_tokens: int) -> str:
        """Enqueue a stream; returns its sid.  Admission (shed/accept) is
        the caller's job — this never refuses."""
        sid = f"s{next(self._sid_counter)}-{self._sid_salt}"
        st = StreamState(
            sid=sid, prompt=list(prompt), max_new_tokens=int(max_new_tokens)
        )
        with self._lock:
            self._streams[sid] = st
            self._pending.append(sid)
            self.streams_total += 1
        self._wake.set()
        return sid

    def poll(self, sid: str, cursor: int = 0) -> Optional[dict]:
        """Tokens from ``cursor`` on, plus done/error; None = unknown sid."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return None
            cursor = max(0, int(cursor))
            return {
                "sid": sid,
                "tokens": list(st.tokens[cursor:]),
                "cursor": cursor + len(st.tokens[cursor:]),
                "done": st.done,
                "error": st.error,
            }

    def cancel(self, sid: str) -> bool:
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return False
            already_done = st.done
            st.cancelled = True
        self._wake.set()
        return not already_done

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def active_count(self) -> int:
        """Streams holding a slot or waiting for one."""
        with self._lock:
            return sum(
                1 for st in self._streams.values() if not st.done
            )

    def slots_in_use(self) -> int:
        # reading the decoder's live mask from another thread is a benign
        # monitoring race (numpy bool reads tear at element granularity)
        return int(self.decoder.live.sum())

    def estimate_retry_after_s(self) -> float:
        """Best-effort hint for shed replies: how long until a slot is
        plausibly free — queued work × observed per-step time over the
        slot count, clamped to [0.1, 30]."""
        step = self.step_time_ema or 0.05
        with self._lock:
            backlog = len(self._pending) + 1
            budgets = [
                max(1, st.max_new_tokens - len(st.tokens))
                for st in self._streams.values()
                if not st.done
            ]
        mean_budget = (sum(budgets) / len(budgets)) if budgets else 8.0
        est = backlog * mean_budget * step / max(1, self.decoder.max_slots)
        return float(min(30.0, max(0.1, est)))

    def stats(self) -> dict:
        with self._lock:
            return {
                "streams_total": self.streams_total,
                "streams_finished_total": self.streams_finished_total,
                "streams_errored_total": self.streams_errored_total,
                "streams_cancelled_total": self.streams_cancelled_total,
                "tokens_total": self.tokens_total,
                "streams_active": sum(
                    1 for st in self._streams.values() if not st.done
                ),
                "pending": len(self._pending),
                "slots": self.decoder.max_slots,
                "slots_in_use": self.slots_in_use(),
                "step_time_ema_s": self.step_time_ema,
            }

    # ---- the decode loop (lah-gw-decode thread ONLY below here) ----

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self._iteration()
            except Exception:
                # the loop must survive anything a single pass throws —
                # a dead decode thread strands every live stream
                logger.exception("gateway decode iteration failed")
                worked = False
            if not worked:
                self._wake.wait(timeout=self.idle_wait_s)
                self._wake.clear()

    def _iteration(self) -> bool:
        now = time.monotonic()
        self._evict_cancelled(now)
        self._admit_pending(now)
        worked = self._decode_once(now)
        if now - self._last_gc > max(1.0, self.stream_ttl_s / 10):
            self._gc_streams(now)
            self._last_gc = now
        return worked

    def _finish(self, st: StreamState, now: float, *, error=None,
                cancelled=False) -> None:
        """Release st's slot (decoder side) and mark it done (table side).
        Caller must NOT hold the lock.  Idempotent: a stream cancelled
        while pending is finished by ``_evict_cancelled`` but its sid is
        still in the pending deque, so ``_admit_pending`` reaches it a
        second time — the counters must not double-count it."""
        if st.slot is not None:
            self.decoder.evict(st.slot)
        with self._lock:
            if st.done:
                st.slot = None
                return
            st.slot = None
            st.done = True
            st.finished_at = now
            if error is not None:
                st.error = error
                self.streams_errored_total += 1
            elif cancelled:
                self.streams_cancelled_total += 1
            else:
                self.streams_finished_total += 1

    def _evict_cancelled(self, now: float) -> None:
        with self._lock:
            doomed = [
                st for st in self._streams.values()
                if st.cancelled and not st.done
            ]
        for st in doomed:
            self._finish(st, now, cancelled=True)

    def _admit_pending(self, now: float) -> None:
        while True:
            free = self.decoder.free_slots()
            if not free:
                return
            with self._lock:
                sid = self._pending.popleft() if self._pending else None
                st = self._streams.get(sid) if sid else None
            if st is None:
                return
            if st.cancelled:
                self._finish(st, now, cancelled=True)
                continue
            try:
                tok = self.decoder.prefill_into_slot(
                    free[0], st.prompt, stream_id=st.sid
                )
            except Exception as e:
                logger.exception("prefill failed for stream %s", st.sid)
                self._finish(st, now, error=f"{type(e).__name__}: {e}")
                continue
            with self._lock:
                st.slot = free[0]
                st.first_token_at = time.monotonic()
                st.tokens.append(tok)
                self.tokens_total += 1
                full = (
                    len(st.tokens) >= st.max_new_tokens
                    or self.decoder.at_capacity(free[0])
                )
            if full:
                self._finish(st, now)

    def _decode_once(self, now: float) -> bool:
        live = self.decoder.live_slots()
        if not live:
            return False
        t0 = time.monotonic()
        try:
            nxt = self.decoder.decode_step()
        except Exception as e:
            # a failed step (e.g. total dispatch failure with every
            # expert down) poisons every stream in the batch: error them
            # all out rather than spin on the same failure
            logger.exception("decode step failed — erroring %d streams",
                             len(live))
            for _slot, sid in live:
                with self._lock:
                    st = self._streams.get(sid)
                if st is not None:
                    self._finish(st, now, error=f"{type(e).__name__}: {e}")
            return True
        dt = time.monotonic() - t0
        self.step_time_ema = (
            dt if self.step_time_ema is None
            else 0.8 * self.step_time_ema + 0.2 * dt
        )
        finished = []
        with self._lock:
            for slot, sid in live:
                st = self._streams.get(sid)
                if st is None:  # GC'd mid-flight: free the slot below
                    finished.append((slot, None))
                    continue
                st.tokens.append(int(nxt[slot]))
                self.tokens_total += 1
                if (
                    len(st.tokens) >= st.max_new_tokens
                    or self.decoder.at_capacity(slot)
                    or st.cancelled
                ):
                    finished.append((slot, st))
        for slot, st in finished:
            if st is None:
                self.decoder.evict(slot)
            else:
                self._finish(st, now, cancelled=st.cancelled)
        return True

    def _gc_streams(self, now: float) -> None:
        """Drop finished streams nobody polled away after the TTL —
        bounded memory under fire-and-forget clients."""
        with self._lock:
            stale = [
                sid for sid, st in self._streams.items()
                if st.done and st.finished_at is not None
                and now - st.finished_at > self.stream_ttl_s
            ]
            for sid in stale:
                del self._streams[sid]
        if stale:
            logger.info("gateway stream GC dropped %d stale results",
                        len(stale))
