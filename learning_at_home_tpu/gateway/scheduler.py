"""Continuous-batching slot scheduler: the gateway's decode engine.

One dedicated daemon thread (``lah-gw-decode``) EXCLUSIVELY owns the
:class:`SwarmKVDecoder` — its slot table, KV caches/page pool and
per-slot scalars are never touched from any other thread or loop
(docs/CONCURRENCY.md invariant 12).  The loop it runs is the whole
continuous-batching policy:

1. evict streams cancelled since the last pass (slot + KV pages freed);
2. admit pending streams into free slots — under the paged layout this
   only CLAIMS the slot and serves the prefix cache
   (:meth:`begin_prefill`); the prompt forward itself runs in step 3.
   With ``prefill_chunk_tokens=0`` (or a dense decoder) admission does
   the whole prefill serially, the PR-12 legacy behaviour kept as the
   bench A/B arm;
3. **chunked prefill**: a fixed token budget per pass is spent
   round-robin across mid-prefill slots (:meth:`prefill_step`), so one
   long prompt costs every running stream at most one chunk of extra
   inter-token latency instead of its whole prefill;
4. one :meth:`decode_step` advances EVERY live stream by one token —
   arrivals join at token boundaries, nothing waits for a batch drain;
5. streams that hit their token budget or cache capacity vacate their
   slot immediately.

With ``spec_k > 0`` and a drafter, step 4 becomes a **speculative
verify round** instead: each live stream's drafter proposes up to
``spec_k`` continuation tokens from its committed context, and ONE
batched :meth:`~learning_at_home_tpu.models.swarm_decoder.
SwarmKVDecoder.verify_step` checks every drafted position for every
stream in a single trunk pass — one coalesced expert fan-out per layer
buys up to ``spec_k + 1`` tokens per stream per round-trip, with
output token-identical to non-speculative decoding (the counter-based
RNG makes acceptance an exact recomputation, models/sampling.py).

Page pressure (paged layout only) is resolved by **preemption and
recompute**: the youngest stream that cannot get a page is evicted and
requeued at the FRONT of the pending queue with an effective prompt of
``prompt + tokens-so-far`` — counter-based (seed, position) decoding
makes the recomputed continuation token-identical for greedy and
sampled streams alike, so clients only ever observe added latency,
never changed output.

Everything the FRONT DOOR touches (the stream table, the pending queue,
per-stream token buffers) is guarded by the ``gateway.streams`` lock with
short critical sections; the decoder itself needs no lock because only
this thread calls it.  Stream results for clients that never poll again
are garbage-collected after ``LAH_GW_STREAM_TTL_S`` (default 600 s).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import os
import threading
import time
import uuid
from collections import deque
from typing import Optional

from learning_at_home_tpu.models.kv_pages import PagePressure
from learning_at_home_tpu.utils import flight, sanitizer
from learning_at_home_tpu.utils.metrics import registry
from learning_at_home_tpu.utils.profiling import timeline

logger = logging.getLogger(__name__)

_DEFAULT_STREAM_TTL_S = 600.0
_DEFAULT_PREFILL_CHUNK = 32
_DEFAULT_SPEC_K = 0  # speculative decode off unless opted in


def _monotonic() -> float:
    """Clock seam: every internal timestamp flows through here so the
    lah-verify interleaving explorer can drive the scheduler on a virtual
    clock (deterministic TTL-GC / age ordering across replayed schedules)."""
    return time.monotonic()


# Machine-checked invariants over this module, in the shape lah-verify
# aggregates: (name, what the checker asserts).  ``scheduler.*`` names are
# enforced by :meth:`SlotScheduler.audit` on every explored interleaving;
# the quiesce leak check runs at claimed-idle points under LAH_SANITIZE=1.
# docs/CONCURRENCY.md "Verified invariants" mirrors this table.
VERIFIED_INVARIANTS = (
    ("scheduler.slot_unique",
     "no two non-done streams ever reference the same decoder slot"),
    ("scheduler.done_slotless",
     "a done stream holds no slot (slot freed before done is set)"),
    ("scheduler.counter_conservation",
     "streams_total == finished + errored + cancelled + still-open "
     "(catches _finish double-counting a stream)"),
    ("scheduler.slot_table_consistent",
     "every decoder-side live/prefilling slot is owned by exactly one "
     "non-done stream (no leaked or doubly-owned slots)"),
    ("scheduler.quiesce_baseline",
     "at scheduler idle (no open streams, empty queue) no slot is in "
     "use and the KV page pool accounting is internally consistent"),
    ("scheduler.spec_prefix_accept",
     "a speculative verify round commits exactly the longest matched "
     "draft prefix plus the bonus sample — never a token at or past "
     "the first mismatch (recomputed from the decoder's last_verify "
     "record on every audit)"),
)


@dataclasses.dataclass
class StreamState:
    sid: str
    prompt: list
    max_new_tokens: int
    tokens: list = dataclasses.field(default_factory=list)
    done: bool = False
    error: Optional[str] = None
    cancelled: bool = False
    slot: Optional[int] = None
    prefilling: bool = False
    sampling: Optional[object] = None  # SamplingParams (None = greedy)
    submitted_at: float = dataclasses.field(
        default_factory=lambda: _monotonic()
    )
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None
    # serving-trace id (ISSUE 19): rides every lifecycle span + poll reply
    trace: Optional[str] = None
    # times this stream lost its slot (>0 ⇒ next admit is a recompute)
    preemptions: int = 0
    # last time the stream entered the pending queue (submit or preempt
    # requeue) — start of the "pending wait" span recorded at slot assign
    queued_at: float = 0.0


class SlotScheduler:
    """Stream table + the ``lah-gw-decode`` thread driving the decoder."""

    def __init__(
        self,
        decoder,
        *,
        idle_wait_s: float = 0.02,
        stream_ttl_s: Optional[float] = None,
        prefill_chunk_tokens: Optional[int] = None,
        spec_k: Optional[int] = None,
        drafter=None,
    ):
        self.decoder = decoder
        self.idle_wait_s = idle_wait_s
        if stream_ttl_s is None:
            try:
                stream_ttl_s = float(
                    os.environ.get("LAH_GW_STREAM_TTL_S",
                                   str(_DEFAULT_STREAM_TTL_S))
                )
            except ValueError:
                stream_ttl_s = _DEFAULT_STREAM_TTL_S
        self.stream_ttl_s = stream_ttl_s
        if prefill_chunk_tokens is None:
            try:
                prefill_chunk_tokens = int(
                    os.environ.get("LAH_GW_PREFILL_CHUNK",
                                   str(_DEFAULT_PREFILL_CHUNK))
                )
            except ValueError:
                prefill_chunk_tokens = _DEFAULT_PREFILL_CHUNK
        # 0 = serial prefill at admission (legacy/bench arm); chunking
        # also needs a paged decoder
        self.prefill_chunk_tokens = max(0, int(prefill_chunk_tokens))
        self.chunked = (
            self.decoder.supports_chunked_prefill
            and self.prefill_chunk_tokens > 0
        )
        if spec_k is None:
            try:
                spec_k = int(
                    os.environ.get("LAH_GW_SPEC_K", str(_DEFAULT_SPEC_K))
                )
            except ValueError:
                spec_k = _DEFAULT_SPEC_K
        self.spec_k = max(0, int(spec_k))
        self.drafter = drafter
        # speculation needs both a positive k and someone to draft;
        # either missing keeps decode_step as the exact legacy path
        self.speculative = self.spec_k > 0 and drafter is not None
        self._lock = sanitizer.lock("gateway.streams")
        self._streams: dict[str, StreamState] = {}
        self._pending: deque[str] = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._sid_counter = itertools.count()
        self._sid_salt = uuid.uuid4().hex[:6]
        self._prefill_rr = 0  # round-robin cursor over mid-prefill slots
        # counters (read by metrics collector / stats; guarded by _lock)
        self.streams_total = 0
        self.streams_finished_total = 0
        self.streams_errored_total = 0
        self.streams_cancelled_total = 0
        self.tokens_total = 0
        self.preemptions_total = 0
        # speculative-decode counters (acceptance rate = accepted /
        # proposed; effective k = tokens / rounds)
        self.spec_rounds_total = 0
        self.spec_proposed_total = 0
        self.spec_accepted_total = 0
        self.spec_tokens_total = 0
        self.spec_draft_seconds_total = 0.0
        self.spec_verify_seconds_total = 0.0
        # TTFT SLO feed (utils/slo.py burn-rate evaluator): every first
        # token counts one event; slower than ``ttft_target_s`` counts it
        # bad.  The Gateway sets the target from its SLO spec.
        self.ttft_target_s: Optional[float] = None
        self.ttft_events_total = 0
        self.ttft_slow_total = 0
        # decode-step wall time EMA (seconds) — the admission controller's
        # retry-after scale
        self.step_time_ema: Optional[float] = None
        self._last_gc = _monotonic()
        # resource-leak audit at claimed-idle points (sanitizer-gated;
        # no-op in production).  Per-instance site so one scheduler's
        # quiesce check never reads another's mid-work state; bound
        # method held weakly, so no unregister needed on teardown.
        self._quiesce_site = f"gateway.scheduler.{id(self):x}"
        sanitizer.register_quiesce_audit(self._quiesce_site,
                                         self._quiesce_audit)

    # ---- lifecycle ----

    def start(self) -> "SlotScheduler":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="lah-gw-decode", daemon=True
        )
        self._thread.start()
        return self

    def shutdown(self, timeout: float = 10.0) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None

    # ---- front-door surface (any thread/loop; short lock sections) ----

    def submit(
        self, prompt, max_new_tokens: int, sampling=None, trace=None
    ) -> str:
        """Enqueue a stream; returns its sid.  Admission (shed/accept) is
        the caller's job — this never refuses.  ``sampling`` is an
        optional :class:`~learning_at_home_tpu.models.sampling.
        SamplingParams` (None = greedy); ``trace`` an optional validated
        16-hex serving-trace id stamped onto every lifecycle span."""
        sid = f"s{next(self._sid_counter)}-{self._sid_salt}"
        st = StreamState(
            sid=sid, prompt=list(prompt),
            max_new_tokens=int(max_new_tokens), sampling=sampling,
            trace=trace,
        )
        st.queued_at = st.submitted_at
        with self._lock:
            self._streams[sid] = st
            self._pending.append(sid)
            self.streams_total += 1
        self._wake.set()
        return sid

    def poll(self, sid: str, cursor: int = 0) -> Optional[dict]:
        """Tokens from ``cursor`` on, plus done/error; None = unknown sid."""
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return None
            cursor = max(0, int(cursor))
            reply = {
                "sid": sid,
                "tokens": list(st.tokens[cursor:]),
                "cursor": cursor + len(st.tokens[cursor:]),
                "done": st.done,
                "error": st.error,
            }
            if st.trace is not None:
                reply["trace"] = st.trace
            return reply

    def trace_of(self, sid: str) -> Optional[str]:
        """Serving-trace id for a live stream, or None.  Lock-free read
        (GIL-atomic dict get on an immutable-per-stream field) so the
        coalescer may call it from the decode thread mid-step."""
        st = self._streams.get(sid)
        return st.trace if st is not None else None

    def cancel(self, sid: str) -> bool:
        with self._lock:
            st = self._streams.get(sid)
            if st is None:
                return False
            already_done = st.done
            st.cancelled = True
        self._wake.set()
        return not already_done

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def active_count(self) -> int:
        """Streams holding a slot or waiting for one."""
        with self._lock:
            return sum(
                1 for st in self._streams.values() if not st.done
            )

    def slots_in_use(self) -> int:
        # reading the decoder's live/prefilling masks from another thread
        # is a benign monitoring race (numpy bool reads tear at element
        # granularity)
        return int((self.decoder.live | self.decoder.prefilling).sum())

    def free_page_headroom(self) -> Optional[int]:
        """Free+reclaimable pages net of the active-slot reserve (None on
        a dense decoder) — the admission controller's page-pressure
        signal.  Plain-int reads of decode-thread-owned counters: benign
        monitoring, no lock (CONCURRENCY.md invariant 12)."""
        return self.decoder.free_page_headroom()

    def estimate_retry_after_s(self) -> float:
        """Best-effort hint for shed replies: how long until a slot is
        plausibly free — queued work × observed per-step time over the
        slot count, clamped to [0.1, 30]."""
        step = self.step_time_ema or 0.05
        with self._lock:
            backlog = len(self._pending) + 1
            budgets = [
                max(1, st.max_new_tokens - len(st.tokens))
                for st in self._streams.values()
                if not st.done
            ]
        mean_budget = (sum(budgets) / len(budgets)) if budgets else 8.0
        est = backlog * mean_budget * step / max(1, self.decoder.max_slots)
        return float(min(30.0, max(0.1, est)))

    def stats(self) -> dict:
        with self._lock:
            out = {
                "streams_total": self.streams_total,
                "streams_finished_total": self.streams_finished_total,
                "streams_errored_total": self.streams_errored_total,
                "streams_cancelled_total": self.streams_cancelled_total,
                "tokens_total": self.tokens_total,
                "streams_active": sum(
                    1 for st in self._streams.values() if not st.done
                ),
                "pending": len(self._pending),
                "slots": self.decoder.max_slots,
                "slots_in_use": self.slots_in_use(),
                "step_time_ema_s": self.step_time_ema,
                "prefill_chunk_tokens": (
                    self.prefill_chunk_tokens if self.chunked else 0
                ),
                "prefill_chunks_total": self.decoder.prefill_chunks_total,
                "preemptions_total": self.preemptions_total,
                "ttft_events_total": self.ttft_events_total,
                "ttft_slow_total": self.ttft_slow_total,
                "spec_k": self.spec_k if self.speculative else 0,
                "spec_rounds_total": self.spec_rounds_total,
                "spec_proposed_total": self.spec_proposed_total,
                "spec_accepted_total": self.spec_accepted_total,
                "spec_tokens_total": self.spec_tokens_total,
                "spec_draft_seconds_total": round(
                    self.spec_draft_seconds_total, 6
                ),
                "spec_verify_seconds_total": round(
                    self.spec_verify_seconds_total, 6
                ),
                "spec_acceptance_rate": round(
                    self.spec_accepted_total / self.spec_proposed_total, 4
                ) if self.spec_proposed_total else 0.0,
                "spec_effective_k": round(
                    self.spec_tokens_total / self.spec_rounds_total, 4
                ) if self.spec_rounds_total else 0.0,
            }
        out.update(self.decoder.kv_stats())
        return out

    # ---- the decode loop (lah-gw-decode thread ONLY below here) ----

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self._iteration()
            except Exception:
                # the loop must survive anything a single pass throws —
                # a dead decode thread strands every live stream
                logger.exception("gateway decode iteration failed")
                worked = False
            if not worked:
                # claimed-idle moment: nothing advanced this pass, so
                # slot/page accounting must be back at baseline if the
                # stream table is empty of open work (sanitizer-gated)
                sanitizer.quiesce_point(self._quiesce_site)
                self._wake.wait(timeout=self.idle_wait_s)
                self._wake.clear()

    def _iteration(self) -> bool:
        now = _monotonic()
        self._evict_cancelled(now)
        self._admit_pending(now)
        worked = self._prefill_chunks(now)
        worked = self._decode_once(now) or worked
        if now - self._last_gc > max(1.0, self.stream_ttl_s / 10):
            self._gc_streams(now)
            self._last_gc = now
        return worked

    def _finish(self, st: StreamState, now: float, *, error=None,
                cancelled=False) -> None:
        """Release st's slot (decoder side) and mark it done (table side).
        Caller must NOT hold the lock.  Idempotent: a stream cancelled
        while pending is finished by ``_evict_cancelled`` but its sid is
        still in the pending deque, so ``_admit_pending`` reaches it a
        second time — the counters must not double-count it."""
        if st.slot is not None:
            self.decoder.evict(st.slot)
        with self._lock:
            if st.done:
                st.slot = None
                return
            st.slot = None
            st.prefilling = False
            st.done = True
            st.finished_at = now
            if error is not None:
                st.error = error
                self.streams_errored_total += 1
            elif cancelled:
                self.streams_cancelled_total += 1
            else:
                self.streams_finished_total += 1
        # reached once per stream (the idempotency return above guards
        # re-entry): the umbrella span every other lifecycle span nests
        # under by time containment, plus an outcome marker
        if timeline.enabled:
            timeline.record(
                "gateway.stream", st.submitted_at,
                max(0.0, now - st.submitted_at), trace=st.trace,
            )
            if cancelled:
                timeline.record(
                    "gateway.stream.cancel", now, 0.0, trace=st.trace
                )
            elif error is not None:
                timeline.record(
                    "gateway.stream.error", now, 0.0, trace=st.trace
                )

    def _evict_cancelled(self, now: float) -> None:
        with self._lock:
            doomed = [
                st for st in self._streams.values()
                if st.cancelled and not st.done
            ]
        for st in doomed:
            self._finish(st, now, cancelled=True)

    def _effective_prompt(self, st: StreamState) -> list:
        """What prefill must run for st: the submitted prompt plus every
        token already delivered (non-empty after a preemption — the
        counter-based (seed, position) RNG makes the recomputed
        continuation identical for greedy and sampled streams alike, so
        the requeue is invisible to the client beyond latency)."""
        with self._lock:
            return list(st.prompt) + [int(t) for t in st.tokens]

    def _prompt_can_ever_fit(self, n_tokens: int) -> bool:
        """False when a prompt needs more pages than the WHOLE pool —
        requeueing it would livelock admission forever (+1: the stream
        must be able to decode at least one token past the prompt)."""
        kv = getattr(self.decoder, "kv", None)
        if kv is None:
            return True
        need = self.decoder.pages_needed(
            min(n_tokens + 1, self.decoder.seq_len)
        )
        return need <= kv.pages_total()

    def _admit_pending(self, now: float) -> None:
        while True:
            free = self.decoder.free_slots()
            if not free:
                return
            with self._lock:
                sid = self._pending.popleft() if self._pending else None
                st = self._streams.get(sid) if sid else None
            if st is None:
                return
            if st.cancelled:
                self._finish(st, now, cancelled=True)
                continue
            prompt = self._effective_prompt(st)
            if (
                len(prompt) >= self.decoder.seq_len
                and len(prompt) > len(st.prompt)
            ):
                # a preempted victim whose recompute prompt reached the
                # cache edge: no row is left to prefill its next logits,
                # but it did not fail — it hit capacity, exactly as if it
                # had decoded to seq_len in place (found by lah-verify:
                # erroring it here leaked a spurious client-visible
                # failure under prefix-cache page pressure)
                self._finish(st, now)
                continue
            if not self._prompt_can_ever_fit(len(prompt)):
                self._finish(
                    st, now,
                    error=(
                        f"prompt needs {self.decoder.pages_needed(len(prompt))}"
                        f" KV pages but the pool holds "
                        f"{self.decoder.kv.pages_total()}"
                    ),
                )
                continue
            if self.chunked:
                try:
                    self.decoder.begin_prefill(
                        free[0], prompt, stream_id=st.sid,
                        sampling=st.sampling,
                    )
                except PagePressure:
                    # not even the prefix-cache boundary copy fits right
                    # now — requeue at the front and let decode/prefill
                    # progress free pages
                    with self._lock:
                        self._pending.appendleft(st.sid)
                    return
                except Exception as e:
                    logger.exception("begin_prefill failed for stream %s",
                                     st.sid)
                    self._finish(st, now, error=f"{type(e).__name__}: {e}")
                    continue
                self._record_admit_spans(st, _monotonic())
                with self._lock:
                    st.slot = free[0]
                    st.prefilling = True
                continue
            # serial prefill (dense decoder, or chunking disabled for the
            # legacy bench arm)
            t_assign = _monotonic()
            try:
                with timeline.span("gateway.prefill", trace=st.trace):
                    tok = self.decoder.prefill_into_slot(
                        free[0], prompt, stream_id=st.sid,
                        sampling=st.sampling,
                    )
            except PagePressure:
                self.decoder.evict(free[0])
                with self._lock:
                    self._pending.appendleft(st.sid)
                return
            except Exception as e:
                logger.exception("prefill failed for stream %s", st.sid)
                self._finish(st, now, error=f"{type(e).__name__}: {e}")
                continue
            self._record_admit_spans(st, t_assign)
            self._stream_got_token(st, free[0], tok, now)

    def _record_admit_spans(self, st: StreamState, t_assign: float) -> None:
        """Slot-assign spans: the pending wait this stream just completed
        plus an instant admit marker — named ``gateway.recompute.admit``
        when the admit re-runs a preempted stream's token-identical
        prefill (ISSUE 19 trace continuity through preemption)."""
        if not timeline.enabled:
            return
        timeline.record(
            "gateway.pending.wait", st.queued_at,
            max(0.0, t_assign - st.queued_at), trace=st.trace,
        )
        name = (
            "gateway.recompute.admit" if st.preemptions
            else "gateway.slot.assign"
        )
        timeline.record(name, t_assign, 0.0, trace=st.trace)

    def _stream_got_token(self, st: StreamState, slot: int, tok: int,
                          now: float) -> None:
        """A prefill produced st's next token: record it, turn the slot
        live on the table side, finish if the budget is already met."""
        ttft = None
        with self._lock:
            st.slot = slot
            st.prefilling = False
            if st.first_token_at is None:
                st.first_token_at = _monotonic()
                ttft = st.first_token_at - st.submitted_at
                self.ttft_events_total += 1
                if (
                    self.ttft_target_s is not None
                    and ttft > self.ttft_target_s
                ):
                    self.ttft_slow_total += 1
            st.tokens.append(tok)
            self.tokens_total += 1
            full = (
                len(st.tokens) >= st.max_new_tokens
                or self.decoder.at_capacity(slot)
            )
        if ttft is not None:
            registry.histogram(
                "lah_gateway_ttft_seconds",
                "time to first token per stream (submit → first token)",
            ).observe(ttft)
            if timeline.enabled:
                timeline.record(
                    "gateway.token.first", st.first_token_at, 0.0,
                    trace=st.trace,
                )
        elif timeline.enabled:
            timeline.record("gateway.token", now, 0.0, trace=st.trace)
        if full:
            self._finish(st, now)

    def _prefill_chunks(self, now: float) -> bool:
        """Spend one pass's prefill token budget round-robin across
        mid-prefill slots — the interleave that keeps running-stream ITL
        flat while long prompts prefill."""
        if not self.chunked:
            return False
        budget = self.prefill_chunk_tokens
        slots = self.decoder.prefilling_slots()
        if not slots:
            return False
        rot = self._prefill_rr % len(slots)
        slots = slots[rot:] + slots[:rot]
        self._prefill_rr += 1
        worked = False
        for slot, sid in slots:
            if budget <= 0:
                break
            with self._lock:
                st = self._streams.get(sid)
                # a PagePressure earlier in THIS pass may have preempted
                # this very stream — its snapshot entry is stale and its
                # slot already evicted
                stale = st is not None and (
                    not st.prefilling or st.slot != slot
                )
            if st is None:  # GC'd mid-prefill: free the slot
                self.decoder.evict(slot)
                continue
            if stale:
                continue
            if st.cancelled:  # next _evict_cancelled pass finishes it
                continue
            try:
                with timeline.span("gateway.prefill.chunk", trace=st.trace):
                    consumed, tok = self.decoder.prefill_step(slot, budget)
            except PagePressure:
                # the raiser is NOT excluded from the victim pool: if it
                # is itself the youngest slotted stream it gets requeued,
                # so the oldest stream's progress is monotone and two
                # mid-prefill streams can never preempt each other
                # forever (the livelock an exclude-self rule creates)
                if not self._preempt_one(now):
                    break  # nothing preemptable; decode will free pages
                continue  # st retries next pass against the freed pages
            except Exception as e:
                logger.exception("prefill chunk failed for stream %s", sid)
                self._finish(st, now, error=f"{type(e).__name__}: {e}")
                continue
            budget -= consumed
            worked = True
            if tok is not None:
                self._stream_got_token(st, slot, tok, now)
        return worked

    def _preempt_one(self, now: float,
                     among: Optional[list] = None) -> bool:
        """Preempt-and-recompute the YOUNGEST victim stream: evict its
        slot (pages return to the pool) and requeue it at the front with
        its tokens folded into the prompt.  Decoding victims are
        preferred over mid-prefill ones (less work to redo per page
        freed).  A pressure-raising stream may pick ITSELF (it is the
        youngest): self-preemption is what makes the contention order
        total — the oldest stream always keeps its pages.  Returns False
        when there is nothing to preempt."""
        with self._lock:
            if among is not None:
                pool = [st for st in among if not st.done]
            else:
                pool = [
                    st for st in self._streams.values()
                    if st.slot is not None and not st.done
                ]
            decoding = [st for st in pool if not st.prefilling]
            candidates = decoding or pool
            if not candidates:
                return False
            victim = max(
                candidates,
                key=lambda st: st.first_token_at or st.submitted_at,
            )
        self.decoder.evict(victim.slot)
        t_evict = _monotonic()
        with self._lock:
            victim.slot = None
            victim.prefilling = False
            victim.preemptions += 1
            victim.queued_at = t_evict
            tokens_redone = len(victim.tokens)
            self._pending.appendleft(victim.sid)
        self.preemptions_total += 1
        flight.record(
            "gateway", "preempt", sid=victim.sid,
            tokens_redone=tokens_redone, preemptions=victim.preemptions,
        )
        if timeline.enabled:
            timeline.record(
                "gateway.preempt", t_evict, 0.0, trace=victim.trace
            )
        logger.info("gateway preempted stream %s under page pressure",
                    victim.sid)
        return True

    def _decode_once(self, now: float) -> bool:
        # page pressure first: every live slot must hold a page for its
        # next position before the batched step
        while True:
            lacking = self.decoder.ensure_decode_pages()
            if not lacking:
                break
            with self._lock:
                lacking_sts = [
                    st for st in self._streams.values()
                    if st.slot in lacking and not st.done
                ]
            if not lacking_sts or not self._preempt_one(
                now, among=lacking_sts
            ):
                break  # defensive: nothing matched the lacking slots
        live = self.decoder.live_slots()
        if not live:
            return False
        if self.speculative:
            return self._verify_once(now, live)
        t0 = _monotonic()
        try:
            nxt = self.decoder.decode_step()
        except Exception as e:
            # a failed step (e.g. total dispatch failure with every
            # expert down) poisons every stream in the batch: error them
            # all out rather than spin on the same failure
            logger.exception("decode step failed — erroring %d streams",
                             len(live))
            for _slot, sid in live:
                with self._lock:
                    st = self._streams.get(sid)
                if st is not None:
                    self._finish(st, now, error=f"{type(e).__name__}: {e}")
            return True
        dt = _monotonic() - t0
        self.step_time_ema = (
            dt if self.step_time_ema is None
            else 0.8 * self.step_time_ema + 0.2 * dt
        )
        profiled = timeline.enabled
        if profiled:
            timeline.record("gateway.decode.step", t0, dt)
        finished = []
        with self._lock:
            for slot, sid in live:
                st = self._streams.get(sid)
                if st is None:  # GC'd mid-flight: free the slot below
                    finished.append((slot, None))
                    continue
                if st.slot != slot:  # preempted within this pass
                    continue
                st.tokens.append(int(nxt[slot]))
                self.tokens_total += 1
                if profiled:
                    timeline.record(
                        "gateway.token", now, 0.0, trace=st.trace
                    )
                if (
                    len(st.tokens) >= st.max_new_tokens
                    or self.decoder.at_capacity(slot)
                    or st.cancelled
                ):
                    finished.append((slot, st))
        for slot, st in finished:
            if st is None:
                self.decoder.evict(slot)
            else:
                self._finish(st, now, cancelled=st.cancelled)
        return True

    def _verify_once(self, now: float, live: list) -> bool:
        """One speculative round: draft up to ``spec_k`` tokens per live
        stream, verify every drafted position for every stream in ONE
        batched trunk pass, commit the accepted prefixes.  Replaces the
        single :meth:`decode_step` of the non-speculative loop — an
        empty proposal (drafter found nothing, or no budget/capacity
        headroom) degrades that stream to a plain decode row, so the
        round always advances every stream by at least one token."""
        proposals: dict[int, list] = {}
        t_draft = _monotonic()
        for slot, sid in live:
            with self._lock:
                st = self._streams.get(sid)
                if st is None or st.slot != slot:
                    remaining = 1  # advance the orphan row; cleaned below
                    sampling = None
                    ctx = None
                else:
                    remaining = st.max_new_tokens - len(st.tokens)
                    sampling = st.sampling
                    ctx = list(st.prompt) + [int(t) for t in st.tokens]
            # a round delivers 1..k+1 tokens: cap k so the budget and
            # the cache row at the last drafted position both exist
            k = min(
                self.spec_k,
                max(0, remaining - 1),
                self.decoder.seq_len - 1 - int(self.decoder.pos[slot]),
            )
            drafts: list = []
            if k > 0 and ctx is not None:
                try:
                    drafts = [
                        int(t)
                        for t in self.drafter.propose(ctx, k, sampling)
                    ][:k]
                except Exception:
                    logger.exception(
                        "drafter failed for stream %s — plain decode", sid
                    )
                    drafts = []
            if drafts:
                covered = self.decoder.ensure_lookahead_pages(
                    slot, len(drafts)
                )
                drafts = drafts[:covered]
            proposals[slot] = drafts
        draft_dt = _monotonic() - t_draft
        self.spec_draft_seconds_total += draft_dt
        if timeline.enabled:
            timeline.record("gateway.spec.draft", t_draft, draft_dt)
        t0 = _monotonic()
        try:
            results = self.decoder.verify_step(proposals)
        except Exception as e:
            logger.exception("verify step failed — erroring %d streams",
                             len(live))
            for _slot, sid in live:
                with self._lock:
                    st = self._streams.get(sid)
                if st is not None:
                    self._finish(st, now, error=f"{type(e).__name__}: {e}")
            return True
        dt = _monotonic() - t0
        self.spec_verify_seconds_total += dt
        self.step_time_ema = (
            dt if self.step_time_ema is None
            else 0.8 * self.step_time_ema + 0.2 * dt
        )
        profiled = timeline.enabled
        if profiled:
            timeline.record("gateway.spec.verify", t0, dt)
        finished = []
        with self._lock:
            for slot, sid in live:
                st = self._streams.get(sid)
                if st is None:  # GC'd mid-flight: free the slot below
                    finished.append((slot, None))
                    continue
                if st.slot != slot:  # preempted within this pass
                    continue
                res = results.get(slot)
                if res is None:
                    continue
                self.spec_rounds_total += 1
                self.spec_proposed_total += res["proposed"]
                self.spec_accepted_total += res["accepted"]
                self.spec_tokens_total += len(res["tokens"])
                if profiled:
                    # accepted-k rides the span name: one instant marker
                    # per stream per verify round (k is bounded by spec_k
                    # so the name set stays small)
                    timeline.record(
                        f"gateway.spec.accept.k{res['accepted']}",
                        now, 0.0, trace=st.trace,
                    )
                for tok in res["tokens"]:
                    st.tokens.append(int(tok))
                    self.tokens_total += 1
                if (
                    len(st.tokens) >= st.max_new_tokens
                    or self.decoder.at_capacity(slot)
                    or st.cancelled
                ):
                    finished.append((slot, st))
        for slot, st in finished:
            if st is None:
                self.decoder.evict(slot)
            else:
                self._finish(st, now, cancelled=st.cancelled)
        return True

    def _gc_streams(self, now: float) -> None:
        """Drop finished streams nobody polled away after the TTL —
        bounded memory under fire-and-forget clients."""
        with self._lock:
            stale = [
                sid for sid, st in self._streams.items()
                if st.done and st.finished_at is not None
                and now - st.finished_at > self.stream_ttl_s
            ]
            traces = [self._streams[sid].trace for sid in stale]
            for sid in stale:
                del self._streams[sid]
        if timeline.enabled:
            for tr in traces:
                timeline.record("gateway.stream.gc", now, 0.0, trace=tr)
        if stale:
            logger.info("gateway stream GC dropped %d stale results",
                        len(stale))

    # ---- machine-checked invariants (lah-verify / sanitizer) ----

    def audit(self) -> list[str]:
        """Check every ``scheduler.*`` row of :data:`VERIFIED_INVARIANTS`
        against the live state; returns violation strings (empty = clean).
        Called by the lah-verify explorer after every step of every
        explored interleaving, and by the quiesce audit at idle.  Must be
        callable from the decode thread (reads decoder masks directly)."""
        leaks: list[str] = []
        with self._lock:
            open_streams = [
                st for st in self._streams.values() if not st.done
            ]
            slots: dict[int, str] = {}
            for st in open_streams:
                if st.slot is None:
                    continue
                if st.slot in slots:
                    leaks.append(
                        f"slot_unique: slot {st.slot} owned by both "
                        f"{slots[st.slot]} and {st.sid}"
                    )
                slots[st.slot] = st.sid
            for st in self._streams.values():
                if st.done and st.slot is not None:
                    leaks.append(
                        f"done_slotless: done stream {st.sid} still "
                        f"holds slot {st.slot}"
                    )
            closed = (
                self.streams_finished_total + self.streams_errored_total
                + self.streams_cancelled_total
            )
            if self.streams_total != closed + len(open_streams):
                leaks.append(
                    "counter_conservation: total "
                    f"{self.streams_total} != closed {closed} + open "
                    f"{len(open_streams)} (a _finish double-count or a "
                    "lost stream)"
                )
        busy = getattr(self.decoder, "busy_slots", None)
        if callable(busy):
            decoder_side = set(busy())
            table_side = set(slots)
            for slot in decoder_side - table_side:
                leaks.append(
                    f"slot_table_consistent: decoder slot {slot} is "
                    "live/prefilling but no open stream owns it (leak)"
                )
            for slot in table_side - decoder_side:
                leaks.append(
                    f"slot_table_consistent: stream {slots[slot]} claims "
                    f"slot {slot} the decoder thinks is free"
                )
        for rec in getattr(self.decoder, "last_verify", None) or []:
            drafts = rec.get("drafts", [])
            samples = rec.get("samples", [])
            a = 0
            while a < len(drafts) and drafts[a] == samples[a]:
                a += 1
            if rec.get("accepted") != a or (
                rec.get("tokens") != samples[:a + 1]
            ):
                leaks.append(
                    "spec_prefix_accept: slot "
                    f"{rec.get('slot')} committed {rec.get('tokens')} "
                    f"(claimed accepted={rec.get('accepted')}) but the "
                    f"longest matched prefix of drafts {drafts} vs "
                    f"samples {samples} is {a}"
                )
        kv_audit = getattr(
            getattr(self.decoder, "kv", None), "audit", None
        )
        if callable(kv_audit):
            leaks.extend(f"kv: {x}" for x in kv_audit())
        return leaks

    def _quiesce_audit(self) -> list[str]:
        """Leak check at a claimed-idle moment.  Only bites when the
        stream table holds no open work — mid-work calls return clean
        rather than second-guess a busy scheduler."""
        with self._lock:
            busy = self._pending or any(
                not st.done for st in self._streams.values()
            )
        if busy:
            return []
        leaks = self.audit()
        in_use = self.slots_in_use()
        if in_use:
            leaks.append(
                f"quiesce_baseline: {in_use} decoder slot(s) in use "
                "with no open streams"
            )
        return leaks
