"""The gateway front door: framed-TCP serving process for generate
streams, plus the sync client that talks to it.

Speaks the SAME wire protocol as expert servers (utils/serialization.py
framing, ``hello`` → protocol v2 mux), so the existing
``ConnectionPool``/``PoolRegistry`` client stack works against a gateway
unchanged.  All gateway ops are meta-only control frames (token ids ride
in msgpack meta, never as tensors — a generate stream moves a few ints
per poll, not megabyte activations):

- ``gen_submit`` {prompt: [int], max_new_tokens, seed?, temperature?,
  top_p?, top_k?, trace?} → {"accepted": true, "sid", "trace"?} or
  {"accepted": false, "shed": true, "retry_after_s", "message"}
  (the four optional sampling fields select counter-based sampled
  decoding; all absent = greedy, the legacy wire shape unchanged.
  ``trace`` is an optional 16-hex stream trace id — a valid one is
  echoed and stamped on every lifecycle span, a malformed one is
  dropped, and with profiling on the gateway mints one itself)
- ``gen_poll``   {sid, cursor} → {"tokens": [int], "cursor", "done",
  "error"?, "trace"?} (tokens from ``cursor`` on; poll again from the
  returned cursor — replies are immediate, never held)
- ``gen_cancel`` {sid} → {"cancelled": bool}
- ``stats``      {} → gateway counters + the metrics registry snapshot

Invalid requests (unknown sid, malformed prompt, budget over capacity)
get an ``error`` frame; a SHED is a well-formed ``result`` with
``accepted=false`` — backpressure is an answer, not a failure
(docs/PROTOCOL.md "Gateway RPC family").

The serving loop (``lah-gateway`` BackgroundLoop) does admission reads,
stream-table reads/writes (short ``gateway.streams`` lock sections) and
framing only; prefill/decode compute and expert RPCs live on the
scheduler's ``lah-gw-decode`` thread (docs/CONCURRENCY.md).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Optional

from learning_at_home_tpu.gateway.admission import AdmissionController
from learning_at_home_tpu.gateway.coalesce import ExpertCoalescer
from learning_at_home_tpu.gateway.scheduler import SlotScheduler
from learning_at_home_tpu.models.drafter import (
    NGramDrafter,
    TruncatedTrunkDrafter,
)
from learning_at_home_tpu.models.sampling import SamplingParams
from learning_at_home_tpu.models.swarm_decoder import SwarmKVDecoder
from learning_at_home_tpu.utils import flight
from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop
from learning_at_home_tpu.utils.profiling import (
    new_trace_id,
    timeline,
    valid_trace_id,
)
from learning_at_home_tpu.utils.slo import BurnRateSLO, SLOEvaluator
from learning_at_home_tpu.utils.serialization import (
    WireTensors,
    pack_frames,
    peek_header,
    recv_frame,
    send_frame_parts,
    unpack_message,
)

logger = logging.getLogger(__name__)

# same negotiation surface as the expert server: mux so thousands of
# concurrent streams share connections; gateway frames are tiny control
# meta, so the quantized-codec feature is not offered
GATEWAY_FEATURES = ("mux",)


class Gateway:
    """Front-door serving process over one swarm model.

    Owns the whole serving stack: decoder (paged KV pool with
    shared-prefix reuse by default; ``kv_layout="dense"`` keeps the
    static slot table), coalescer (cross-user expert-set grouping),
    scheduler (continuous batching with chunked prefill on
    ``lah-gw-decode``), admission controller (slots, server queues AND
    free-page headroom), the
    ``lah-gateway`` serving loop, a metrics-registry collector, and —
    when a DHT handle is passed — a ``telemetry.<prefix>`` heartbeat with
    role ``gateway`` so ``lah_top`` renders it as a first-class peer.
    """

    def __init__(
        self,
        model,
        params,
        *,
        max_slots: int = 8,
        coalesce: bool = True,
        host: str = "127.0.0.1",
        port: int = 0,
        dht=None,
        telemetry_prefix: Optional[str] = None,
        max_pending: Optional[int] = None,
        max_server_queue: float = 64.0,
        stream_ttl_s: Optional[float] = None,
        kv_layout: str = "paged",
        page_len: Optional[int] = None,
        num_pages: Optional[int] = None,
        prefix_cache: bool = True,
        prefill_chunk_tokens: Optional[int] = None,
        spec_k: Optional[int] = None,
        spec_drafter: Optional[str] = None,
    ):
        self.model = model
        self.coalescer = ExpertCoalescer(coalesce=coalesce)
        if page_len is None:
            try:
                page_len = int(os.environ.get("LAH_GW_PAGE_LEN", "16"))
            except ValueError:
                page_len = 16
        # the gateway defaults to the paged layout (bounded by tokens in
        # flight, prefix reuse, chunked prefill); kv_layout="dense" keeps
        # the PR-12 slot table as the bench/parity baseline
        self.decoder = SwarmKVDecoder(
            model, params, max_slots=max_slots,
            moe_dispatch=self.coalescer.dispatch,
            kv_layout=kv_layout, page_len=page_len, num_pages=num_pages,
            prefix_cache=prefix_cache,
        )
        # speculative decode: k drafted tokens verified per swarm
        # round-trip (LAH_GW_SPEC_K=0 keeps the token-at-a-time loop)
        if spec_k is None:
            try:
                spec_k = int(os.environ.get("LAH_GW_SPEC_K", "0"))
            except ValueError:
                spec_k = 0
        spec_k = max(0, int(spec_k))
        drafter = None
        if spec_k > 0:
            if spec_drafter is None:
                spec_drafter = os.environ.get(
                    "LAH_GW_SPEC_DRAFTER", "ngram"
                )
            if spec_drafter == "trunk":
                drafter = TruncatedTrunkDrafter(model, params)
            elif spec_drafter == "ngram":
                drafter = NGramDrafter()
            else:
                raise ValueError(
                    f"spec_drafter must be 'ngram' or 'trunk', got "
                    f"{spec_drafter!r}"
                )
        self.scheduler = SlotScheduler(
            self.decoder, stream_ttl_s=stream_ttl_s,
            prefill_chunk_tokens=prefill_chunk_tokens,
            spec_k=spec_k, drafter=drafter,
        )
        # stream traces nest the coalescer's client.dispatch.{fire,join}
        # spans under the submitting stream (ISSUE 19 layer 1)
        self.coalescer.trace_lookup = self.scheduler.trace_of
        # server-load feed: the MoE's own cost model already TTL-caches
        # the load.<prefix> heartbeats (PR 8) — reuse it instead of
        # growing a second DHT reader.  loads() blocks on the refresh
        # window, which is why admission polls it on its own thread.
        load_fn = (
            model.moes[0].cost_model.loads
            if getattr(model, "moes", None) else None
        )
        self.admission = AdmissionController(
            self.scheduler,
            max_pending=max_pending,
            max_server_queue=max_server_queue,
            load_fn=load_fn,
        )
        self._loop = BackgroundLoop(name="lah-gateway")
        self._server = None
        self.host = host
        try:
            self.port: int = self._loop.run(self._start(host, port), timeout=10)
        except BaseException:
            self._loop.shutdown()
            raise
        self.endpoint = (host, self.port)
        self.scheduler.start()
        self.admission.start()
        self.started_at = time.monotonic()
        from learning_at_home_tpu.utils.metrics import registry

        self._collector_key = f"gateway-{id(self)}"
        registry.register_collector(self._collector_key, self._collect)
        # declarative TTFT SLO (ISSUE 19 layer 3): the scheduler counts
        # first-token events against the target; burn-rate evaluation
        # runs at scrape time on the lah-metrics loop, and entering PAGE
        # dumps a flight-recorder artifact.  Env knobs exist so smokes
        # and operators can tighten without code changes.
        def _env_float(name: str, default: float) -> float:
            try:
                return float(os.environ.get(name, default))
            except ValueError:
                return default

        self.ttft_slo_target_s = _env_float("LAH_TTFT_SLO_S", 30.0)
        self.scheduler.ttft_target_s = self.ttft_slo_target_s
        self.slo = SLOEvaluator(component="gateway")
        sched = self.scheduler
        self.slo.register(
            BurnRateSLO(
                name="gateway_ttft",
                objective=min(
                    0.999999,
                    max(1e-6, _env_float("LAH_TTFT_SLO_OBJECTIVE", 0.99)),
                ),
                fast_window_s=_env_float("LAH_SLO_FAST_S", 60.0),
                slow_window_s=max(
                    _env_float("LAH_SLO_FAST_S", 60.0),
                    _env_float("LAH_SLO_SLOW_S", 600.0),
                ),
                description=(
                    f"TTFT <= {self.ttft_slo_target_s:g}s for the "
                    "objective fraction of streams"
                ),
            ),
            lambda: (
                sched.ttft_events_total - sched.ttft_slow_total,
                sched.ttft_slow_total,
            ),
        )
        self._slo_collector_key = f"slo-gateway-{id(self)}"
        registry.register_collector(self._slo_collector_key, self.slo.collect)
        self.telemetry = None
        if dht is not None:
            from learning_at_home_tpu.utils.telemetry import (
                TelemetryPublisher,
            )

            self.telemetry = TelemetryPublisher(
                dht,
                prefix=telemetry_prefix or model.cfg.telemetry_prefix,
                role="gateway",
                host=host,
                meta={"gateway_port": self.port},
                extra_fn=lambda: {"gateway": self.gateway_stats()},
            ).start()

    # ---- lifecycle ----

    async def _start(self, host: str, port: int) -> int:
        self._server = await asyncio.start_server(
            self._handle_connection, host, port
        )
        return self._server.sockets[0].getsockname()[1]

    def shutdown(self) -> None:
        from learning_at_home_tpu.utils.metrics import registry

        registry.unregister_collector(self._collector_key)
        registry.unregister_collector(self._slo_collector_key)
        if self.telemetry is not None:
            self.telemetry.stop()
            self.telemetry = None
        self.admission.stop()
        self.scheduler.shutdown()
        if self._server is not None:
            self._loop.loop.call_soon_threadsafe(self._server.close)
            self._server = None
        self._loop.shutdown()

    def __enter__(self) -> "Gateway":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    # ---- observability ----

    def gateway_stats(self) -> dict:
        return {
            **self.scheduler.stats(),
            **self.admission.stats(),
            **self.coalescer.stats(),
            "uptime_s": time.monotonic() - self.started_at,
        }

    def _collect(self) -> dict:
        s = self.scheduler
        out = {
            "lah_gateway_streams_total": s.streams_total,
            "lah_gateway_streams_finished_total": s.streams_finished_total,
            "lah_gateway_streams_errored_total": s.streams_errored_total,
            "lah_gateway_streams_cancelled_total": s.streams_cancelled_total,
            "lah_gateway_streams_active": s.active_count(),
            "lah_gateway_slots": self.decoder.max_slots,
            "lah_gateway_slots_in_use": s.slots_in_use(),
            "lah_gateway_tokens_total": s.tokens_total,
            "lah_gateway_shed_total": self.admission.shed_total,
            "lah_gateway_shed_pages_total": self.admission.shed_pages_total,
            "lah_gateway_group_dispatches_total":
                self.coalescer.group_dispatches_total,
            "lah_gateway_coalesced_dispatches_total":
                self.coalescer.coalesced_dispatches_total,
            "lah_gateway_step_time_ema_s": s.step_time_ema or 0.0,
            "lah_gateway_preemptions_total": s.preemptions_total,
            "lah_gateway_prefill_chunks_total":
                self.decoder.prefill_chunks_total,
            "lah_gateway_spec_k": s.spec_k if s.speculative else 0,
            "lah_gateway_spec_rounds_total": s.spec_rounds_total,
            "lah_gateway_spec_proposed_total": s.spec_proposed_total,
            "lah_gateway_spec_accepted_total": s.spec_accepted_total,
            "lah_gateway_spec_tokens_total": s.spec_tokens_total,
            "lah_gateway_spec_draft_seconds_total":
                s.spec_draft_seconds_total,
            "lah_gateway_spec_verify_seconds_total":
                s.spec_verify_seconds_total,
        }
        kv = self.decoder.kv
        if kv is not None:
            out.update({
                "lah_gateway_kv_pages_total": kv.pages_total(),
                "lah_gateway_kv_pages_used": kv.pages_used(),
                "lah_gateway_kv_pages_reclaimable": kv.pages_reclaimable(),
                "lah_gateway_kv_page_len": kv.page_len,
                "lah_gateway_prefix_hits_total": kv.prefix_hits_total,
                "lah_gateway_prefix_hit_tokens_total":
                    kv.prefix_hit_tokens_total,
                "lah_gateway_cow_copies_total": kv.cow_copies_total,
                "lah_gateway_kv_pages_reclaimed_total":
                    kv.pages_reclaimed_total,
                "lah_gateway_kv_rollback_pages_total":
                    kv.rollback_pages_total,
            })
        return out

    # ---- the serving loop (lah-gateway) ----

    async def _handle_connection(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        peer = writer.get_extra_info("peername")
        muxed = False
        wlock = asyncio.Lock()
        inflight: set[asyncio.Task] = set()
        try:
            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                try:
                    msg_type, rid = peek_header(payload)
                except Exception:
                    msg_type, rid = None, None
                if msg_type == "hello":
                    # peer-supplied hello: non-map meta / non-list offer
                    # negotiates the empty set, never a torn connection
                    try:
                        _, _, hmeta = unpack_message(payload)
                        offered = hmeta.get("features")
                    except Exception:
                        offered = None
                    if not isinstance(offered, list):
                        offered = []
                    common = [f for f in GATEWAY_FEATURES if f in offered]
                    muxed = "mux" in common
                    await self._send(
                        writer, wlock,
                        pack_frames(
                            "hello_ok", WireTensors.prepare(),
                            {"features": common}, rid=rid,
                        ),
                    )
                    continue
                if muxed and rid is not None:
                    task = asyncio.get_running_loop().create_task(
                        self._serve_muxed(payload, rid, writer, wlock)
                    )
                    inflight.add(task)
                    task.add_done_callback(inflight.discard)
                    continue
                await self._send(writer, wlock, self._dispatch(payload, rid))
        except Exception:
            logger.exception("gateway connection failed for peer %s", peer)
        finally:
            for task in inflight:
                task.cancel()
            writer.close()

    @staticmethod
    async def _send(writer, wlock: asyncio.Lock, parts: list) -> None:
        async with wlock:
            await send_frame_parts(writer, parts)

    async def _serve_muxed(
        self, payload: bytes, rid: int, writer, wlock: asyncio.Lock
    ) -> None:
        try:
            await self._send(writer, wlock, self._dispatch(payload, rid))
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("gateway muxed request %d failed", rid)

    # sync, not async: every op below is dict/lock bookkeeping — the
    # blocking compute lives on lah-gw-decode, never on this loop
    def _dispatch(self, payload: bytes, rid=None) -> list:
        def reply(msg_type: str, meta=None) -> list:
            return pack_frames(
                msg_type, WireTensors.prepare(), meta, rid=rid
            )

        try:
            msg_type, _tensors, meta = unpack_message(payload)
        except Exception as e:
            return reply("error", {"message": f"malformed request: {e}"})
        try:
            if msg_type == "gen_submit":
                return reply("result", self._gen_submit(meta))
            elif msg_type == "gen_poll":
                sid = meta.get("sid")
                out = self.scheduler.poll(
                    sid if isinstance(sid, str) else "",
                    int(meta.get("cursor") or 0),
                )
                if out is None:
                    return reply(
                        "error", {"message": f"unknown stream {sid!r}"}
                    )
                if out["error"] is None:
                    del out["error"]
                return reply("result", out)
            elif msg_type == "gen_cancel":
                sid = meta.get("sid")
                cancelled = self.scheduler.cancel(
                    sid if isinstance(sid, str) else ""
                )
                return reply("result", {"cancelled": cancelled})
            elif msg_type == "stats":
                from learning_at_home_tpu.utils.metrics import registry

                return reply(
                    "result",
                    {"gateway": self.gateway_stats(),
                     "metrics": registry.snapshot()},
                )
            else:
                return reply(
                    "error",
                    {"message": f"unknown message type {msg_type!r}"},
                )
        except Exception as e:
            logger.exception("gateway request %s failed", msg_type)
            return reply("error", {"message": f"{type(e).__name__}: {e}"})

    def _gen_submit(self, meta: dict) -> dict:
        # per-stream trace id (ISSUE 19): echo a structurally valid
        # client-supplied id, mint one only while profiling is on (the
        # disabled path stays allocation-free), drop anything malformed
        trace = meta.get("trace")
        if not valid_trace_id(trace):
            trace = None
        if trace is None and timeline.enabled:
            trace = new_trace_id()
        prompt = meta.get("prompt")
        max_new = meta.get("max_new_tokens")
        vocab = self.model.cfg.vocab_size
        if not (
            isinstance(prompt, (list, tuple))
            and prompt
            and all(
                isinstance(t, int) and not isinstance(t, bool)
                and 0 <= t < vocab for t in prompt
            )
        ):
            raise ValueError(
                "prompt must be a non-empty list of token ids in "
                f"[0, {vocab})"
            )
        if (
            not isinstance(max_new, int) or isinstance(max_new, bool)
            or max_new < 1
        ):
            raise ValueError("max_new_tokens must be a positive int")
        # optional counter-based sampling fields — any present field
        # turns the stream sampled; hostile values (bools, NaN, out of
        # range) become well-formed error frames, never decoder state
        sampling = None
        seed = meta.get("seed")
        temperature = meta.get("temperature")
        top_p = meta.get("top_p")
        top_k = meta.get("top_k")
        if any(v is not None for v in (seed, temperature, top_p, top_k)):
            if seed is None:
                seed = 0
            if not isinstance(seed, int) or isinstance(seed, bool):
                raise ValueError("seed must be an int")
            if temperature is None:
                temperature = 0.0
            if isinstance(temperature, bool) or not isinstance(
                temperature, (int, float)
            ):
                raise ValueError("temperature must be a number")
            if top_p is None:
                top_p = 1.0
            if isinstance(top_p, bool) or not isinstance(
                top_p, (int, float)
            ):
                raise ValueError("top_p must be a number")
            if top_k is None:
                top_k = 0
            if not isinstance(top_k, int) or isinstance(top_k, bool):
                raise ValueError("top_k must be an int")
            # range validation (finite temperature >= 0, top_p in
            # (0, 1], top_k >= 0, seed in [0, 2**63)) lives in
            # SamplingParams and raises ValueError too
            sampling = SamplingParams(
                seed=seed, temperature=float(temperature),
                top_p=float(top_p), top_k=top_k,
            )
        # an over-long prompt is a well-formed error frame BEFORE the
        # stream table sees it — it must never reach the decode thread,
        # where it could only crash prefill or wedge the pending queue
        capacity = self.decoder.seq_len - len(prompt)
        if capacity < 1:
            raise ValueError(
                f"prompt length {len(prompt)} leaves no decode capacity "
                f"(cache holds {self.decoder.seq_len} positions)"
            )
        max_new = min(max_new, capacity)
        # k-aware slot accounting: a speculative stream's peak page use
        # includes up to spec_k lookahead positions past its budget
        # (rolled back after rejection, but mapped at the peak)
        spec_k = (
            self.scheduler.spec_k if self.scheduler.speculative else 0
        )
        pages_needed = self.decoder.pages_needed(
            len(prompt), max_new + spec_k
        )
        if (
            self.decoder.kv is not None
            and self.decoder.pages_needed(len(prompt) + 1)
            > self.decoder.kv.pages_total()
        ):
            raise ValueError(
                f"prompt needs {self.decoder.pages_needed(len(prompt) + 1)}"
                f" KV pages but the pool holds "
                f"{self.decoder.kv.pages_total()}"
            )
        with timeline.span("gateway.admit", trace=trace):
            accepted, retry_after_s, reason = self.admission.admit(
                pages_needed=pages_needed
            )
        if not accepted:
            flight.record(
                "gateway", "shed", reason=reason,
                retry_after_s=retry_after_s, pages_needed=pages_needed,
            )
            out = {
                "accepted": False,
                "shed": True,
                "retry_after_s": retry_after_s,
                "message": reason,
            }
            if trace is not None:
                out["trace"] = trace
            return out
        sid = self.scheduler.submit(
            prompt, max_new, sampling=sampling, trace=trace
        )
        out = {"accepted": True, "sid": sid}
        if trace is not None:
            out["trace"] = trace
        return out


class GatewayClient:
    """Sync client over the shared RPC stack (control-plane ``rpc()`` on
    the ``lah-client`` loop — gateway frames are tiny meta maps)."""

    def __init__(self, endpoint, timeout: float = 30.0):
        self.endpoint = (endpoint[0], int(endpoint[1]))
        self.timeout = timeout

    def _rpc(self, msg_type: str, meta: dict) -> dict:
        from learning_at_home_tpu.client.rpc import client_loop, pool_registry

        pool = pool_registry().get(self.endpoint)
        _tensors, reply = client_loop().run(
            pool.rpc(msg_type, meta=meta, timeout=self.timeout),
            timeout=self.timeout + 5,
        )
        return reply or {}

    def submit(self, prompt, max_new_tokens: int, *,
               seed=None, temperature=None, top_p=None,
               top_k=None, trace=None) -> dict:
        """One admission attempt; the reply is either accepted ({sid}) or
        a shed ({shed, retry_after_s}).  Raises RemoteCallError only for
        INVALID requests — backpressure is a normal reply.  The sampling
        kwargs ride as optional gen_submit fields (all None = greedy,
        and the wire frame carries no sampling keys at all).  ``trace``
        optionally carries a caller-minted 16-hex trace id; the gateway
        echoes it in the reply and stamps it on every lifecycle span."""
        meta = {
            "prompt": [int(t) for t in prompt],
            "max_new_tokens": int(max_new_tokens),
        }
        if seed is not None:
            meta["seed"] = int(seed)
        if temperature is not None:
            meta["temperature"] = float(temperature)
        if top_p is not None:
            meta["top_p"] = float(top_p)
        if top_k is not None:
            meta["top_k"] = int(top_k)
        if trace is not None:
            meta["trace"] = str(trace)
        return self._rpc("gen_submit", meta)

    def poll(self, sid: str, cursor: int = 0) -> dict:
        return self._rpc("gen_poll", {"sid": sid, "cursor": int(cursor)})

    def cancel(self, sid: str) -> bool:
        return bool(self._rpc("gen_cancel", {"sid": sid}).get("cancelled"))

    def stats(self) -> dict:
        return self._rpc("stats", {})

    def generate(
        self,
        prompt,
        max_new_tokens: int,
        *,
        poll_interval_s: float = 0.005,
        deadline_s: float = 120.0,
        on_token=None,
        seed=None,
        temperature=None,
        top_p=None,
        top_k=None,
    ) -> dict:
        """Submit once and poll to completion.  Returns
        ``{"tokens", "shed", "retry_after_s"?, "error"?}`` — a shed
        returns immediately (open-loop callers own the retry policy)."""
        sub = self.submit(
            prompt, max_new_tokens,
            seed=seed, temperature=temperature, top_p=top_p, top_k=top_k,
        )
        if not sub.get("accepted"):
            return {
                "tokens": [],
                "shed": True,
                "retry_after_s": sub.get("retry_after_s"),
            }
        sid = sub["sid"]
        tokens: list[int] = []
        cursor = 0
        deadline = time.monotonic() + deadline_s
        while True:
            out = self.poll(sid, cursor)
            fresh = out.get("tokens") or []
            if fresh:
                tokens.extend(int(t) for t in fresh)
                cursor = int(out.get("cursor") or cursor + len(fresh))
                if on_token is not None:
                    for _ in fresh:
                        on_token(time.monotonic())
            if out.get("done"):
                result = {"tokens": tokens, "shed": False}
                if out.get("error") is not None:
                    result["error"] = out["error"]
                return result
            if time.monotonic() > deadline:
                self.cancel(sid)
                return {"tokens": tokens, "shed": False,
                        "error": "client deadline exceeded"}
            time.sleep(poll_interval_s)
