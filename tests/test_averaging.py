"""Decentralized parameter averaging: matchmaking, butterfly parity,
mid-round death, late joiners, chaos-dropped frames (ISSUE 3).

All tests run real averager peers — own loops, TCP endpoints, and a real
in-process DHT for rendezvous — at tiny tree sizes, so they exercise the
full wire path (v2 mux frames, held replies) in tier-1 time."""

import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.averaging import (
    AveragingConfig,
    AveragingFailed,
    DecentralizedAverager,
)
from learning_at_home_tpu.averaging.partitioning import (
    chunk_ranges,
    flatten_tree,
    partition_bounds,
    unflatten_tree,
    weighted_mean,
)
from learning_at_home_tpu.dht import DHT


# ---------------------------------------------------------------------------
# pure partitioning helpers
# ---------------------------------------------------------------------------


def test_flatten_roundtrip_mixed_dtypes():
    tree = {
        "w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
        "b": jnp.ones((4,), jnp.bfloat16),
        "nested": [jnp.float32(3.5), jnp.zeros((2, 2), jnp.float32)],
    }
    vec, treedef, specs = flatten_tree(tree)
    assert vec.dtype == np.float32
    back = unflatten_tree(vec, treedef, specs)
    for a, b in zip(jax.tree.leaves(tree), jax.tree.leaves(back)):
        assert a.dtype == b.dtype
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_partition_bounds_cover_and_chunk_ranges():
    bounds = partition_bounds(10, 4)
    assert bounds == [(0, 3), (3, 6), (6, 8), (8, 10)]
    assert partition_bounds(2, 4) == [(0, 1), (1, 2), (2, 2), (2, 2)]
    assert chunk_ranges(10, 4) == [(0, 4), (4, 4), (8, 2)]
    assert chunk_ranges(0, 4) == [(0, 0)]  # empty partition still framed


def test_weighted_mean_matches_tree_map_mean_bitwise():
    rs = np.random.RandomState(0)
    vecs = [rs.randn(37).astype(np.float32) for _ in range(4)]
    got = weighted_mean(
        [(f"p{i}", 1.0, v) for i, v in enumerate(vecs)]
    )
    want = np.asarray(sum(vecs) / 4)
    np.testing.assert_array_equal(got, want)  # atol=0: same order, f32


# ---------------------------------------------------------------------------
# multi-peer rounds over the real stack
# ---------------------------------------------------------------------------


def _make_tree(seed: int, d: int = 17):
    rs = np.random.RandomState(seed)
    return {
        "embed": jnp.asarray(rs.randn(3, d).astype(np.float32)),
        "gate": {"w": jnp.asarray(rs.randn(d).astype(np.float32))},
    }


def _run_rounds(averagers, trees, matchmaking_timeout=20.0):
    """step_round on every averager concurrently; returns results list
    aligned with ``averagers`` (None entries for peers that raised)."""
    results = [None] * len(averagers)
    errors = []

    def run(i):
        try:
            results[i] = averagers[i].step_round(
                trees[i], matchmaking_timeout=matchmaking_timeout
            )
        except BaseException as e:
            errors.append((i, e))

    threads = [
        threading.Thread(target=run, args=(i,), daemon=True)
        for i in range(len(averagers))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
        assert not t.is_alive(), "averaging round hung"
    return results, errors


@pytest.fixture
def dht():
    d = DHT()
    yield d
    d.shutdown()


def _spawn(dht, n, cfg=None, chaos=None, peer_ids=None):
    cfg = cfg or AveragingConfig()
    out = []
    for i in range(n):
        out.append(
            DecentralizedAverager(
                dht, config=cfg,
                peer_id=(peer_ids[i] if peer_ids else f"peer{i:02d}"),
                chaos=(chaos[i] if chaos else None),
            )
        )
    return out


def test_two_peer_round_bitwise_identical(dht):
    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0)
    a, b = _spawn(dht, 2, cfg)
    trees = [_make_tree(0), _make_tree(1)]
    try:
        results, errors = _run_rounds([a, b], trees)
        assert not errors, errors
        (tree_a, info_a), (tree_b, info_b) = results
        assert info_a["gid"] == info_b["gid"]
        assert not info_a["degraded"] and not info_b["degraded"]
        for la, lb in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        # and the value IS the mean of the inputs
        want = jax.tree.map(lambda x, y: (x + y) / 2, *trees)
        for la, lw in zip(jax.tree.leaves(tree_a), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lw))
        assert a.stats()["rounds"] == 1 and b.stats()["rounds"] == 1
        assert a.stats()["bytes_sent"] > 0 and b.stats()["bytes_sent"] > 0
    finally:
        a.shutdown()
        b.shutdown()


def test_four_peer_butterfly_parity_with_local_mean(dht):
    # chunk_elems=7 forces multi-chunk partitions: the chunked wire path
    # must reassemble exactly
    cfg = AveragingConfig(min_group_size=4, max_group_size=4,
                          part_timeout=5.0, chunk_elems=7)
    avgs = _spawn(dht, 4, cfg)
    trees = [_make_tree(i) for i in range(4)]
    try:
        results, errors = _run_rounds(avgs, trees)
        assert not errors, errors
        # peers are sorted by peer_id == spawn order == trees order, so
        # the local reference accumulates in the same order
        want = jax.tree.map(lambda *xs: sum(xs) / 4, *trees)
        for tree_i, info in results:
            assert not info["degraded"], info
            assert info["group_size"] == 4
            for li, lw in zip(
                jax.tree.leaves(tree_i), jax.tree.leaves(want)
            ):
                np.testing.assert_array_equal(  # atol=0 parity
                    np.asarray(li), np.asarray(lw)
                )
    finally:
        for av in avgs:
            av.shutdown()


def test_member_death_mid_round_degrades_not_hangs(dht):
    part_timeout = 1.5
    cfg = AveragingConfig(
        min_group_size=3, max_group_size=3, part_timeout=part_timeout
    )
    avgs = _spawn(dht, 3, cfg)
    dead = avgs[2]  # a FOLLOWER (leader is the smallest peer id)
    dead.debug_die_after_match = True  # joins, then sends/serves nothing
    trees = [_make_tree(i) for i in range(3)]
    try:
        t0 = time.monotonic()
        results, errors = _run_rounds(avgs, trees)
        elapsed = time.monotonic() - t0
        assert not errors, errors
        # the configured bound: survivors must finish within the round
        # timeout, not hang on the dead peer
        assert elapsed < cfg.resolved_round_timeout() + 10
        (tree_a, info_a), (tree_b, info_b), (tree_c, info_c) = results
        assert tree_c is None and info_c.get("died_after_match")
        assert info_a["degraded"] and info_b["degraded"]
        assert avgs[0].stats()["degraded_rounds"] == 1
        assert avgs[1].stats()["degraded_rounds"] == 1
        # survivors' OWN partitions are the re-weighted mean over the two
        # survivors; the dead member's partition kept local values
        vecs = [flatten_tree(t)[0] for t in trees]
        bounds = partition_bounds(vecs[0].size, 3)
        got_a = flatten_tree(tree_a)[0]
        got_b = flatten_tree(tree_b)[0]
        for lo, hi in bounds[:2]:  # partitions owned by survivors
            want = (vecs[0][lo:hi] + vecs[1][lo:hi]) / np.float32(2.0)
            np.testing.assert_array_equal(got_a[lo:hi], want)
            np.testing.assert_array_equal(got_b[lo:hi], want)
        lo, hi = bounds[2]  # dead member's partition: local values kept
        np.testing.assert_array_equal(got_a[lo:hi], vecs[0][lo:hi])
        np.testing.assert_array_equal(got_b[lo:hi], vecs[1][lo:hi])
        assert 2 in info_a["failed_parts"] and 2 in info_b["failed_parts"]
    finally:
        for av in avgs:
            av.shutdown()


def test_late_joiner_waits_for_next_epoch(dht):
    from learning_at_home_tpu.server.chaos import ChaosConfig

    # follower bb's avg_part replies are chaos-delayed 1.5 s, so the
    # LEADER aa (whom cc will knock at) stays visibly mid-round waiting
    # for its bb-owned partition — a deterministic wait window for cc
    slow = ChaosConfig(averaging_base_latency=1.5, seed=0).make()
    cfg = AveragingConfig(
        min_group_size=2, max_group_size=3, part_timeout=6.0,
        gather_timeout=4.0,
    )
    a, b = _spawn(dht, 2, cfg, peer_ids=["aa", "bb"], chaos=[None, slow])
    late = DecentralizedAverager(dht, config=cfg, peer_id="cc")
    trees = [_make_tree(0), _make_tree(1)]
    try:
        round1 = {}

        def run_first(av, key, tree):
            round1[key] = av.step_round(tree, matchmaking_timeout=20.0)

        ta = threading.Thread(target=run_first, args=(a, "a", trees[0]),
                              daemon=True)
        tb = threading.Thread(target=run_first, args=(b, "b", trees[1]),
                              daemon=True)
        ta.start()
        tb.start()
        # wait until the leader froze the group and is mid-round, THEN
        # knock: cc must be told to wait for the next epoch, never break
        # into the running round
        deadline = time.monotonic() + 15
        while not a._round_active and time.monotonic() < deadline:
            time.sleep(0.02)
        assert a._round_active, "round 1 never became active"
        late_result = {}

        def run_late():
            late_result["r"] = late.step_round(
                _make_tree(2), matchmaking_timeout=40.0
            )

        tl = threading.Thread(target=run_late, daemon=True)
        tl.start()
        ta.join(timeout=45)
        tb.join(timeout=45)
        assert not ta.is_alive() and not tb.is_alive()
        epoch1 = round1["a"][1]["epoch"]
        assert round1["a"][1]["members"] == ["aa", "bb"]
        # round 2: aa and bb go again; cc (still retrying) joins this one
        results, errors = _run_rounds(
            [a, b], trees, matchmaking_timeout=30.0
        )
        assert not errors, errors
        tl.join(timeout=60)
        assert not tl.is_alive(), "late joiner hung"
        assert "r" in late_result
        _, late_info = late_result["r"]
        assert late_info["epoch"] > epoch1
        assert "cc" in late_info["members"]
        assert late.stats()["late_join_waits"] >= 1
    finally:
        a.shutdown()
        b.shutdown()
        late.shutdown()


def test_chaos_dropped_frames_trigger_timeout_path(dht):
    from learning_at_home_tpu.server.chaos import ChaosConfig

    # peer1's handler drops every avg_part REPLY: peer0's sends to it
    # time out → peer0 completes degraded; the data still reached peer1,
    # so peer1's own partition reduces fully
    chaos = ChaosConfig(averaging_drop_prob=1.0, seed=0).make()
    cfg = AveragingConfig(
        min_group_size=2, max_group_size=2, part_timeout=1.0,
        sender_timeout=2.0, round_timeout=6.0,
    )
    a, b = _spawn(dht, 2, cfg, chaos=[None, chaos])
    try:
        t0 = time.monotonic()
        results, errors = _run_rounds(
            [a, b], [_make_tree(0), _make_tree(1)]
        )
        assert not errors, errors
        assert time.monotonic() - t0 < 30
        (_, info_a), (_, info_b) = results
        assert info_a["degraded"], info_a  # the dropped-reply partition
        assert 1 in info_a["failed_parts"]
        assert chaos.injected_averaging_drops >= 1
        assert a.stats()["degraded_rounds"] == 1
    finally:
        a.shutdown()
        b.shutdown()


def test_chunk_cap_prevents_held_reply_starvation(dht):
    """chunk_elems=1 on a ~500-element tree would mean ~250 held-reply
    chunk RPCs per partition — far over the mux in-flight limit (64),
    which deadlocks-until-timeout because reduction needs ALL chunks
    admitted before ANY reply resolves.  The MAX_CHUNKS_PER_PART cap
    widens chunks instead; the round must complete cleanly."""
    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0, chunk_elems=1)
    a, b = _spawn(dht, 2, cfg)
    trees = [_make_tree(i, d=29) for i in range(2)]  # 3*29 + 29 = 116/leafset
    try:
        results, errors = _run_rounds([a, b], trees)
        assert not errors, errors
        (tree_a, info_a), (tree_b, _) = results
        assert not info_a["degraded"], info_a
        want = jax.tree.map(lambda x, y: (x + y) / 2, *trees)
        for la, lw in zip(jax.tree.leaves(tree_a), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lw))
    finally:
        a.shutdown()
        b.shutdown()


def test_averaging_survives_global_v1_pin(dht):
    """The legacy/A-B dispatch switch pins protocol v1 process-wide, but
    averaging's held replies REQUIRE the v2 out-of-order contract — its
    pools opt out of the pin (require_v2) and must still negotiate v2."""
    from learning_at_home_tpu.utils.connection import force_protocol_v1

    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0)
    a, b = _spawn(dht, 2, cfg)
    force_protocol_v1(True)
    try:
        results, errors = _run_rounds([a, b], [_make_tree(0), _make_tree(1)])
        assert not errors, errors
        (tree_a, info_a), (tree_b, _) = results
        assert not info_a["degraded"], info_a
        for la, lb in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        assert all(p._proto == 2 for p in a._registry.pools())
    finally:
        force_protocol_v1(False)
        a.shutdown()
        b.shutdown()


def test_matchmaking_times_out_alone(dht):
    cfg = AveragingConfig(min_group_size=2, poll=0.1)
    av = _spawn(dht, 1, cfg)[0]
    try:
        with pytest.raises(AveragingFailed):
            av.step_round(_make_tree(0), matchmaking_timeout=1.5)
        assert av.stats()["matchmaking_failures"] == 1
    finally:
        av.shutdown()


def test_weighted_degraded_mean_reweights(dht):
    """Unequal weights: the survivors' mean uses THEIR weights only."""
    cfg_a = AveragingConfig(min_group_size=3, max_group_size=3,
                            part_timeout=1.5, weight=1.0)
    cfg_b = AveragingConfig(min_group_size=3, max_group_size=3,
                            part_timeout=1.5, weight=3.0)
    cfg_dead = AveragingConfig(min_group_size=3, max_group_size=3,
                               part_timeout=1.5)
    a = DecentralizedAverager(dht, config=cfg_a, peer_id="pa")
    b = DecentralizedAverager(dht, config=cfg_b, peer_id="pb")
    dead = DecentralizedAverager(dht, config=cfg_dead, peer_id="pz")
    dead.debug_die_after_match = True
    trees = [_make_tree(0), _make_tree(1), _make_tree(2)]
    try:
        results, errors = _run_rounds([a, b, dead], trees)
        assert not errors, errors
        (tree_a, info_a), _, _ = results
        assert info_a["degraded"]
        vecs = [flatten_tree(t)[0] for t in trees]
        bounds = partition_bounds(vecs[0].size, 3)
        lo, hi = bounds[0]  # partition owned by pa (sorted first)
        want = (
            vecs[0][lo:hi] * np.float32(1.0)
            + vecs[1][lo:hi] * np.float32(3.0)
        ) / np.float32(4.0)
        got = flatten_tree(tree_a)[0][lo:hi]
        np.testing.assert_array_equal(got, want)
    finally:
        for av in (a, b, dead):
            av.shutdown()


def test_session_background_delta_apply(dht):
    """Background mode (PipelinedSwarmTrainer's shape): notify_step kicks
    a round off-thread; the group delta is applied through apply_fn.
    With no steps taken during the round, delta-apply == group mean."""
    from learning_at_home_tpu.averaging import AveragingSession

    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0)
    a, b = _spawn(dht, 2, cfg)
    sa = AveragingSession(a, every_steps=1)
    sb = AveragingSession(b, every_steps=1)
    params = [_make_tree(0), _make_tree(1)]
    snap0 = [params[0], params[1]]
    locks = [threading.Lock(), threading.Lock()]

    def wire(i, session):
        def snapshot():
            with locks[i]:
                return params[i]

        def apply_fn(transform):
            with locks[i]:
                params[i] = transform(params[i])

        session.attach_trainer(snapshot, apply_fn)

    try:
        wire(0, sa)
        wire(1, sb)
        sa.notify_step(1)
        sb.notify_step(1)
        deadline = time.monotonic() + 45
        while time.monotonic() < deadline:
            if sa.rounds_applied >= 1 and sb.rounds_applied >= 1:
                break
            time.sleep(0.05)
        assert sa.rounds_applied == 1 and sb.rounds_applied == 1, (
            sa.averaging_stats(), sb.averaging_stats()
        )
        want = jax.tree.map(lambda x, y: (x + y) / 2, snap0[0], snap0[1])
        for i in range(2):
            for leaf, lw in zip(
                jax.tree.leaves(params[i]), jax.tree.leaves(want)
            ):
                np.testing.assert_allclose(
                    np.asarray(leaf), np.asarray(lw), atol=1e-6
                )
    finally:
        sa.shutdown()
        sb.shutdown()


def test_session_blocking_round_and_stats(dht):
    from learning_at_home_tpu.averaging import AveragingSession

    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0)
    a, b = _spawn(dht, 2, cfg)
    sa, sb = AveragingSession(a), AveragingSession(b)
    trees = [_make_tree(0), _make_tree(1)]
    out = [None, None]
    try:
        threads = [
            threading.Thread(
                target=lambda i, s: out.__setitem__(
                    i, s.blocking_round(trees[i], matchmaking_timeout=20.0)
                ),
                args=(i, s), daemon=True,
            )
            for i, s in enumerate((sa, sb))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive()
        for la, lb in zip(jax.tree.leaves(out[0]), jax.tree.leaves(out[1])):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        stats = sa.averaging_stats()
        assert stats["rounds"] == 1 and stats["rounds_applied"] == 1
        assert stats["round_p50_ms"] is not None
        # a lone failed round is counted, not raised
        lone = sa.blocking_round(trees[0], matchmaking_timeout=0.5)
        assert lone is trees[0]
        assert sa.averaging_stats()["rounds_skipped"] == 1
    finally:
        sa.shutdown()
        sb.shutdown()


# ---------------------------------------------------------------------------
# quantized wire chunks (ISSUE 5): only the wire compresses — the f32
# sorted-peer reduction and the bitwise-equality contract are untouched
# ---------------------------------------------------------------------------


def test_quantized_wire_keeps_members_bitwise_identical(dht):
    """With blockq8 chunks, every member must still end with IDENTICAL
    bytes per reduced partition (replies stay raw f32 — one exact result
    distribution), within quantization error of the true mean, with the
    contribute direction actually quantized (counter + bytes)."""
    cfg = AveragingConfig(min_group_size=3, max_group_size=3,
                          part_timeout=3.0, chunk_elems=1 << 10,
                          wire_codec="blockq8")
    avs = _spawn(dht, 3, cfg)
    trees = [_make_tree(i, d=997) for i in range(3)]
    try:
        results, errors = _run_rounds(avs, trees)
        assert not errors, errors
        outs = [r[0] for r in results]
        for r in results:
            assert not r[1]["degraded"], r[1]
        for other in outs[1:]:
            for la, lb in zip(jax.tree.leaves(outs[0]),
                              jax.tree.leaves(other)):
                np.testing.assert_array_equal(
                    np.asarray(la), np.asarray(lb)
                )
        exact = jax.tree.map(lambda *xs: sum(xs) / len(xs), *trees)
        for la, le in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(exact)):
            err = float(np.abs(np.asarray(la) - np.asarray(le)).max())
            assert err < 0.1, err  # quantization-bounded, not exact
        stats = [av.stats() for av in avs]
        assert all(s["quantized_chunks"] > 0 for s in stats), stats
        assert all(s["wire_codec"] == "blockq8" for s in stats)
        # contribute direction really shrank: quantized bytes received
        # are well under the raw-f32 volume a ``none`` round would move
        raw_per_owner = sum(t.size for t in jax.tree.leaves(trees[0])) * 4
        for s in stats:
            assert s["bytes_received"] < raw_per_owner, (
                s["bytes_received"], raw_per_owner,
            )
    finally:
        for av in avs:
            av.shutdown()


def test_quantized_wire_falls_back_against_no_codec_owner(dht, monkeypatch):
    """An owner whose hello does not advertise ``codec`` (old build) must
    transparently receive raw f32 chunks — the round still completes and
    stays exact."""
    from learning_at_home_tpu.averaging import handler as avg_handler

    monkeypatch.setattr(avg_handler, "AVERAGING_FEATURES", ("mux",))
    cfg = AveragingConfig(min_group_size=2, max_group_size=2,
                          part_timeout=3.0, wire_codec="u8")
    a, b = _spawn(dht, 2, cfg)
    trees = [_make_tree(0), _make_tree(1)]
    try:
        results, errors = _run_rounds([a, b], trees)
        assert not errors, errors
        (tree_a, info_a), (tree_b, _) = results
        assert not info_a["degraded"]
        want = jax.tree.map(lambda x, y: (x + y) / 2, *trees)
        for la, lw in zip(jax.tree.leaves(tree_a), jax.tree.leaves(want)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lw))
        # nothing arrived quantized: the senders saw no codec feature
        assert a.stats()["quantized_chunks"] == 0
        assert b.stats()["quantized_chunks"] == 0
    finally:
        a.shutdown()
        b.shutdown()
