"""Pytree-input experts over the wire (SURVEY §2 'Nested structures')."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.server import ExpertBackend, Server
from learning_at_home_tpu.utils.nested import (
    nested_flatten,
    schema_from_tree,
    tree_from_schema,
)

HID = 16


def test_schema_roundtrip():
    tree = {"b": (np.ones(2), [np.zeros(1)]), "a": np.ones(3)}
    schema = schema_from_tree(tree)
    leaves = nested_flatten(tree)
    rebuilt = tree_from_schema(schema, leaves)
    assert set(rebuilt) == {"a", "b"}
    assert isinstance(rebuilt["b"], tuple) and isinstance(rebuilt["b"][1], list)
    np.testing.assert_array_equal(rebuilt["a"], tree["a"])
    with pytest.raises(ValueError, match="extra"):
        tree_from_schema(schema, leaves + [np.ones(1)])
    with pytest.raises(ValueError, match="too few"):
        tree_from_schema(schema, leaves[:-1])


def test_schema_ordereddict_and_none():
    from collections import OrderedDict

    # OrderedDict: insertion order must survive (jax flattens it that way)
    od = OrderedDict([("x", np.ones(2)), ("a", np.zeros(3))])
    leaves = nested_flatten(od)
    rebuilt = tree_from_schema(schema_from_tree(od), leaves)
    np.testing.assert_array_equal(rebuilt["x"], od["x"])
    np.testing.assert_array_equal(rebuilt["a"], od["a"])
    assert list(rebuilt) == ["x", "a"]

    # None is structure, not a leaf
    tree = {"a": np.ones(2), "b": None}
    leaves = nested_flatten(tree)
    assert len(leaves) == 1
    rebuilt = tree_from_schema(schema_from_tree(tree), leaves)
    assert rebuilt["b"] is None
    np.testing.assert_array_equal(rebuilt["a"], tree["a"])


def test_n_inputs_structure_contradiction():
    import optax

    with pytest.raises(ValueError, match="contradicts"):
        ExpertBackend(
            "bad",
            lambda p, t: t,
            {"w": jnp.ones(1)},
            optax.sgd(0.1),
            n_inputs=3,
            input_structure={"a": np.zeros(1), "b": np.zeros(1)},
        )


@pytest.fixture(scope="module")
def pytree_server():
    # expert takes {"scale": [n,1], "x": [n,HID]} → x * scale @ W
    def init(rng):
        return {"w": jax.random.normal(rng, (HID, HID)) * 0.1}

    def apply_fn(params, tree):
        return (tree["x"] * tree["scale"]) @ params["w"]

    structure = {"scale": np.zeros((1, 1)), "x": np.zeros((1, HID))}
    backend = ExpertBackend(
        "py.0",
        apply_fn,
        init(jax.random.PRNGKey(0)),
        optax.sgd(0.01),
        input_structure=structure,
    )
    server = Server({"py.0": backend}, host="127.0.0.1")
    server.run_in_background()
    yield server
    server.shutdown()
    reset_client_rpc()


def test_structure_mismatch_fails_loudly(pytree_server):
    """A client whose nest flattens differently must get an error, not
    silently swapped tensor bindings."""
    from collections import OrderedDict

    srv = pytree_server
    expert = RemoteExpert("py.0", srv.endpoint, output_spec_fn=lambda *s: s[1])
    x = jnp.ones((2, HID))
    scale = jnp.ones((2, 1))
    # insertion order x-then-scale ≠ server's sorted scale-then-x
    bad = OrderedDict([("x", x), ("scale", scale)])
    with pytest.raises(ValueError, match="structure mismatch"):
        expert(bad)


def test_wrong_forward_arity_rejected_cleanly(pytree_server):
    """Wrong tensor count is rejected at the handler, not inside a batch."""
    from learning_at_home_tpu.utils.connection import RemoteCallError

    srv = pytree_server
    expert = RemoteExpert("py.0", srv.endpoint)
    with pytest.raises(RemoteCallError, match="takes 2 inputs"):
        expert.forward_blocking([np.ones((2, HID), np.float32)])


def test_wrong_backward_arity_rejected_cleanly(pytree_server):
    """A backward request with no grad_output tensors (arity == n_inputs)
    must be rejected at the handler, before it can poison a formed batch."""
    from learning_at_home_tpu.client.rpc import client_loop, pool_registry
    from learning_at_home_tpu.utils.connection import RemoteCallError

    srv = pytree_server

    async def call():
        return await pool_registry().get(srv.endpoint).rpc(
            "backward",
            [np.ones((2, 1), np.float32), np.ones((2, HID), np.float32)],
            {"uid": "py.0", "n_inputs": 2},
            timeout=5.0,
        )

    with pytest.raises(RemoteCallError, match="grad_outputs"):
        client_loop().run(call())


def test_pytree_expert_forward_and_grad(pytree_server):
    srv = pytree_server
    # leaves arrive in flattened (sorted-key) order: [scale, x]; the
    # output is x-shaped, so point the spec at leaf 1
    expert = RemoteExpert(
        "py.0", srv.endpoint, output_spec_fn=lambda *specs: specs[1]
    )
    info = expert.info()
    assert info["n_inputs"] == 2
    assert info["input_schema"]["t"] == "d"

    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, HID).astype(np.float32))
    scale = jnp.asarray(rs.randn(4, 1).astype(np.float32))
    tree = {"scale": scale, "x": x}

    out = expert(tree)
    params = srv.experts["py.0"].state_dict()["params"]
    expected = (np.asarray(x) * np.asarray(scale)) @ params["w"]
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)

    # grads flow back INTO the nest
    def loss(tree):
        return jnp.sum(expert(tree) ** 2)

    g = jax.grad(loss)(tree)
    assert set(g) == {"scale", "x"}
    assert float(jnp.abs(g["x"]).sum()) > 0
    assert float(jnp.abs(g["scale"]).sum()) > 0
    # server applied its async update through the pytree backward
    assert srv.experts["py.0"].update_count == 1
