"""The unified metrics registry (ISSUE 4): instruments, bounded label
sets, collectors, Prometheus/JSON export, the Timeline counter-key cap,
and the disabled-path overhead bound."""

import json
import re
import threading
import time

import numpy as np
import pytest

from learning_at_home_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    sanitize_metric_name,
)
from learning_at_home_tpu.utils.profiling import Timeline, new_trace_id


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------


def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("lah_t_total", "things")
    c.inc()
    c.inc(2.5)
    c.inc(1, pool="a")
    assert c.value() == 3.5
    assert c.value(pool="a") == 1.0
    with pytest.raises(ValueError):
        c.inc(-1)

    g = reg.gauge("lah_t_gauge")
    g.set(7)
    g.inc(3)
    assert g.value() == 10.0

    h = reg.histogram("lah_t_hist", buckets=(1.0, 10.0))
    for v in (0.5, 5.0, 50.0):
        h.observe(v)
    snap = reg.snapshot()
    hs = snap["histograms"]["lah_t_hist"]
    assert hs["count"] == 3 and hs["sum"] == 55.5
    assert hs["buckets"]["1.0"] == 1 and hs["buckets"]["10.0"] == 2


def test_kind_conflict_raises():
    reg = MetricsRegistry()
    reg.counter("lah_x")
    with pytest.raises(ValueError):
        reg.gauge("lah_x")


def test_name_sanitization():
    assert sanitize_metric_name("runtime.stack.ffn.0") == "runtime_stack_ffn_0"
    assert sanitize_metric_name("9lives") == "_9lives"
    reg = MetricsRegistry()
    c = reg.counter("a.b-c")
    assert c.name == "a_b_c"


# ---------------------------------------------------------------------------
# bounded label sets — a long-lived peer must not leak cardinality
# ---------------------------------------------------------------------------


def test_label_sets_bounded_with_overflow_bucket():
    reg = MetricsRegistry(max_label_sets=8)
    c = reg.counter("lah_bounded_total")
    for i in range(50):
        c.inc(1, uid=f"expert.{i}")
    with c._lock:
        keys = set(c._values)
    # 8 admitted + the single overflow series
    assert len(keys) == 9
    assert (("overflow", "true"),) in keys
    # every observation was still counted somewhere
    snap = reg.snapshot()
    assert sum(snap["counters"]["lah_bounded_total"].values()) == 50
    assert snap["dropped_label_sets"] == 42
    text = reg.render_prometheus()
    assert 'overflow="true"' in text
    assert "lah_metrics_dropped_label_sets_total 42" in text


# ---------------------------------------------------------------------------
# collectors
# ---------------------------------------------------------------------------


def test_collectors_merge_rule_sum_totals_max_rest():
    """_total names sum across collectors (event counts add); anything
    else takes the MAX — summing two MoE layers' dispatch p50s would
    report 2x the true latency (review finding, PR 4)."""
    reg = MetricsRegistry()
    reg.register_collector(
        "layer0", lambda: {"lah_d_total": 2, "lah_d_p50_ms": 7.0}
    )
    reg.register_collector(
        "layer1", lambda: {"lah_d_total": 3, "lah_d_p50_ms": 5.0}
    )
    merged = reg.collect()
    assert merged["lah_d_total"] == 5.0
    assert merged["lah_d_p50_ms"] == 7.0  # worst layer, never the sum


def test_collectors_sum_and_prune():
    reg = MetricsRegistry()
    reg.register_collector("a", lambda: {"lah_widgets_total": 2})
    reg.register_collector("b", lambda: {"lah_widgets_total": 3})
    assert reg.collect()["lah_widgets_total"] == 5.0
    # a collector returning None is pruned (the weakref-died idiom)
    alive = {"flag": True}
    reg.register_collector(
        "c", lambda: {"lah_gone": 1} if alive["flag"] else None
    )
    assert reg.collect()["lah_gone"] == 1.0
    alive["flag"] = False
    assert "lah_gone" not in reg.collect()
    with reg._lock:
        assert "c" not in reg._collectors
    # a CRASHING collector is skipped, never fatal
    reg.register_collector("boom", lambda: 1 / 0)
    assert reg.collect()["lah_widgets_total"] == 5.0


def test_weakref_component_collector_prunes_after_gc():
    import gc

    reg = MetricsRegistry()

    class Component:
        def metrics(self):
            return {"lah_component_up": 1}

    import weakref

    comp = Component()
    ref = weakref.ref(comp)
    reg.register_collector(
        "comp", lambda: ref().metrics() if ref() else None
    )
    assert reg.collect()["lah_component_up"] == 1.0
    del comp
    gc.collect()
    assert "lah_component_up" not in reg.collect()


# ---------------------------------------------------------------------------
# export formats
# ---------------------------------------------------------------------------

# one exposition line: metric name, optional {labels}, numeric value
_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [0-9eE+.\-]+(\.[0-9]+)?$"
)


def test_prometheus_text_parses():
    reg = MetricsRegistry(max_label_sets=4)
    reg.counter("lah_req_total", "requests served").inc(3, op="forward")
    reg.gauge("lah_depth").set(2)
    h = reg.histogram("lah_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    reg.register_collector("x", lambda: {"lah_collected": 1.5})
    text = reg.render_prometheus()
    assert text.endswith("\n")
    seen_types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ")
            assert kind in ("counter", "gauge", "histogram", "untyped")
            seen_types[name] = kind
            continue
        assert _PROM_LINE.match(line), f"bad exposition line: {line!r}"
    assert seen_types["lah_req_total"] == "counter"
    assert seen_types["lah_lat_seconds"] == "histogram"
    # histogram series: cumulative buckets + +Inf + sum/count
    assert 'lah_lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lah_lat_seconds_count 2" in text
    assert "lah_collected 1.5" in text


def test_snapshot_is_json_and_msgpack_safe():
    import msgpack

    reg = MetricsRegistry()
    reg.counter("lah_a").inc(1, uid="x.1")
    reg.gauge("lah_b").set(0.5)
    reg.histogram("lah_c").observe(0.2)
    reg.register_collector("k", lambda: {"lah_d": 4})
    snap = reg.snapshot()
    json.dumps(snap)  # raises on anything non-serializable
    msgpack.packb(snap, use_bin_type=True)  # the stats-RPC wire constraint


# ---------------------------------------------------------------------------
# Timeline counter-key cap (ISSUE 4 satellite: bounded key growth)
# ---------------------------------------------------------------------------


def test_timeline_counter_keys_bounded():
    tl = Timeline(max_counter_keys=8)
    tl.enable()
    for i in range(40):
        tl.count(f"bucket.{i}", 2.0)
    counters = tl.counters()
    # 8 real keys + the two reserved accounting keys
    assert len(counters) == 10
    assert counters["timeline.dropped_keys"] == 32
    assert counters["timeline.overflow"] == 64.0
    # resident keys keep counting normally at the cap
    tl.count("bucket.0", 1.0)
    assert tl.counters()["bucket.0"] == 3.0
    # reserved keys always work, even at the cap
    tl.count("timeline.dropped_keys", 0.0)


def test_timeline_cap_resets_on_clear():
    tl = Timeline(max_counter_keys=4)
    tl.enable()
    for i in range(10):
        tl.count(f"k.{i}")
    tl.clear()
    tl.count("fresh")
    assert tl.counters() == {"fresh": 1.0}


# ---------------------------------------------------------------------------
# trace ids + disabled-path overhead
# ---------------------------------------------------------------------------


def test_trace_ids_compact_and_unique():
    ids = {new_trace_id() for _ in range(256)}
    assert len(ids) == 256
    assert all(len(t) == 16 and int(t, 16) >= 0 for t in ids)


def test_disabled_timeline_no_spans_no_counters_no_trace_cost():
    tl = Timeline()
    tl.disable()
    with tl.span("x", trace="deadbeefdeadbeef"):
        pass
    tl.count("y")
    assert tl.summary() == {} and tl.counters() == {}
    assert tl.chrome_trace()[1:] == []  # only the process_name metadata


def test_registry_disabled_path_overhead_bounded():
    """Mirror of test_client_pipeline's no-work-on-loop regression, in
    time form: with nothing scraping, the always-on surfaces cost plain
    attribute arithmetic.  The bound is deliberately loose (sandbox CPUs
    swing wildly) — it exists to catch an accidental O(n) or I/O on the
    increment path, not to benchmark."""
    reg = MetricsRegistry()
    c = reg.counter("lah_hot_total")
    n = 10_000
    t0 = time.perf_counter()
    for _ in range(n):
        c.inc()
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 100e-6, f"counter.inc costs {per_call * 1e6:.1f}µs"
    # scrape-time work must not mutate instrument state
    before = c.value()
    reg.render_prometheus()
    reg.snapshot()
    assert c.value() == before


def test_registry_scrape_never_runs_on_hot_thread():
    """Collectors are scrape-time only: incrementing instruments must
    not invoke any registered collector (the hot path would otherwise
    pay arbitrary component-stats costs per dispatch)."""
    reg = MetricsRegistry()
    calls = []
    reg.register_collector("probe", lambda: calls.append(1) or {})
    c = reg.counter("lah_hot2_total")
    for _ in range(100):
        c.inc()
    assert calls == []
    reg.collect()
    assert calls == [1]


def test_concurrent_increments_are_consistent():
    reg = MetricsRegistry()
    c = reg.counter("lah_mt_total")

    def worker():
        for _ in range(1000):
            c.inc()

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value() == 4000


# ---------------------------------------------------------------------------
# chrome trace export (unit level; end-to-end in test_observability)
# ---------------------------------------------------------------------------


def test_chrome_trace_event_shape(tmp_path):
    tl = Timeline()
    tl.enable()
    t0 = time.monotonic()
    tl.record("outer", t0, 0.010, trace="aa" * 8)
    tl.record("inner", t0 + 0.002, 0.004, trace="aa" * 8)
    tl.record("untraced", t0, 0.001)
    events = tl.chrome_trace(process_name="unit")
    meta = events[0]
    assert meta["ph"] == "M" and meta["args"]["name"] == "unit"
    xs = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in xs} == {"outer", "inner", "untraced"}
    for e in xs:
        assert set(e) >= {"name", "ts", "dur", "pid", "tid", "cat"}
    traced = {e["name"]: e for e in xs if "args" in e}
    assert traced["outer"]["args"]["trace"] == "aa" * 8
    assert "untraced" not in traced
    # inner nests inside outer on the time axis
    o, i = traced["outer"], traced["inner"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"]
    # file export round-trips as JSON
    path = tmp_path / "trace.json"
    n = tl.save_chrome_trace(str(path), extra_events=[{"ph": "M", "pid": 9}])
    doc = json.loads(path.read_text())
    assert len(doc["traceEvents"]) == n == len(events) + 1
