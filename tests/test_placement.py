"""Co-activation-aware placement (ISSUE 16): the pure cost model +
seeded local-search solver, the `links.*` telemetry parsers, the
rebalancer's pure snapshot builder and SLO gate, and the `--plan` CLI's
byte-determinism contract (what the collect-gate placement stage runs).

Live migration actuation (real servers, the `migrate` RPC) lives in
test_migration.py; the routing-side link-prior fallback lives in
test_routing_cost.py.
"""

import importlib.util
import json
import os
import subprocess
import sys

from learning_at_home_tpu.analysis.placement import (
    DEFAULT_RTT_S,
    pair_key,
    placement_cost,
    plan_to_json,
    solve,
)
from learning_at_home_tpu.utils.telemetry import (
    MAX_ADVERTISED_LINKS,
    links_key,
    parse_links_value,
)

NODE_A = "10.0.0.1:31330"
NODE_B = "10.0.0.2:31330"


def clustered_snapshot():
    """Two co-activation clusters split across two nodes with a slow,
    measured inter-node link: the optimum consolidates each cluster."""
    return {
        "experts": {
            "e.0": NODE_A, "e.1": NODE_B,
            "e.2": NODE_A, "e.3": NODE_B,
        },
        "activations": {"e.0": 100, "e.1": 100, "e.2": 100, "e.3": 100},
        "coact": {"e.0|e.1": 50, "e.2|e.3": 50},
        "links": {NODE_A: {NODE_B: [0.03, 1.0e8]}},
        "sources": {"trainer-a": 1.0},
        "bytes_per_dispatch": 2.0e6,
    }


# ---- pair_key / cost model ----


def test_pair_key_canonical_order():
    assert pair_key("b", "a") == "a|b" == pair_key("a", "b")


def test_placement_cost_counts_cross_node_pairs_once():
    snap = clustered_snapshot()
    cost = placement_cost(snap)
    # both pairs straddle the measured link: 50·(0.03 + 2e6/1e8) each,
    # plus the source term at DEFAULT_RTT_S per activation
    link = 0.03 + 2.0e6 / 1.0e8
    assert abs(cost - (100 * link + 400 * DEFAULT_RTT_S)) < 1e-9


def test_colocated_pair_costs_zero():
    snap = clustered_snapshot()
    snap["experts"]["e.1"] = NODE_A
    snap["experts"]["e.3"] = NODE_A
    snap.pop("sources")
    assert placement_cost(snap) == 0.0


# ---- solver ----


def test_solve_consolidates_clusters_and_improves_cost():
    plan = solve(clustered_snapshot(), seed=0)
    assert plan["moves"], plan
    assert plan["cost_after"] < plan["cost_before"]
    # every cluster ends co-located
    final = {u: n for u, n in clustered_snapshot()["experts"].items()}
    for m in plan["moves"]:
        final[m["uid"]] = m["to"]
    assert final["e.0"] == final["e.1"]
    assert final["e.2"] == final["e.3"]


def test_solve_deterministic_byte_identical_per_seed():
    a = plan_to_json(solve(clustered_snapshot(), seed=7))
    b = plan_to_json(solve(clustered_snapshot(), seed=7))
    assert a == b
    # a different seed may visit differently but still returns a plan
    assert isinstance(solve(clustered_snapshot(), seed=8)["moves"], list)


def test_solve_respects_capacity():
    snap = clustered_snapshot()
    snap["capacity"] = {NODE_A: 2, NODE_B: 2}
    plan = solve(snap, seed=0)
    occupancy = {NODE_A: 0, NODE_B: 0}
    final = dict(snap["experts"])
    for m in plan["moves"]:
        final[m["uid"]] = m["to"]
    for node in final.values():
        occupancy[node] += 1
    assert occupancy[NODE_A] <= 2 and occupancy[NODE_B] <= 2


def test_solve_caps_distinct_moved_experts():
    # a 12-expert chain all wanting to consolidate; max_moves must bound
    # the DISTINCT experts moved, keeping plans executable move-for-move
    uids = [f"m.{i}" for i in range(12)]
    snap = {
        "experts": {u: (NODE_A if i % 2 else NODE_B)
                    for i, u in enumerate(uids)},
        "coact": {pair_key(uids[i], uids[i + 1]): 100
                  for i in range(len(uids) - 1)},
        "links": {NODE_A: {NODE_B: [0.05, None]}},
        "bytes_per_dispatch": 0.0,
    }
    plan = solve(snap, seed=0, max_moves=3)
    assert 0 < len({m["uid"] for m in plan["moves"]}) <= 3


def swap_locked_snapshot():
    """Two co-activation clusters split across two FULL nodes (cap ==
    occupancy): no single-expert move is admissible, only a pair swap
    can consolidate the clusters."""
    return {
        "experts": {
            "a.0": NODE_A, "a.1": NODE_B,
            "b.0": NODE_A, "b.1": NODE_B,
        },
        "coact": {"a.0|a.1": 500, "b.0|b.1": 500},
        "links": {NODE_A: {NODE_B: [0.04, 5.0e7]}},
        "capacity": {NODE_A: 2, NODE_B: 2},
        "bytes_per_dispatch": 1.5e6,
    }


def test_swap_untangles_capacity_locked_nodes():
    snap = swap_locked_snapshot()
    plan = solve(snap, seed=0)
    assert len(plan["moves"]) == 2, plan
    assert plan["cost_after"] < plan["cost_before"]
    final = dict(snap["experts"])
    occupancy = {NODE_A: 0, NODE_B: 0}
    for m in plan["moves"]:
        final[m["uid"]] = m["to"]
    for node in final.values():
        occupancy[node] += 1
    # occupancy unchanged (a swap is capacity-neutral), clusters joined
    assert occupancy == {NODE_A: 2, NODE_B: 2}
    assert final["a.0"] == final["a.1"]
    assert final["b.0"] == final["b.1"]


def test_swap_plans_byte_deterministic_per_seed():
    for seed in (0, 7, 1234):
        a = plan_to_json(solve(swap_locked_snapshot(), seed=seed))
        b = plan_to_json(solve(swap_locked_snapshot(), seed=seed))
        assert a == b


def test_swap_respects_max_moves_budget():
    # a swap moves TWO distinct experts; with budget 1 it must not fire
    plan = solve(swap_locked_snapshot(), seed=0, max_moves=1)
    assert plan["moves"] == []
    assert plan["cost_after"] == plan["cost_before"]


def test_solve_tolerates_garbage_snapshots():
    for snap in (
        None, [], {}, {"experts": "nope"},
        {"experts": {1: 2, "u": None}},
        {"experts": {"u": NODE_A}},  # one node: nothing to solve
        {"experts": {"u": NODE_A, "v": NODE_B},
         "coact": {"u|v": float("nan"), 3: 1, "u|u": 5, "u|ghost": 2},
         "links": {NODE_A: "junk", 7: {}},
         "activations": {"u": -1, "v": True},
         "sources": {"s": "hot"},
         "bytes_per_dispatch": "many"},
    ):
        plan = solve(snap, seed=0)
        assert plan["moves"] == []
        assert plan["cost_after"] == plan["cost_before"]


def test_gain_fields_sorted_and_positive():
    plan = solve(clustered_snapshot(), seed=0)
    gains = [m["gain"] for m in plan["moves"]]
    assert gains == sorted(gains, reverse=True)
    assert all(g > 0 for g in gains)


# ---- links.* telemetry parsing ----


def test_links_key_scoped_by_prefix():
    assert links_key("swarm") == "links.swarm"
    assert links_key("other") != links_key("swarm")


def test_parse_links_value_roundtrip_and_garbage():
    got = parse_links_value(
        {"l": {"10.0.0.2:31330": [0.02, 1.5e8],
               "10.0.0.3:31330": [0.05, None]}}
    )
    assert got == {
        "10.0.0.2:31330": {"rtt_s": 0.02, "bw_bps": 1.5e8},
        "10.0.0.3:31330": {"rtt_s": 0.05, "bw_bps": None},
    }
    # outer-shape garbage -> None; per-entry garbage -> skipped
    for bad in (None, 17, [], "x", {"nope": {}}, {"l": "x"}):
        assert parse_links_value(bad) is None
    partial = parse_links_value(
        {"l": {"10.0.0.2:31330": [0.02, 1e8],
               "noport": [0.01, 1e8],          # dst must look host:port
               "10.0.0.4:1": ["fast", 1e8],    # rtt must be numeric
               "10.0.0.5:1": [float("nan")],   # NaN rtt is garbage
               "10.0.0.6:1": [-0.1],           # negative rtt is garbage
               "10.0.0.7:1": [0.03, -5]}}      # bad bw degrades to None
    )
    assert set(partial) == {"10.0.0.2:31330", "10.0.0.7:1"}
    assert partial["10.0.0.7:1"] == {"rtt_s": 0.03, "bw_bps": None}
    assert MAX_ADVERTISED_LINKS >= 1


# ---- rebalancer: pure snapshot builder + SLO gate ----


_REBALANCE_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tools", "lah_rebalance.py",
)
_REBALANCE_MOD = None


def _rebalance():
    global _REBALANCE_MOD
    if _REBALANCE_MOD is None:
        spec = importlib.util.spec_from_file_location(
            "lah_rebalance_placement", _REBALANCE_PATH
        )
        _REBALANCE_MOD = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(_REBALANCE_MOD)
    return _REBALANCE_MOD


def test_build_snapshot_merges_servers_trainers_and_dht_links():
    reb = _rebalance()
    rows = [
        {"peer_id": "srv-a", "role": "server", "snapshot": {
            "endpoint": ["10.0.0.1", 31330],
            "experts": {"e.0": 5, "e.2": 3}}},
        {"peer_id": "srv-b", "role": "server", "snapshot": {
            "endpoint": ["10.0.0.2", 31330],
            "experts": {"e.1": 4, "e.3": 2}}},
        {"peer_id": "trn-a", "role": "trainer", "snapshot": {
            "dispatch": {"placement": {
                "coact": {"e.0|e.1": 50, "e.2|e.3": 40},
                "coact_dispatches": 90,
                "links": {NODE_A: {"rtt_s": 0.002, "bw_bps": 2e8}},
                "bytes_per_dispatch": 1.5e6}}}},
        {"peer_id": "dead", "role": "server", "snapshot": None},
    ]
    dht_links = {NODE_A: {NODE_B: {"rtt_s": 0.04, "bw_bps": 5e7}}}
    snap = reb.build_snapshot(rows, dht_links)
    assert snap["experts"] == {
        "e.0": NODE_A, "e.2": NODE_A, "e.1": NODE_B, "e.3": NODE_B,
    }
    assert snap["activations"]["e.0"] == 5.0
    assert snap["coact"] == {"e.0|e.1": 50.0, "e.2|e.3": 40.0}
    assert snap["sources"] == {"trn-a": 90.0}
    assert snap["links"]["trn-a"][NODE_A]["rtt_s"] == 0.002
    assert snap["links"][NODE_A][NODE_B]["rtt_s"] == 0.04
    assert snap["bytes_per_dispatch"] == 1.5e6
    # the merged snapshot is solvable end to end
    plan = solve(snap, seed=0)
    assert plan["cost_after"] <= plan["cost_before"]


def test_build_snapshot_tolerates_garbage_rows():
    reb = _rebalance()
    snap = reb.build_snapshot(
        [None, {}, {"snapshot": 5}, {"peer_id": "x", "snapshot": {
            "endpoint": ["h"], "experts": {"u": 1},
            "dispatch": {"placement": {"coact": "nope"}}}}],
        dht_links="junk",
    )
    assert snap["experts"] == {} and snap["coact"] == {}


def test_slo_gate_fires_on_p99_and_shed_regression():
    reb = _rebalance()

    class Args:
        slo_p99_factor = 1.5
        slo_shed_margin = 0.05

    base = {"p99_ms": 100.0, "shed_fraction": 0.01}
    ok = {"p99_ms": 120.0, "shed_fraction": 0.02}
    assert reb._slo_degraded(base, ok, Args()) == ""
    assert "p99" in reb._slo_degraded(
        base, {"p99_ms": 200.0, "shed_fraction": 0.01}, Args()
    )
    assert "shed" in reb._slo_degraded(
        base, {"p99_ms": 100.0, "shed_fraction": 0.2}, Args()
    )
    # no baseline p99 yet (cold swarm): the p99 arm never fires
    cold = {"p99_ms": 0.0, "shed_fraction": 0.0}
    assert reb._slo_degraded(
        cold, {"p99_ms": 500.0, "shed_fraction": 0.0}, Args()
    ) == ""


def test_sample_slo_takes_worst_trainer():
    reb = _rebalance()
    rows = [
        {"snapshot": {"metrics": {"collected": {
            "lah_client_dispatch_p99_ms": 80.0,
            "lah_client_samples_total": 100,
            "lah_client_samples_dropped_total": 10}}}},
        {"snapshot": {"metrics": {"collected": {
            "lah_client_dispatch_p99_ms": 120.0,
            "lah_client_samples_total": 100,
            "lah_client_samples_dropped_total": 0}}}},
        {"snapshot": None},
    ]
    slo = reb.sample_slo(rows)
    assert slo["p99_ms"] == 120.0
    assert abs(slo["shed_fraction"] - 0.05) < 1e-12


# ---- --plan CLI: the collect-gate determinism contract ----


def test_plan_cli_byte_identical_across_processes(tmp_path):
    snap_path = tmp_path / "snap.json"
    snap_path.write_text(json.dumps(clustered_snapshot()))
    outs = []
    for _ in range(2):
        r = subprocess.run(
            [sys.executable, _REBALANCE_PATH,
             "--plan", str(snap_path), "--seed", "0"],
            capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        outs.append(r.stdout)
    assert outs[0] == outs[1]
    plan = json.loads(outs[0])
    assert plan["moves"] and plan["cost_after"] < plan["cost_before"]
    assert outs[0].strip() == plan_to_json(
        solve(clustered_snapshot(), seed=0)
    )
