"""Serving gateway (ISSUE 12): continuous batching over the pack-once
swarm dispatch, cross-user expert-set coalescing, admission control, and
the slot/KV lifecycle.

The contracts under test:

- decoder parity: the slot-table KV decoder's greedy tokens match a full
  re-forward argmax chain through ``model.apply`` exactly;
- coalescing is bitwise-invisible: grouping streams with overlapping
  expert sets into one dispatch produces BIT-identical per-stream outputs
  vs one-dispatch-per-stream (selection and combine are row-wise);
- admission: a saturated gateway sheds with a well-formed retry-after
  reply instead of queueing unboundedly;
- churn: streams killed mid-decode free their slot and KV rows — no slot
  or stream-table leak across 100 churned streams;
- lah_top renders gateway telemetry as STREAMS/SLOTS/SHED columns and
  dashes for peers without (or with malformed) gateway sections.
"""

import contextlib
import time

import jax
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.gateway import (
    AdmissionController,
    ExpertCoalescer,
    Gateway,
    GatewayClient,
)
from learning_at_home_tpu.models.swarm_decoder import SwarmKVDecoder
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server.server import background_server

D = 16
VOCAB = 32
SEQ = 16
LAYERS = 2
UIDS = [f"ffn{layer}.{e}" for layer in range(LAYERS) for e in range(2)]


def _cfg(**overrides):
    base = dict(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=4,
        seq_len=SEQ, grid_size=(2,), k_best=2, k_min=2, uid_prefix="ffn",
        timeout_after_k_min=30.0,
        forward_timeout=60.0, backward_timeout=60.0,
        # pin codec + blind gate: the bitwise contracts here must not
        # depend on adaptive wire precision or cost-model bias state
        wire_codec="none", routing_cost_weight=0,
    )
    base.update(overrides)
    return SwarmTransformerConfig(**base)


@pytest.fixture()
def swarm():
    """One in-process server hosting all experts + a swarm model."""
    with contextlib.ExitStack() as stack:
        endpoint, _srv = stack.enter_context(
            background_server(expert_uids=UIDS, hidden_dim=D, seed=0)
        )
        src = StaticExpertSource({u: endpoint for u in UIDS})
        model = SwarmDMoETransformerLM(_cfg(), src)
        params = model.init_params(jax.random.PRNGKey(0))
        yield model, params
    reset_client_rpc()


# ---------------------------------------------------------------------------
# decoder parity
# ---------------------------------------------------------------------------


def test_swarm_decoder_matches_reforward(swarm):
    """Greedy tokens from the KV decoder == re-forward argmax chains."""
    model, params = swarm
    dec = SwarmKVDecoder(model, params, max_slots=3)
    prompts = [[1, 2, 3], [4, 5], [7, 8, 9, 10]]
    outs = dec.generate(prompts, max_new_tokens=4)
    for prompt, toks in zip(prompts, outs):
        seqtoks = list(prompt)
        ref = []
        for _ in range(4):
            logits = model.apply(params, np.asarray([seqtoks], np.int32))
            t = int(np.asarray(logits)[0, -1].argmax())
            ref.append(t)
            seqtoks.append(t)
        assert toks == ref
    # every slot was vacated by generate()
    assert dec.free_slots() == [0, 1, 2]


# ---------------------------------------------------------------------------
# coalescing: bitwise-invisible grouping
# ---------------------------------------------------------------------------


def test_coalesced_dispatch_bitwise_equals_ungrouped(swarm):
    """The hook-level contract: one grouped dispatch over many streams'
    rows returns BIT-identical outputs to per-stream dispatches."""
    model, params = swarm
    moe = model.moes[0]
    gate = params["layers"][0]["gate"]
    x = np.random.RandomState(0).randn(4, D).astype(np.float32)
    streams = ["a", "b", "c", "d"]
    grouped = ExpertCoalescer(coalesce=True)
    ungrouped = ExpertCoalescer(coalesce=False)
    y_g = grouped.dispatch(0, moe, gate, x, streams)
    y_u = ungrouped.dispatch(0, moe, gate, x, streams)
    assert np.array_equal(np.asarray(y_g), np.asarray(y_u))
    # k_best == grid_size here, so every stream shares the expert set:
    # the grouped arm must have fired ONE dispatch for all four streams
    assert grouped.group_dispatches_total == 1
    assert grouped.coalesced_dispatches_total == 3
    assert ungrouped.group_dispatches_total == 4
    assert ungrouped.coalesced_dispatches_total == 0


def test_coalesced_generation_tokens_equal_ungrouped(swarm):
    """End-to-end: two decoders over the same weights, one coalescing
    and one not, emit identical token streams."""
    model, params = swarm
    prompts = [[1, 2, 3], [9, 8], [4, 5, 6, 7]]
    co = ExpertCoalescer(coalesce=True)
    dec_g = SwarmKVDecoder(model, params, max_slots=3,
                           moe_dispatch=co.dispatch)
    dec_u = SwarmKVDecoder(model, params, max_slots=3)
    outs_g = dec_g.generate(prompts, max_new_tokens=5)
    outs_u = dec_u.generate(prompts, max_new_tokens=5)
    assert outs_g == outs_u
    assert co.coalesced_dispatches_total > 0


def test_preview_failure_falls_back_to_singletons(swarm):
    """A preview failure degrades to ungrouped dispatch — coalescing is
    an optimization, never a correctness dependency."""
    model, params = swarm
    moe = model.moes[0]
    gate = params["layers"][0]["gate"]
    x = np.random.RandomState(1).randn(2, D).astype(np.float32)
    co = ExpertCoalescer(coalesce=True)
    orig = moe.preview_expert_sets
    moe.preview_expert_sets = lambda *_a, **_k: (_ for _ in ()).throw(
        RuntimeError("preview down")
    )
    try:
        y = co.dispatch(0, moe, gate, x, ["a", "b"])
    finally:
        moe.preview_expert_sets = orig
    y_ref = ExpertCoalescer(coalesce=False).dispatch(
        0, moe, gate, x, ["a", "b"]
    )
    assert np.array_equal(np.asarray(y), np.asarray(y_ref))
    assert co.preview_failures_total == 1
    assert co.coalesced_dispatches_total == 0


# ---------------------------------------------------------------------------
# gateway end-to-end over RPC
# ---------------------------------------------------------------------------


def test_gateway_rpc_end_to_end(swarm):
    """Submit/poll/cancel/stats over the real wire; tokens match the
    bare decoder's output for the same prompt."""
    model, params = swarm
    ref = SwarmKVDecoder(model, params, max_slots=1).generate(
        [[1, 2, 3]], max_new_tokens=5
    )[0]
    with Gateway(model, params, max_slots=4) as gw:
        client = GatewayClient(gw.endpoint)
        out = client.generate([1, 2, 3], 5)
        assert not out.get("shed") and not out.get("error")
        assert out["tokens"] == ref
        st = client.stats()
        assert st["gateway"]["streams_finished_total"] >= 1
        assert st["gateway"]["slots"] == 4
        m = st["metrics"]["collected"]
        assert m["lah_gateway_streams_total"] >= 1
        assert m["lah_gateway_tokens_total"] >= 5
        # malformed submits are rejected with an error frame, not a
        # hang: the pinned battery (tests/fuzz_corpus, ISSUE 15) covers
        # empty/out-of-vocab/no-decode-room/over-long prompts and bool
        # token ids / token budgets.  Raw frames, since
        # GatewayClient.submit int-coerces its arguments.
        import json
        import os

        from learning_at_home_tpu.utils.connection import RemoteCallError

        path = os.path.join(os.path.dirname(__file__), "fuzz_corpus",
                            "gateway_submit.json")
        with open(path) as fh:
            corpus = json.load(fh)
        assert corpus["format"] == "lah-fuzz-battery-v1"
        scope = {"VOCAB": VOCAB, "SEQ": SEQ}
        for case in corpus["cases"]:
            meta = {
                k: eval(v[1:], dict(scope))
                if isinstance(v, str) and v.startswith("$") else v
                for k, v in case["meta"].items()
            }
            with pytest.raises(RemoteCallError):
                client._rpc("gen_submit", meta)
                raise AssertionError(
                    f"malformed submit accepted: {case['name']}"
                )
        # the gateway survived the whole battery: still serving
        out = client.generate([1, 2, 3], 5)
        assert not out.get("shed") and not out.get("error")
        assert out["tokens"] == ref


def test_saturated_gateway_sheds_not_queues(swarm):
    """Past ``max_pending`` the gateway sheds with a well-formed
    retry-after reply; the pending queue stays bounded throughout."""
    model, params = swarm
    with Gateway(model, params, max_slots=1, max_pending=2) as gw:
        client = GatewayClient(gw.endpoint)
        replies = [client.submit([1, 2], SEQ - 3) for _ in range(12)]
        shed = [r for r in replies if r.get("shed")]
        accepted = [r for r in replies if r.get("accepted")]
        assert shed, "12 submits into 1 slot + 2 pending never shed"
        for r in shed:
            assert r["accepted"] is False
            assert r["retry_after_s"] > 0
            # either signal is a legitimate shed on a 1-slot gateway:
            # pending-bound saturation or KV page pressure
            assert (
                "saturated" in r["message"]
                or "page pressure" in r["message"]
            )
        # bounded: at no point can more than max_pending streams wait
        assert gw.scheduler.pending_count() <= 2
        assert gw.admission.shed_total == len(shed)
        for r in accepted:
            client.cancel(r["sid"])


def test_admission_server_queue_signal():
    """The DHT-advertised expert-server queue depth sheds on its own,
    independent of gateway occupancy (pure, no swarm)."""
    class _StubSched:
        def pending_count(self):
            return 0

        def estimate_retry_after_s(self):
            return 1.5

    ctrl = AdmissionController(
        _StubSched(), max_pending=4, max_server_queue=8.0,
        load_fn=lambda: {"srv": {"q": 99.0}, "junk": "not-a-dict"},
    )
    ok, retry, reason = ctrl.admit()
    assert ok and retry is None and reason is None
    ctrl._refresh_once()
    assert ctrl.server_queue_depth == 99.0
    ok, retry, reason = ctrl.admit()
    assert not ok and retry == 1.5 and "servers saturated" in reason
    # refresh failures are counted and tolerated, never raised
    ctrl._load_fn = lambda: (_ for _ in ()).throw(OSError("dht down"))
    ctrl._refresh_once()
    assert ctrl.load_refresh_failures == 1


# ---------------------------------------------------------------------------
# churn: cancelled streams must free slots and KV rows
# ---------------------------------------------------------------------------


def test_stream_churn_no_slot_leak(swarm):
    """100 streams submitted with long budgets and killed mid-decode:
    every slot and stream-table entry must come back."""
    model, params = swarm
    with Gateway(model, params, max_slots=4, max_pending=400,
                 stream_ttl_s=0.5) as gw:
        client = GatewayClient(gw.endpoint)
        sids = []
        for i in range(100):
            r = client.submit([1 + (i % 8), 2], SEQ - 3)
            assert r.get("accepted"), r
            sids.append(r["sid"])
            if i % 4 == 3:
                # let a few decode steps run so cancels land mid-decode,
                # then kill the whole batch in flight
                time.sleep(0.02)
                for sid in sids[-4:]:
                    client.cancel(sid)
        for sid in sids:
            client.cancel(sid)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            s = gw.scheduler.stats()
            if s["streams_active"] == 0 and s["pending"] == 0:
                break
            time.sleep(0.05)
        s = gw.scheduler.stats()
        assert s["streams_active"] == 0 and s["pending"] == 0, s
        assert s["slots_in_use"] == 0
        assert gw.decoder.free_slots() == [0, 1, 2, 3]
        assert not any(gw.decoder.live)
        assert (
            s["streams_cancelled_total"] + s["streams_finished_total"]
            + s["streams_errored_total"] == 100
        )
        assert s["streams_errored_total"] == 0
        # TTL GC drains the result table too (no unbounded memory for
        # fire-and-forget clients)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with gw.scheduler._lock:
                left = len(gw.scheduler._streams)
            if left == 0:
                break
            time.sleep(0.1)
        assert left == 0, f"{left} stream records never GC'd"


# ---------------------------------------------------------------------------
# lah_top gateway rows
# ---------------------------------------------------------------------------


def test_lah_top_renders_gateway_columns():
    import importlib

    lah_top = importlib.import_module("tools.lah_top")

    def row(peer_id, gateway_section):
        return {
            "peer_id": peer_id, "role": "gateway",
            "endpoint": ("127.0.0.1", 1), "expires_at": 0.0,
            "snapshot": {"gateway": gateway_section, "metrics": {}},
        }

    rows = [
        row("gw-1", {"streams_active": 3, "streams_total": 41,
                     "slots": 8, "slots_in_use": 2, "shed_total": 7,
                     "kv_pages_total": 33, "kv_pages_used": 12,
                     "prefix_hits_total": 5}),
        # dense-layout gateway: slot columns fill, page columns dash
        row("gw-dense", {"streams_active": 1, "streams_total": 2,
                         "slots": 4, "slots_in_use": 1, "shed_total": 0}),
        {"peer_id": "srv-1", "role": "server",
         "endpoint": ("127.0.0.1", 2), "expires_at": 0.0, "snapshot": {}},
    ]
    out = lah_top.render(rows, "swarm", dead=set())
    assert "STREAMS" in out and "SLOTS" in out and "SHED" in out
    assert "PAGES" in out and "PFX-HIT" in out
    assert "3/41" in out and "2/8" in out and "12/33" in out
    gw_line = next(ln for ln in out.splitlines() if ln.startswith("gw-1"))
    assert gw_line.rstrip().endswith("5")  # PFX-HIT is the last column
    assert " 12/33 " in gw_line
    dense_line = next(
        ln for ln in out.splitlines() if ln.startswith("gw-dense")
    )
    assert dense_line.rstrip().endswith("-")  # no page pool to report
    assert " 1/4 " in dense_line
    # peers without a gateway section render dashes
    srv_line = next(ln for ln in out.splitlines() if ln.startswith("srv-1"))
    assert srv_line.rstrip().endswith("-")
    # malformed sections render dashes, never crash
    rows.append(row("gw-weird", {"slots": "eight", "shed_total": 1}))
    rows.append(row("gw-bool", {"slots": True}))
    rows.append(row("gw-badpages", {"slots": 2, "kv_pages_total": "many",
                                    "prefix_hits_total": 3}))
    out = lah_top.render(rows, "swarm", dead=set())
    for peer in ("gw-weird", "gw-bool", "gw-badpages"):
        line = next(ln for ln in out.splitlines() if ln.startswith(peer))
        assert line.rstrip().endswith("-")
