"""Checkpoint/resume tests: sharded train state and per-expert server state."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.models.transformer import (
    DMoETransformerConfig,
    DMoETransformerLM,
)
from learning_at_home_tpu.parallel import batch_sharding, make_mesh
from learning_at_home_tpu.utils.checkpoint import (
    TrainCheckpointer,
    latest_step,
    list_steps,
)


def test_train_checkpointer_roundtrip_sharded(tmp_path):
    mesh = make_mesh({"data": 2, "expert": 4})
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, seq_len=16,
        num_experts=8, k=2, dtype=jnp.float32,
    )
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-3)
    opt_state = model.init_opt_state(opt, params)
    step_fn = model.make_train_step(opt)

    rs = np.random.RandomState(0)
    ids = jax.device_put(jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh))
    tgt = jax.device_put(jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh))
    params, opt_state, loss1, _ = step_fn(params, opt_state, ids, tgt)

    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), keep_last=2)
    ckpt.save(1, params, opt_state)
    assert latest_step(str(tmp_path / "ckpt")) == 1

    # fresh model instance restores onto the SAME shardings
    model2 = DMoETransformerLM(cfg, mesh)
    params2 = model2.init_params(jax.random.PRNGKey(99))  # different values
    opt_state2 = model2.init_opt_state(opt, params2)
    restored = ckpt.restore_latest(params2, opt_state2)
    assert restored is not None
    step, rparams, ropt = restored
    assert step == 1
    # exact value match
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(rparams)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sharding preserved on expert stacks
    assert rparams["layers"]["moe"]["w1"].sharding.spec == params[
        "layers"
    ]["moe"]["w1"].sharding.spec
    # resumed training continues identically
    _, _, loss_resumed, _ = step_fn(rparams, ropt, ids, tgt)
    _, _, loss_orig, _ = step_fn(params, opt_state, ids, tgt)
    np.testing.assert_allclose(float(loss_resumed), float(loss_orig), rtol=1e-5)


def test_train_checkpointer_prunes(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "c"), keep_last=2)
    tree = {"a": jnp.ones(3)}
    for s in (1, 2, 3, 4):
        ckpt.save(s, tree, tree)
    assert list_steps(str(tmp_path / "c")) == [3, 4]


def test_server_checkpoint_resume(tmp_path):
    from learning_at_home_tpu.server.server import background_server

    root = str(tmp_path / "server_ckpt")
    with background_server(num_experts=2, hidden_dim=16, seed=1) as (ep, srv):
        # do one update so state differs from init
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        g = np.ones((4, 16), np.float32)
        srv.experts["expert.0"].backward([x], [g])
        srv.save_checkpoint(root, step=7)
        want = {
            uid: b.state_dict()["params"] for uid, b in srv.experts.items()
        }

    # a NEW server (fresh params) restores the snapshot
    with background_server(num_experts=2, hidden_dim=16, seed=999) as (ep, srv2):
        restored_step = srv2.load_checkpoint(root)
        assert restored_step == 7
        for uid, backend in srv2.experts.items():
            got = backend.state_dict()["params"]
            for a, b in zip(
                jax.tree_util.tree_leaves(want[uid]),
                jax.tree_util.tree_leaves(got),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert srv2.experts["expert.0"].update_count == 1
